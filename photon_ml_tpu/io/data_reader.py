"""Data readers: Avro training data -> GameDataset, plus LibSVM text.

Reference parity: photon-client data/avro/AvroDataReader.scala (reads Avro
GenericRecords, merges feature bags into per-shard vectors via index maps,
:165-200), data/DataReader.scala (readMerged overloads), GameConverters
(row -> GameDatum keyed by unique sample id), and
dev-scripts/libsvm_text_to_trainingexample_avro.py (LibSVM ingestion).

TPU-native: the reader produces a column-oriented GameDataset — dense
[n, d_shard] blocks per feature shard (sparse inputs are scattered into
dense rows; shards are domain-limited so d_shard stays MXU-friendly),
[n] label/offset/weight vectors, and host-side id columns for random-effect
grouping and per-query evaluation.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import logging
import os
from typing import Iterable, Iterator, Mapping, Sequence

import numpy as np

logger = logging.getLogger(__name__)

from photon_ml_tpu.data.game_data import GameDataset, build_game_dataset
from photon_ml_tpu.data.sparse_batch import SparseShard
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io.index_map import (
    INTERCEPT_KEY,
    IndexMap,
    feature_key,
)

#: Standard column names (reference data/InputColumnsNames.scala).
UID = "uid"
RESPONSE = "response"
OFFSET = "offset"
WEIGHT = "weight"
META_DATA_MAP = "metadataMap"
RESERVED_COLUMNS = frozenset({UID, RESPONSE, "label", OFFSET, WEIGHT, META_DATA_MAP, "foldId"})


@dataclasses.dataclass(frozen=True)
class FeatureShardConfiguration:
    """Reference photon-client io/FeatureShardConfiguration.scala: which
    feature bags merge into this shard and whether to append an intercept.

    sparse=True keeps the shard as COO triples end to end (SparseShard) —
    for giant feature spaces where a dense [n, d] block cannot exist
    (reference AvroDataReader keeps name-term bags sparse for the same
    reason; README.md:77 "hundreds of billions of coefficients"). Only
    fixed-effect coordinates can train on a sparse shard."""

    feature_bags: tuple[str, ...]
    has_intercept: bool = True
    sparse: bool = False
    #: PRE-INDEXED feature space (LibSVM integer columns / hashing-trick):
    #: column j IS feature index j — no name-term map is materialized
    #: (io.index_map.IdentityIndexMap), so ``dimension`` may be 10⁹⁺
    #: (README.md:77 scale through the product path). LibSVM format only.
    pre_indexed: bool = False
    dimension: int | None = None
    #: storage dtype of the assembled dense block: "float32" (default) or
    #: "bfloat16". bf16 halves the block's HBM footprint and traffic — the
    #: hot loop streams it at ~1.2-1.4x the f32 rate with all accumulation,
    #: coefficients, and aux columns staying f32 (BASELINE.md r4 bf16
    #: study; <5e-6 coefficient delta on the accuracy table). Dense shards
    #: only. No reference analogue (TPU-first capability).
    dtype: str = "float32"
    #: hybrid dense-head / sparse-tail layout for giant-d sparse shards
    #: (data/sparse_batch.HybridPolicy): the nnz-hottest columns train on
    #: a dense MXU block, the cold residual on the ELL tail — the index-op
    #:  removal win on power-law name-term bags (BASELINE.md r6). Sparse
    #: shards only; strictly opt-in (off is bitwise-identical).
    hybrid: bool = False
    #: explicit hot-head column budget (``hybrid.hot.cols``); None lets
    #: ``hybrid_coverage`` drive the split
    hybrid_hot_cols: int | None = None
    #: target fraction of nonzeros the head should cover
    #: (``hybrid.coverage``); None with no explicit budget uses the
    #: builder default
    hybrid_coverage: float | None = None

    def __post_init__(self):
        if self.dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"feature shard dtype must be 'float32' or 'bfloat16', "
                f"got {self.dtype!r}"
            )
        if self.dtype == "bfloat16" and self.sparse:
            raise ValueError(
                "dtype=bfloat16 applies to dense feature blocks; sparse "
                "(COO/ELL) shards keep f32 values — their hot loop is "
                "index-bound, not bandwidth-bound (BASELINE.md sparse "
                "floor study)"
            )
        if self.hybrid and not self.sparse:
            raise ValueError(
                "hybrid=true is the dense-head/sparse-tail layout of "
                "SPARSE shards (sparse=true); dense blocks are already "
                "one MXU matmul"
            )
        if not self.hybrid and (
            self.hybrid_hot_cols is not None
            or self.hybrid_coverage is not None
        ):
            raise ValueError(
                "hybrid.hot.cols / hybrid.coverage require hybrid=true"
            )
        # range checks delegate to HybridPolicy so the CLI and programmatic
        # paths agree on the contract
        self.hybrid_policy()

    def hybrid_policy(self, label: str = "sparse"):
        """The shard's HybridPolicy (None when hybrid is off); ``label``
        namespaces the layout telemetry gauges (``layout/<label>/*``)."""
        if not self.hybrid:
            return None
        from photon_ml_tpu.data.sparse_batch import HybridPolicy

        return HybridPolicy(
            hot_cols=self.hybrid_hot_cols,
            coverage=self.hybrid_coverage,
            label=label,
        )


def read_avro_records(
    path: str | os.PathLike, *, on_corrupt: str = "raise"
) -> Iterator[dict]:
    """Iterate training records from an Avro file or directory of part files."""
    return avro_io.read_directory(path, on_corrupt=on_corrupt)


def read_libsvm(path: str | os.PathLike, *, zero_based: bool = False) -> Iterator[dict]:
    """Read LibSVM text (e.g. a1a) into TrainingExampleAvro-shaped dicts:
    feature name = str(index), term = "" — the same mapping the reference's
    dev script applies (dev-scripts/libsvm_text_to_trainingexample_avro.py
    flow, behavior re-derived not copied)."""
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            raw_label = float(parts[0])
            # ±1 is the LibSVM binary-classification convention (a1a); map it
            # to {0,1}. Any other value is a regression target — keep it.
            label = (1.0 if raw_label > 0 else 0.0) if raw_label in (-1.0, 1.0) else raw_label
            features = []
            for tok in parts[1:]:
                if tok.startswith("#"):
                    break  # trailing comment
                idx_s, _, val_s = tok.partition(":")
                idx = int(idx_s) - (0 if zero_based else 1)
                if idx < 0:
                    # match the CSR parsers: a 0 index in a 1-based file is
                    # an error, not a phantom feature named "-1"
                    raise ValueError(
                        f"feature index out of range at line {i + 1}: {tok!r}"
                    )
                features.append({"name": str(idx), "term": "", "value": float(val_s)})
            yield {
                "uid": str(i),
                "label": label,
                "features": features,
                "weight": 1.0,
                "offset": 0.0,
                "metadataMap": None,
            }


def _record_bags(record: dict) -> dict[str, list[dict]]:
    """Feature bags = record fields holding arrays of feature dicts
    (reference AvroDataReader reads every array-of-FeatureAvro field)."""
    bags = {}
    for key, value in record.items():
        if (
            isinstance(value, list)
            and value
            and isinstance(value[0], dict)
            and "name" in value[0]
            and "value" in value[0]
        ):
            bags[key] = value
        elif isinstance(value, list) and not value and key not in RESERVED_COLUMNS:
            bags[key] = []
    return bags


def build_index_maps(
    records: Iterable[dict],
    shard_configs: Mapping[str, FeatureShardConfiguration],
) -> dict[str, IndexMap]:
    """One pass over the data to collect distinct feature keys per shard
    (reference FeatureIndexingDriver / DefaultIndexMapLoader path)."""
    keys: dict[str, set[str]] = {shard: set() for shard in shard_configs}
    for record in records:
        bags = _record_bags(record)
        for shard, cfg in shard_configs.items():
            for bag in cfg.feature_bags:
                for feat in bags.get(bag, ()):
                    keys[shard].add(feature_key(feat["name"], feat.get("term") or ""))
    return {
        shard: IndexMap.from_keys(keys[shard], add_intercept=cfg.has_intercept)
        for shard, cfg in shard_configs.items()
    }


@dataclasses.dataclass
class ReadResult:
    dataset: GameDataset
    index_maps: dict[str, IndexMap]
    intercept_indices: dict[str, int]


def _scatter_dense(
    n: int, d: int, row_idx: np.ndarray, col_idx: np.ndarray, vals: np.ndarray, dtype
) -> np.ndarray:
    """[n, d] dense block from COO triples; duplicate (row, col) accumulate
    (the one shared accumulation rule for every reader path)."""
    x = np.zeros((n, d), dtype=dtype)
    if len(col_idx):
        np.add.at(
            x, (row_idx.astype(np.intp), col_idx.astype(np.intp)), vals.astype(dtype)
        )
    return x


def _assemble_sparse_shard(
    n: int,
    imap: IndexMap,
    cfg: FeatureShardConfiguration,
    triples: np.ndarray,
    dtype,
    shard: str,
    intercept_indices: dict[str, int],
) -> SparseShard:
    """COO shard assembly: never densifies. The intercept column becomes n
    explicit (i, intercept, 1.0) entries; duplicate (row, col) pairs
    accumulate on device via the segment sums (same rule as
    _scatter_dense's np.add.at)."""
    row_idx = triples[:, 0].astype(np.int64)
    col_idx = triples[:, 1].astype(np.int64)
    vals = triples[:, 2].astype(dtype)
    if cfg.has_intercept:
        ii = imap.get_index(INTERCEPT_KEY)
        if ii >= 0:
            row_idx = np.concatenate([row_idx, np.arange(n, dtype=np.int64)])
            col_idx = np.concatenate([col_idx, np.full(n, ii, dtype=np.int64)])
            vals = np.concatenate([vals, np.ones(n, dtype=dtype)])
            intercept_indices[shard] = ii
    return SparseShard(
        rows=row_idx, cols=col_idx, vals=vals,
        num_samples=n, feature_dim=imap.size,
        hybrid_policy=cfg.hybrid_policy(label=shard),
    )


def _apply_intercept(
    x: np.ndarray, imap: IndexMap, shard: str, intercept_indices: dict[str, int]
) -> None:
    """Set the intercept column to 1 and record its index, if the map has one."""
    ii = imap.get_index(INTERCEPT_KEY)
    if ii >= 0:
        x[:, ii] = 1.0
        intercept_indices[shard] = ii


def records_to_game_dataset(
    records: Iterable[dict],
    shard_configs: Mapping[str, FeatureShardConfiguration],
    index_maps: Mapping[str, IndexMap],
    *,
    random_effect_id_columns: Sequence[str] = (),
    evaluation_id_columns: Sequence[str] = (),
    entity_vocabs: Mapping[str, np.ndarray] | None = None,
    dtype=np.float32,
) -> ReadResult:
    """Assemble a GameDataset from record dicts.

    Id columns (random-effect types, per-query eval ids) are taken from the
    record's metadataMap first, then from top-level record fields — the
    reference's idTagToValueMap extraction (GameConverters.scala).
    """
    labels: list[float] = []
    offsets: list[float] = []
    weights: list[float] = []
    uids: list[int] = []
    rows: dict[str, list[tuple[int, int, float]]] = {s: [] for s in shard_configs}
    id_cols: dict[str, list[str]] = {
        c: [] for c in set(random_effect_id_columns) | set(evaluation_id_columns)
    }

    n = 0
    for record in records:
        label = record.get("label", record.get(RESPONSE))
        if label is None:
            raise ValueError("record has neither 'label' nor 'response'")
        labels.append(float(label))
        offset = record.get(OFFSET)
        offsets.append(0.0 if offset is None else float(offset))
        weight = record.get(WEIGHT)
        weights.append(1.0 if weight is None else float(weight))
        uid = record.get(UID)
        try:
            uids.append(int(uid) if uid is not None else n)
        except ValueError:
            # Non-numeric uid: hash the string into a disjoint id space so a
            # fallback can't collide with a genuine numeric uid of another row
            # (stable ids feed reservoir/down-sampling hashes).
            digest = hashlib.blake2b(str(uid).encode(), digest_size=8).digest()
            # mask to 62 bits then tag bit 62: range [2^62, 2^63) is disjoint
            # from any non-negative numeric uid below 2^62
            hashed = int.from_bytes(digest, "little") & ((1 << 62) - 1)
            uids.append(hashed | (1 << 62))

        meta = record.get(META_DATA_MAP) or {}
        for col in id_cols:
            value = meta.get(col, record.get(col))
            id_cols[col].append("" if value is None else str(value))

        bags = _record_bags(record)
        for shard, cfg in shard_configs.items():
            imap = index_maps[shard]
            for bag in cfg.feature_bags:
                for feat in bags.get(bag, ()):
                    j = imap.get_index(feature_key(feat["name"], feat.get("term") or ""))
                    if j >= 0:
                        rows[shard].append((n, j, float(feat["value"])))
        n += 1

    feature_shards: dict[str, object] = {}
    intercept_indices: dict[str, int] = {}
    for shard, cfg in shard_configs.items():
        imap = index_maps[shard]
        triples = (
            np.asarray(rows[shard], dtype=np.float64)
            if rows[shard]
            else np.zeros((0, 3))
        )
        if cfg.sparse:
            feature_shards[shard] = _assemble_sparse_shard(
                n, imap, cfg, triples, dtype, shard, intercept_indices
            )
            continue
        x = _scatter_dense(
            n, imap.size, triples[:, 0], triples[:, 1], triples[:, 2], dtype
        )
        if cfg.has_intercept:
            _apply_intercept(x, imap, shard, intercept_indices)
        feature_shards[shard] = x

    entity_keys = {
        c: np.asarray(id_cols[c]) for c in random_effect_id_columns
    }
    eval_ids = {c: np.asarray(id_cols[c]) for c in evaluation_id_columns}

    dataset = build_game_dataset(
        labels=np.asarray(labels),
        feature_shards=feature_shards,
        entity_keys=entity_keys,
        offsets=np.asarray(offsets),
        weights=np.asarray(weights),
        unique_ids=np.asarray(uids, dtype=np.int64),
        ids=eval_ids,
        entity_vocabs=entity_vocabs,
        dtype=dtype,
        shard_dtypes=shard_np_dtypes(shard_configs),
    )
    return ReadResult(
        dataset=dataset,
        index_maps=dict(index_maps),
        intercept_indices=intercept_indices,
    )


def read_merged(
    path: str | os.PathLike | Sequence[str | os.PathLike],
    shard_configs: Mapping[str, FeatureShardConfiguration],
    *,
    index_maps: Mapping[str, IndexMap] | None = None,
    random_effect_id_columns: Sequence[str] = (),
    evaluation_id_columns: Sequence[str] = (),
    entity_vocabs: Mapping[str, np.ndarray] | None = None,
    fmt: str = "avro",
    dtype=np.float32,
    on_corrupt: str = "raise",
) -> ReadResult:
    """One-call read: build index maps if needed, then the dataset
    (reference DataReader.readMerged). ``path`` may be a list of paths —
    e.g. the daily directories of a date range
    (util/date_range.resolve_input_paths) — read in order as one dataset.

    on_corrupt: "raise" (default — strict, byte-identical to before) or
    "quarantine" (Avro only): corrupt container blocks are skipped and
    counted (io/avro.py per-block validation) instead of failing the read.
    The native columnar path first framing-validates each file cheaply
    (avro.validate_container); a file with corrupt blocks reads through
    the Python quarantine reader so skip semantics stay authoritative.
    """
    paths = (
        [path]
        if isinstance(path, (str, os.PathLike))
        else [p for p in path]
    )
    if not paths:
        raise ValueError("read_merged needs at least one input path")
    if on_corrupt not in ("raise", "quarantine"):
        raise ValueError(
            f"on_corrupt must be 'raise' or 'quarantine', got {on_corrupt!r}"
        )
    if on_corrupt == "quarantine" and fmt != "avro":
        raise ValueError(
            f"on_corrupt={on_corrupt!r} supports fmt='avro' only (LibSVM "
            "text has no block framing to quarantine)"
        )

    pre_idx = [s for s, c in shard_configs.items() if c.pre_indexed]
    if pre_idx and fmt != "libsvm":
        raise ValueError(
            f"pre-indexed shards {pre_idx} require the libsvm input format "
            "(avro features are name-term keyed; index them with feature "
            "maps instead)"
        )
    result = None
    if fmt == "libsvm":
        # CSR fast path: native C++ tokenizer (photon_ml_tpu/native/
        # libsvm_loader.cpp) + vectorized dense assembly, no per-record dicts
        result = _read_merged_libsvm(
            paths,
            shard_configs,
            index_maps=index_maps,
            random_effect_id_columns=random_effect_id_columns,
            evaluation_id_columns=evaluation_id_columns,
            entity_vocabs=entity_vocabs,
            dtype=dtype,
        )
    elif fmt == "avro" and os.environ.get("PHOTON_NO_NATIVE_AVRO") != "1":
        # columnar C++ decode (native/avro_decoder.cpp): ~2 orders of
        # magnitude over the per-record Python path; falls back below on
        # unsupported schema shapes or a missing compiler. Equivalence of
        # the two paths is pinned by tests/test_avro_native.py.
        try:
            result = _read_merged_avro_native(
                paths, shard_configs,
                index_maps=index_maps,
                random_effect_id_columns=random_effect_id_columns,
                evaluation_id_columns=evaluation_id_columns,
                entity_vocabs=entity_vocabs,
                dtype=dtype,
                on_corrupt=on_corrupt,
            )
        except _AvroNativeFallback as e:
            logger.info("native avro path unavailable (%s); using the "
                        "Python reader", e)

    if result is None:
        def records():
            if fmt == "avro":
                return itertools.chain.from_iterable(
                    read_avro_records(p, on_corrupt=on_corrupt)
                    for p in paths
                )
            raise ValueError(f"unknown format {fmt!r}")

        if index_maps is None:
            # Decode once: index-map construction and dataset assembly both
            # scan every record, and assembly materializes the data anyway.
            materialized = list(records())
            index_maps = build_index_maps(materialized, shard_configs)
            record_source = materialized
        else:
            record_source = records()
        result = records_to_game_dataset(
            record_source,
            shard_configs,
            index_maps,
            random_effect_id_columns=random_effect_id_columns,
            evaluation_id_columns=evaluation_id_columns,
            entity_vocabs=entity_vocabs,
            dtype=dtype,
        )
    return result


def shard_np_dtypes(
    shard_configs: Mapping[str, FeatureShardConfiguration],
) -> dict[str, object] | None:
    """Per-shard numpy storage dtypes from the shard configs, for
    ``build_game_dataset(shard_dtypes=...)``.

    Assembly (duplicate accumulation, intercept append) runs in the
    reader's f32; the finished block is rounded to bf16 ONCE on host and
    transferred once — the same arithmetic as casting the operand in the
    kernel, so the BASELINE.md bf16 accuracy table applies. Both the
    device-facing array and the host cache (bucket builders, normalization
    summaries) see the cast block.
    """
    import ml_dtypes

    out = {
        s: ml_dtypes.bfloat16
        for s, c in shard_configs.items()
        if c.dtype == "bfloat16"
    }
    return out or None


class _AvroNativeFallback(Exception):
    """Internal: native avro path not usable for this input — use Python."""


def _read_merged_avro_native(
    paths: Sequence[str | os.PathLike],
    shard_configs: Mapping[str, FeatureShardConfiguration],
    *,
    index_maps: Mapping[str, IndexMap] | None,
    random_effect_id_columns: Sequence[str],
    evaluation_id_columns: Sequence[str],
    entity_vocabs: Mapping[str, np.ndarray] | None,
    dtype,
    on_corrupt: str = "raise",
) -> ReadResult:
    """Vectorized Avro read over the native columnar decoder.

    Same semantics as ``records_to_game_dataset`` over the Python decode —
    label/response precedence, offset/weight defaults, uid hashing,
    metadataMap-then-top-level id lookup, per-shard bag merging with the
    one shared duplicate-accumulation rule. Equivalence is pinned by
    tests/test_avro_native.py. Raises :class:`_AvroNativeFallback` whenever
    any input is outside the native subset.

    Under ``on_corrupt="quarantine"`` every file is framing-validated
    first (length bounds + sync markers — header decode plus one seek and
    a 16-byte read per block, no payload reads); a file with ANY corrupt
    block falls back to the Python quarantine reader, which owns the
    authoritative skip-and-count semantics. Clean files keep the ~13x
    native decode.
    """
    from photon_ml_tpu.io import avro_native as av

    try:
        if not av.avro_native_available():
            raise _AvroNativeFallback("no C++ compiler / build failed")
        files: list[str] = []
        for p in paths:
            files += avro_io.list_avro_files(p)
        if on_corrupt == "quarantine":
            for f in files:
                problems = avro_io.validate_container(f)
                if problems:
                    raise _AvroNativeFallback(
                        f"{f}: {len(problems)} corrupt block span(s); "
                        "quarantining via the Python reader"
                    )
        parts = []
        plan0: "av.AvroPlan | None" = None
        for f in files:
            plan = av.compile_plan(avro_io.read_container_schema(f))
            if plan0 is None:
                plan0 = plan
            elif not plan.same_semantics(plan0):
                # schema evolution between part files: the faithfulness
                # guards are per-plan, so a later part could bypass them
                raise av.AvroNativeUnsupported(
                    f"part file {f} has a different schema"
                )
            parts.append(av.decode_columns(f, plan))
        cols = av.concat_columns(parts)
    except av.AvroNativeUnsupported as e:
        raise _AvroNativeFallback(str(e)) from e
    except avro_io.AvroError as e:
        # includes runtime-unrenderable values (e.g. a double metadataMap
        # entry) — the Python reader is authoritative for both the data and
        # any error message
        raise _AvroNativeFallback(str(e)) from e
    except RuntimeError as e:  # compiler missing etc.
        raise _AvroNativeFallback(str(e)) from e
    n = cols.n

    # requested bags that exist in the schema but were not bag-shaped have
    # uncertain record-level semantics — let the Python path decide
    for cfg in shard_configs.values():
        for bag in cfg.feature_bags:
            if bag in plan0.all_fields and bag not in cols.bags:
                raise _AvroNativeFallback(
                    f"field '{bag}' is not a feature-bag shape"
                )

    def numcol(name, default):
        if name in plan0.all_fields and name not in cols.num:
            # e.g. a string-typed offset: Python parses/raises; a silent
            # default would diverge
            raise _AvroNativeFallback(
                f"field '{name}' has a non-numeric schema shape"
            )
        col = cols.num.get(name)
        if col is None:
            return np.full(n, default, dtype=np.float64)
        null = cols.num_null[name]
        if name in plan0.strnum_fields and np.isnan(col[~null]).any():
            # a non-null NaN under a string union is an unparseable string
            # — Python raises there; let it
            raise _AvroNativeFallback(
                f"field '{name}' has unparseable string values"
            )
        # nulls take the default (Python's `if value is None`); genuine NaN
        # doubles propagate, exactly like float(nan)
        return np.where(null, default, col)

    # Python precedence: label first (whatever its type), then response —
    # a label field the native path could not collect numerically must not
    # silently yield to response
    if "label" in plan0.all_fields and "label" not in cols.num:
        raise _AvroNativeFallback("label field has an uncollectable shape")
    if "label" in cols.num:
        labels = cols.num["label"]
        if cols.num_null["label"].any():
            raise _AvroNativeFallback("null label values")
    elif RESPONSE in cols.num:
        labels = cols.num[RESPONSE]
    elif RESPONSE in plan0.all_fields:
        raise _AvroNativeFallback("response field has an uncollectable shape")
    else:
        raise ValueError("record has neither 'label' nor 'response'")
    if np.isnan(labels).any():
        # null labels error identically on the Python path; non-numeric
        # string labels are its call too
        raise _AvroNativeFallback("null or non-numeric label values")
    offsets = numcol(OFFSET, 0.0)
    weights = numcol(WEIGHT, 1.0)

    # uid -> stable int64 ids (same rules as records_to_game_dataset)
    if UID in cols.num:
        uid_col = cols.num[UID]
        uids = np.where(
            np.isnan(uid_col), np.arange(n, dtype=np.float64), uid_col
        ).astype(np.int64)
    elif UID in cols.str_ids:
        table = cols.str_tables[UID]
        mapped = np.empty(len(table), dtype=np.int64)
        for i, s in enumerate(table):
            try:
                mapped[i] = int(s)
            except ValueError:
                digest = hashlib.blake2b(s.encode(), digest_size=8).digest()
                hashed = int.from_bytes(digest, "little") & ((1 << 62) - 1)
                mapped[i] = hashed | (1 << 62)
        ids = cols.str_ids[UID]
        uids = np.where(
            ids == av.NULL_ID,
            np.arange(n, dtype=np.int64),
            mapped[np.minimum(ids.astype(np.int64), len(table) - 1)]
            if table else 0,
        )
    else:
        uids = np.arange(n, dtype=np.int64)

    # id columns: metadataMap first (key PRESENT wins even with null value),
    # then a top-level field, else ""
    id_cols: dict[str, np.ndarray] = {}
    meta = cols.maps.get(META_DATA_MAP)
    mkeys = cols.map_key_tables.get(META_DATA_MAP, [])
    mvals = np.asarray(
        cols.map_val_tables.get(META_DATA_MAP, []) + [""], dtype=object
    )
    wanted = set(random_effect_id_columns) | set(evaluation_id_columns)
    if wanted and META_DATA_MAP in plan0.all_fields and meta is None:
        raise _AvroNativeFallback(
            "metadataMap has an uncollectable shape but id columns are "
            "requested"
        )
    for col in wanted:
        out = np.full(n, "", dtype=object)
        seen = np.zeros(n, dtype=bool)
        if meta is not None and col in mkeys:
            kid = mkeys.index(col)
            rows, kids, vids = meta
            sel = kids == kid
            rsel = rows[sel].astype(np.int64)
            v = vids[sel].astype(np.int64)
            v = np.where(v == np.int64(av.NULL_ID), len(mvals) - 1, v)
            out[rsel] = mvals[v]
            seen[rsel] = True
        if (
            col not in cols.str_ids and col not in cols.num
            and col in plan0.all_fields and not seen.all()
        ):
            # e.g. an enum-typed id column: Python renders str(value);
            # silently collapsing every entity into "" would be far worse
            raise _AvroNativeFallback(
                f"id column '{col}' has an uncollectable schema shape"
            )
        if col in cols.str_ids:
            table = np.asarray(cols.str_tables[col] + [""], dtype=object)
            ids = cols.str_ids[col].astype(np.int64)
            ids = np.where(ids == np.int64(av.NULL_ID), len(table) - 1, ids)
            fill = ~seen
            out[fill] = table[ids[fill]]
        elif col in cols.num:
            vals = cols.num[col]
            fill = ~seen & ~np.isnan(vals)
            if fill.any() and col in plan0.unfaithful_id_fields:
                # float/bool-typed id columns can't reproduce Python's
                # str() rendering from an f64 column
                raise _AvroNativeFallback(
                    f"id column '{col}' has a float/bool-typed schema"
                )
            # pure int columns render like Python ints (vectorized)
            out[fill] = vals[fill].astype(np.int64).astype(str)
        id_cols[col] = out.astype(str)

    # feature bags -> per-shard triples through the index maps
    if index_maps is None:
        built: dict[str, IndexMap] = {}
        for shard, cfg in shard_configs.items():
            keys: set[str] = set()
            for bag in cfg.feature_bags:
                keys.update(cols.bag_tables.get(bag, []))
            built[shard] = IndexMap.from_keys(
                keys, add_intercept=cfg.has_intercept
            )
        index_maps = built

    feature_shards: dict[str, object] = {}
    intercept_indices: dict[str, int] = {}
    for shard, cfg in shard_configs.items():
        imap = index_maps[shard]
        rows_l, cols_l, vals_l = [], [], []
        for bag in cfg.feature_bags:
            if bag not in cols.bags:
                continue
            br, bk, bv = cols.bags[bag]
            table = cols.bag_tables[bag]
            idx = np.asarray(
                [imap.get_index(k) for k in table], dtype=np.int64
            )
            j = idx[bk.astype(np.int64)] if len(table) else np.zeros(0, np.int64)
            keep = j >= 0
            rows_l.append(br.astype(np.int64)[keep])
            cols_l.append(j[keep])
            vals_l.append(bv[keep])
        if rows_l:
            triples = np.stack(
                [
                    np.concatenate(rows_l).astype(np.float64),
                    np.concatenate(cols_l).astype(np.float64),
                    np.concatenate(vals_l),
                ],
                axis=1,
            )
        else:
            triples = np.zeros((0, 3))
        if cfg.sparse:
            feature_shards[shard] = _assemble_sparse_shard(
                n, imap, cfg, triples, dtype, shard, intercept_indices
            )
            continue
        x = _scatter_dense(
            n, imap.size, triples[:, 0], triples[:, 1], triples[:, 2], dtype
        )
        if cfg.has_intercept:
            _apply_intercept(x, imap, shard, intercept_indices)
        feature_shards[shard] = x

    dataset = build_game_dataset(
        labels=labels,
        feature_shards=feature_shards,
        entity_keys={
            c: id_cols[c] for c in random_effect_id_columns
        },
        offsets=offsets,
        weights=weights,
        unique_ids=uids,
        ids={c: id_cols[c] for c in evaluation_id_columns},
        entity_vocabs=entity_vocabs,
        dtype=dtype,
        shard_dtypes=shard_np_dtypes(shard_configs),
    )
    return ReadResult(
        dataset=dataset,
        index_maps=dict(index_maps),
        intercept_indices=intercept_indices,
    )


def _read_merged_libsvm(
    paths: Sequence[str | os.PathLike],
    shard_configs: Mapping[str, FeatureShardConfiguration],
    *,
    index_maps: Mapping[str, IndexMap] | None,
    random_effect_id_columns: Sequence[str],
    evaluation_id_columns: Sequence[str],
    entity_vocabs: Mapping[str, np.ndarray] | None,
    dtype,
) -> ReadResult:
    """Vectorized LibSVM read (same semantics as the record-dict path:
    feature name = str(0-based index), term = "", one bag called
    "features"; LibSVM carries no id/metadata columns)."""
    from photon_ml_tpu.io.libsvm_native import concat_libsvm, parse_libsvm

    def expand(p):
        # directories expand to their (sorted) regular files, matching the
        # avro path's part-file convention
        if os.path.isdir(p):
            return [
                os.path.join(p, f) for f in sorted(os.listdir(p))
                if not f.startswith(("_", "."))
                and os.path.isfile(os.path.join(p, f))
            ]
        return [p]

    files = [f for p in paths for f in expand(p)]
    if not files:
        raise ValueError(f"no LibSVM files found under {list(paths)}")
    data = concat_libsvm([parse_libsvm(p) for p in files])
    n = data.num_rows
    distinct = np.unique(data.cols) if data.nnz else np.asarray([], dtype=np.uint32)

    if index_maps is None:
        from photon_ml_tpu.io.index_map import IdentityIndexMap

        index_maps = {}
        for shard, cfg in shard_configs.items():
            if cfg.pre_indexed:
                if cfg.dimension is None:
                    raise ValueError(
                        f"pre-indexed shard '{shard}' needs a dimension"
                    )
                if cfg.dimension > np.iinfo(np.int32).max:
                    import jax as _jax

                    if not _jax.config.jax_enable_x64:
                        # without x64, device int arrays silently downcast
                        # to int32 and column ids >= 2^31 would wrap
                        raise ValueError(
                            f"pre-indexed shard '{shard}': dimension "
                            f"{cfg.dimension} exceeds int32; enable "
                            "jax_enable_x64 for >2^31-column spaces"
                        )
                if cfg.has_intercept:
                    raise ValueError(
                        f"pre-indexed shard '{shard}': intercept=false "
                        "required (an appended intercept would change the "
                        "declared dimension; include it in the data)"
                    )
                index_maps[shard] = IdentityIndexMap(cfg.dimension)
            else:
                index_maps[shard] = IndexMap.from_keys(
                    {feature_key(str(int(j)), "") for j in distinct}
                    if "features" in cfg.feature_bags
                    else set(),
                    add_intercept=cfg.has_intercept,
                )

    row_idx = np.repeat(
        np.arange(n, dtype=np.intp), np.diff(data.row_offsets).astype(np.intp)
    )
    feature_shards: dict[str, np.ndarray] = {}
    intercept_indices: dict[str, int] = {}
    for shard, cfg in shard_configs.items():
        imap = index_maps[shard]
        if cfg.pre_indexed and "features" in cfg.feature_bags:
            # columns used AS-IS against the declared dimension; sparse
            # keeps the COO triples (the only layout that exists at 10⁹)
            dim = int(imap.size)
            oob = int((data.cols >= dim).sum()) if data.nnz else 0
            if oob:
                raise ValueError(
                    f"pre-indexed shard '{shard}': {oob} entries have "
                    f"column >= dimension {dim}"
                )
            if cfg.sparse:
                feature_shards[shard] = SparseShard(
                    rows=row_idx.astype(np.int64),
                    cols=data.cols.astype(np.int64),
                    vals=data.vals.astype(dtype),
                    num_samples=n, feature_dim=dim,
                    hybrid_policy=cfg.hybrid_policy(label=shard),
                )
            else:
                feature_shards[shard] = _scatter_dense(
                    n, dim, row_idx, data.cols.astype(np.int64),
                    data.vals, dtype,
                )
            continue
        if "features" in cfg.feature_bags and data.nnz:
            # CSR col j -> shard column via the index map; searchsorted over
            # the distinct indices keeps memory O(distinct), independent of
            # the largest feature index (hashing-trick data)
            mapped_distinct = np.asarray(
                [imap.get_index(feature_key(str(int(j)), "")) for j in distinct],
                dtype=np.int64,
            )
            mapped = mapped_distinct[np.searchsorted(distinct, data.cols)]
            keep = mapped >= 0
            x = _scatter_dense(
                n, imap.size, row_idx[keep], mapped[keep], data.vals[keep], dtype
            )
        else:
            x = np.zeros((n, imap.size), dtype=dtype)
        if cfg.has_intercept:
            _apply_intercept(x, imap, shard, intercept_indices)
        feature_shards[shard] = x

    empty_ids = np.full(n, "", dtype=object)
    dataset = build_game_dataset(
        labels=data.mapped_labels(),
        feature_shards=feature_shards,
        entity_keys={c: empty_ids for c in random_effect_id_columns},
        offsets=np.zeros(n),
        weights=np.ones(n),
        unique_ids=np.arange(n, dtype=np.int64),
        ids={c: empty_ids for c in evaluation_id_columns},
        entity_vocabs=entity_vocabs,
        dtype=dtype,
        shard_dtypes=shard_np_dtypes(shard_configs),
    )
    return ReadResult(
        dataset=dataset,
        index_maps=dict(index_maps),
        intercept_indices=intercept_indices,
    )
