"""Off-heap (memory-mapped) feature index maps.

Reference parity: photon-api index/PalDBIndexMap.scala:26-56 and
PalDBIndexMapBuilder — the reference keeps huge feature vocabularies out of
JVM heap in partitioned PalDB stores. Here a native C++ mmap hash store
(photon_ml_tpu/native/offheap_store.cpp) serves lookups with zero Python
heap cost per key; partitioning (hash(key) % P, global indices stored
directly) matches the reference's partitioned layout without its offset
arithmetic. A pure-Python mmap reader covers compiler-less environments.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import struct
from typing import Iterator, Mapping

import numpy as np

from photon_ml_tpu.io.index_map import INTERCEPT_KEY, IndexMap

_MAGIC = b"PHOTONIX"
_HEADER = struct.Struct("<8sQQQQ")


def _fnv1a(data: bytes) -> int:
    h = 1469598103934665603
    for b in data:
        h ^= b
        h = (h * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    return h


class _PyStore:
    """Pure-Python reader for the photonix format (fallback)."""

    def __init__(self, path: str):
        self._f = open(path, "rb")
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        magic, version, n, table_size, _blob = _HEADER.unpack_from(self._mm, 0)
        if magic != _MAGIC or version != 1:
            raise ValueError(f"{path} is not a photonix store")
        self.n = n
        self.table_size = table_size
        self._off_base = _HEADER.size
        self._table_base = self._off_base + 8 * (n + 1)
        self._blob_base = self._table_base + 8 * table_size

    def _offset(self, i: int) -> int:
        return struct.unpack_from("<Q", self._mm, self._off_base + 8 * i)[0]

    def _key_bytes(self, idx: int) -> bytes:
        start, end = self._offset(idx), self._offset(idx + 1)
        return self._mm[self._blob_base + start : self._blob_base + end]

    def get(self, key: bytes) -> int:
        mask = self.table_size - 1
        slot = _fnv1a(key) & mask
        while True:
            entry = struct.unpack_from("<Q", self._mm, self._table_base + 8 * slot)[0]
            if entry == 0:
                return -1
            idx = entry - 1
            if self._key_bytes(idx) == key:
                return idx
            slot = (slot + 1) & mask

    def key_at(self, idx: int) -> bytes | None:
        if 0 <= idx < self.n:
            return self._key_bytes(idx)
        return None

    def close(self):
        self._mm.close()
        self._f.close()


class _NativeStore:
    """ctypes wrapper over the C++ store."""

    def __init__(self, path: str):
        from photon_ml_tpu.native import load_offheap_library

        self._lib = load_offheap_library()
        self._handle = self._lib.om_open(path.encode())
        if not self._handle:
            raise ValueError(f"cannot open photonix store at {path}")
        self.n = self._lib.om_size(self._handle)
        self._buf = ctypes.create_string_buffer(4096)

    def get(self, key: bytes) -> int:
        return self._lib.om_get(self._handle, key, len(key))

    def key_at(self, idx: int) -> bytes | None:
        length = self._lib.om_key_at(self._handle, idx, self._buf, len(self._buf))
        if length < 0:
            return None
        if length > len(self._buf):
            self._buf = ctypes.create_string_buffer(length)
            self._lib.om_key_at(self._handle, idx, self._buf, len(self._buf))
        return self._buf.raw[:length]

    def close(self):
        if self._handle:
            self._lib.om_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def build_offheap_store(
    directory: str | os.PathLike,
    index_map: Mapping[str, int],
    *,
    num_partitions: int = 1,
    name: str = "index",
) -> list[str]:
    """Write an IndexMap to ``num_partitions`` photonix store files.

    Partition of a key = hash(key_bytes) % P (reference PalDBIndexMap
    partitioning); each store holds its keys sorted by global index, and the
    global index is recovered as offsets stored per partition.
    """
    if num_partitions < 1:
        raise ValueError(f"num_partitions must be >= 1, got {num_partitions}")
    os.makedirs(directory, exist_ok=True)
    ordered = sorted(index_map.items(), key=lambda kv: kv[1])
    if [i for _, i in ordered] != list(range(len(ordered))):
        raise ValueError("index map must be dense 0..n-1")

    partitions: list[list[tuple[bytes, int]]] = [[] for _ in range(num_partitions)]
    for key, idx in ordered:
        kb = key.encode("utf-8")
        partitions[_fnv1a(kb) % num_partitions].append((kb, idx))

    from photon_ml_tpu.native import load_offheap_library

    lib = load_offheap_library()
    paths = []
    for p, members in enumerate(partitions):
        blob = b"".join(kb for kb, _ in members)
        offsets = [0]
        for kb, _ in members:
            offsets.append(offsets[-1] + len(kb))
        # global index of each local slot, stored as a sidecar array
        globals_arr = [idx for _, idx in members]
        path = os.path.join(str(directory), f"{name}.part-{p:05d}.photonix")
        off_arr = (ctypes.c_uint64 * len(offsets))(*offsets)
        rc = lib.om_build(path.encode(), blob, off_arr, len(members))
        if rc != 0:
            raise RuntimeError(f"om_build failed with code {rc} for {path}")
        with open(path + ".idx", "wb") as f:
            f.write(struct.pack(f"<{len(globals_arr)}Q", *globals_arr))
        paths.append(path)
    with open(os.path.join(str(directory), f"{name}.photonix.json"), "w") as f:
        import json

        json.dump(
            {"num_partitions": num_partitions, "size": len(ordered), "name": name}, f
        )
    return paths


class OffHeapIndexMap(Mapping[str, int]):
    """IndexMap-compatible reader over partitioned photonix stores.

    Drop-in for io.index_map.IndexMap in readers/writers: supports
    get_index / get_feature_name / size / intercept lookups with O(1) mmap
    probes and no per-key Python objects.
    """

    def __init__(
        self,
        directory: str | os.PathLike,
        name: str = "index",
        *,
        force_python: bool = False,
    ):
        import json

        with open(os.path.join(str(directory), f"{name}.photonix.json")) as f:
            meta = json.load(f)
        self._size = meta["size"]
        self._num_partitions = meta["num_partitions"]
        self._stores = []
        #: per-partition numpy uint64 arrays — no per-key Python objects
        self._globals: list["np.ndarray"] = []
        #: lazy argsort-based reverse index (global -> partition/local)
        self._rev_part: "np.ndarray | None" = None
        self._rev_local: "np.ndarray | None" = None
        use_native = not force_python
        if use_native:
            from photon_ml_tpu.native import native_available

            use_native = native_available()
        for p in range(self._num_partitions):
            path = os.path.join(str(directory), f"{name}.part-{p:05d}.photonix")
            store = _NativeStore(path) if use_native else _PyStore(path)
            self._stores.append(store)
            with open(path + ".idx", "rb") as f:
                raw = f.read()
            self._globals.append(np.frombuffer(raw, dtype=np.uint64))

    # Reference API ----------------------------------------------------------
    def get_index(self, key: str) -> int:
        kb = key.encode("utf-8")
        p = _fnv1a(kb) % self._num_partitions
        local = self._stores[p].get(kb)
        return -1 if local < 0 else int(self._globals[p][local])

    def get_feature_name(self, index: int) -> str | None:
        if not 0 <= index < self._size:
            return None
        if self._rev_part is None:
            # dense flat arrays indexed by global id: partition + local slot
            self._rev_part = np.zeros(self._size, dtype=np.int32)
            self._rev_local = np.zeros(self._size, dtype=np.int64)
            for p, globals_arr in enumerate(self._globals):
                g = globals_arr.astype(np.int64)
                self._rev_part[g] = p
                self._rev_local[g] = np.arange(len(g), dtype=np.int64)
        p = int(self._rev_part[index])
        kb = self._stores[p].key_at(int(self._rev_local[index]))
        return None if kb is None else kb.decode("utf-8")

    @property
    def size(self) -> int:
        return self._size

    @property
    def has_intercept(self) -> bool:
        return self.get_index(INTERCEPT_KEY) >= 0

    @property
    def intercept_index(self) -> int | None:
        idx = self.get_index(INTERCEPT_KEY)
        return None if idx < 0 else idx

    def close(self) -> None:
        for store in self._stores:
            store.close()

    # Mapping protocol -------------------------------------------------------
    def __getitem__(self, key: str) -> int:
        idx = self.get_index(key)
        if idx < 0:
            raise KeyError(key)
        return idx

    def __len__(self) -> int:
        return self._size

    def __iter__(self) -> Iterator[str]:
        for index in range(self._size):
            name = self.get_feature_name(index)
            if name is not None:
                yield name

    @classmethod
    def build(
        cls,
        directory: str | os.PathLike,
        index_map: Mapping[str, int] | IndexMap,
        *,
        num_partitions: int = 1,
        name: str = "index",
    ) -> "OffHeapIndexMap":
        build_offheap_store(
            directory, index_map, num_partitions=num_partitions, name=name
        )
        return cls(directory, name)
