"""Chunked out-of-core ingestion: double-buffered Avro decode behind compute.

Reference parity: photon-client data/avro/AvroDataReader.scala — the
reference never materializes the full input on one machine; Spark streams
HDFS splits through executor tasks while the driver aggregates. Here the
equivalent for a single host feeding an accelerator is an exact chunked
EPOCH: a background thread decodes the NEXT contiguous run of Avro
container blocks (the PR 2 block planner — ``avro.scan_block_index`` /
``read_container_block_range``) into host numpy buffers while the device
accumulates the CURRENT chunk's contribution (algorithm/streaming.py) —
the compute/ingest overlap Snap ML builds its hierarchy around
(arXiv:1803.06333).

Design rules (all enforced somewhere):

- **Fixed chunk shapes.** Every chunk pads to the plan's ``chunk_rows``
  (zero-weight rows — the framework padding contract), and sparse chunks
  share one ELL width / flat-entry length / hot-column count, so the
  device accumulator compiles ONCE and every chunk rides the same jit
  signature as an ARGUMENT (never a closed-over constant — the measured
  HTTP-413 landmine; dev/lint_parity.py check 9 statically bans nested
  jit in the streaming modules).
- **Prefetch is bounded and hang-free.** The producer thread and the
  consumer exchange through a depth-bounded queue with timeouts both
  ways plus a bounded join on close — a wedged side surfaces as a typed
  :class:`StreamDecodeError`, never an unbounded hang (the chaos suite
  has no pytest-timeout to save it).
- **Failures are classified.** Chunk decode runs under a
  ``resilience.RetryPolicy`` (transient I/O heals, fatal corruption
  surfaces attributed with the chunk's file/block span); the prefetch
  thread never swallows — it forwards the classified error to the
  consumer, which re-raises it on the caller's stack.
- **Observable.** Per-chunk decode ms, per-epoch chunk count, and the
  epoch's overlap fraction feed the process-wide registry
  (telemetry/stream_counters.py) — the run-journal evidence that decode
  actually hid behind device time.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import queue
import threading
import time
from typing import Callable, Mapping, Sequence

import numpy as np

from photon_ml_tpu.data.batch import LabeledPointBatch
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.resilience import RetryPolicy, classify_exception, default_io_policy
from photon_ml_tpu.telemetry import io_counters, stream_counters, tracing

#: consumer-side wait bound per chunk (seconds): generous enough for a slow
#: multi-GB chunk decode, bounded enough that a wedged producer fails
#: attributed instead of hanging a run forever (same rationale as
#: parallel/multihost.DEFAULT_EXCHANGE_TIMEOUT)
DEFAULT_CHUNK_TIMEOUT = 120.0

#: bounded join for the producer thread at close
JOIN_TIMEOUT = 10.0


class StreamDecodeError(RuntimeError):
    """A chunk failed to decode (after classified retries) or the prefetch
    pipeline wedged; carries the chunk attribution in the message."""


@dataclasses.dataclass(frozen=True)
class ChunkSpec:
    """One planned chunk: ``runs`` are contiguous (file, start_block,
    num_blocks) container-block ranges whose records fill the chunk."""

    index: int
    num_records: int
    runs: tuple[tuple[str, int, int], ...] = ()


class ChunkSource:
    """Protocol for streaming chunk sources.

    specs:      the epoch's chunk plan (fixed, re-iterable)
    chunk_rows: fixed padded row count every ``load`` result carries
    dim:        feature-space dimension
    sparse:     True when ``load`` yields SparseLabeledPointBatch chunks
    load(spec): decode + assemble one chunk — pure and idempotent (it is
                retried on transient failures), padded to ``chunk_rows``
    """

    specs: "list[ChunkSpec]"
    chunk_rows: int
    dim: int
    sparse: bool = False

    def load(self, spec: ChunkSpec):
        raise NotImplementedError

    @property
    def num_chunks(self) -> int:
        return len(self.specs)

    @property
    def total_records(self) -> int:
        return int(sum(s.num_records for s in self.specs))


def _pad_dense_chunk(
    features: np.ndarray,
    labels: np.ndarray,
    offsets: np.ndarray,
    weights: np.ndarray,
    chunk_rows: int,
) -> LabeledPointBatch:
    """Host-side zero-weight padding to the fixed chunk shape (numpy — the
    producer thread must not touch the device)."""
    n = features.shape[0]
    pad = chunk_rows - n
    if pad < 0:
        raise ValueError(f"chunk has {n} rows > plan chunk_rows {chunk_rows}")
    if pad:
        features = np.pad(features, ((0, pad), (0, 0)))
        labels = np.pad(labels, (0, pad))
        offsets = np.pad(offsets, (0, pad))
        weights = np.pad(weights, (0, pad))
    return LabeledPointBatch(
        features=features, labels=labels, offsets=offsets, weights=weights
    )


class ArrayChunkSource(ChunkSource):
    """Dense in-memory source: chunks a host [n, d] array by row ranges.

    The reference workload for tests/bench: ``decode_hook`` (called once
    per ``load`` in whichever thread loads) injects host decode cost or
    faults — e.g. a sleep standing in for disk/decompress latency, or a
    ``dev.faultinject.flaky`` transient failure.
    """

    sparse = False

    def __init__(
        self,
        features: np.ndarray,
        labels: np.ndarray,
        *,
        offsets: np.ndarray | None = None,
        weights: np.ndarray | None = None,
        chunk_rows: int,
        decode_hook: Callable[[], None] | None = None,
    ):
        self.features = np.asarray(features)
        n = self.features.shape[0]
        self.labels = np.asarray(labels, dtype=self.features.dtype)
        self.offsets = (
            np.zeros((n,), self.features.dtype) if offsets is None
            else np.asarray(offsets, dtype=self.features.dtype)
        )
        self.weights = (
            np.ones((n,), self.features.dtype) if weights is None
            else np.asarray(weights, dtype=self.features.dtype)
        )
        self.chunk_rows = int(chunk_rows)
        self.dim = int(self.features.shape[1])
        self.decode_hook = decode_hook
        self.specs = [
            ChunkSpec(index=i, num_records=min(self.chunk_rows, n - lo))
            for i, lo in enumerate(range(0, n, self.chunk_rows))
        ]

    def load(self, spec: ChunkSpec) -> LabeledPointBatch:
        if self.decode_hook is not None:
            self.decode_hook()
        lo = spec.index * self.chunk_rows
        hi = lo + spec.num_records
        # copies, not views: a real decode materializes fresh buffers, and
        # the accumulator must never alias the source arrays
        return _pad_dense_chunk(
            np.array(self.features[lo:hi]),
            np.array(self.labels[lo:hi]),
            np.array(self.offsets[lo:hi]),
            np.array(self.weights[lo:hi]),
            self.chunk_rows,
        )


class SparseArrayChunkSource(ChunkSource):
    """Sparse in-memory source: chunks host COO triples by row ranges into
    fixed-layout ELL (+ optional hybrid dense-head) chunks.

    The LAYOUT is resolved once, globally, at construction — one ELL width
    (the max post-head row count over every chunk), one flat-tail entry
    length, and one hot-column id set ranked on the FULL data — so every
    chunk shares a single jit signature (the same global-layout-agreement
    rule io/partitioned_reader._resolve_global_sparse_layout applies
    across ranks, applied here across chunks).
    """

    sparse = True

    def __init__(
        self,
        rows,
        cols,
        vals,
        labels,
        *,
        dim: int,
        chunk_rows: int,
        offsets=None,
        weights=None,
        hybrid=None,
        dtype=np.float64,
        decode_hook: Callable[[], None] | None = None,
    ):
        from photon_ml_tpu.data.sparse_batch import (
            coalesce_coo,
            rank_hot_columns,
            resolve_hybrid_policy,
        )

        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        vals = np.asarray(vals, dtype=dtype)
        self.rows, self.cols, self.vals = coalesce_coo(rows, cols, vals)
        self.labels = np.asarray(labels, dtype=dtype)
        n = self.labels.shape[0]
        self.offsets = (
            np.zeros((n,), dtype) if offsets is None
            else np.asarray(offsets, dtype=dtype)
        )
        self.weights = (
            np.ones((n,), dtype) if weights is None
            else np.asarray(weights, dtype=dtype)
        )
        self.dim = int(dim)
        self.dtype = dtype
        self.chunk_rows = int(chunk_rows)
        self.decode_hook = decode_hook
        self.specs = [
            ChunkSpec(index=i, num_records=min(self.chunk_rows, n - lo))
            for i, lo in enumerate(range(0, n, self.chunk_rows))
        ]

        # ---- one global layout for every chunk ----
        policy = resolve_hybrid_policy(hybrid)
        if policy is not None and policy.hot_ids is None:
            uniq, cnt = np.unique(self.cols, return_counts=True)
            hot = rank_hot_columns(uniq, cnt, len(self.vals), policy)
            policy = dataclasses.replace(
                policy, hot_ids=tuple(int(c) for c in hot)
            )
        self.hybrid_policy = policy
        if policy is not None:
            hot_sorted = np.sort(np.asarray(policy.hot_ids, dtype=np.int64))
            pos = np.searchsorted(hot_sorted, self.cols)
            is_hot = (
                hot_sorted[np.minimum(pos, len(hot_sorted) - 1)] == self.cols
            )
            tail_rows = self.rows[~is_hot]
        else:
            tail_rows = self.rows
        counts = np.bincount(tail_rows, minlength=n) if n else np.zeros(0, int)
        self.ell_width = int(counts.max()) if len(counts) else 0
        # every row fits the agreed width, so the flat tail holds only the
        # inert minimum (one zero entry keeps the [nnz] axis non-empty)
        self.flat_nnz = 1

    def load(self, spec: ChunkSpec):
        from photon_ml_tpu.data.sparse_batch import SparseLabeledPointBatch

        if self.decode_hook is not None:
            self.decode_hook()
        lo = spec.index * self.chunk_rows
        hi = lo + spec.num_records
        sel = (self.rows >= lo) & (self.rows < hi)
        labels = np.zeros((self.chunk_rows,), self.dtype)
        offsets = np.zeros((self.chunk_rows,), self.dtype)
        weights = np.zeros((self.chunk_rows,), self.dtype)
        labels[: spec.num_records] = self.labels[lo:hi]
        offsets[: spec.num_records] = self.offsets[lo:hi]
        weights[: spec.num_records] = self.weights[lo:hi]
        return SparseLabeledPointBatch.from_coo(
            self.rows[sel] - lo,
            self.cols[sel],
            self.vals[sel],
            labels,
            dim=self.dim,
            offsets=offsets,
            weights=weights,
            dtype=self.dtype,
            ell=self.ell_width,
            pad_nnz_to=self.flat_nnz,
            hybrid=self.hybrid_policy,
        )


class DenseRecordAssembler:
    """TrainingExampleAvro record dicts -> one fixed-shape dense chunk.

    Mirrors ``io.data_reader.records_to_game_dataset``'s per-record
    semantics exactly (label/response fallback, None offset -> 0, None
    weight -> 1, name+term feature keys, duplicate (row, col) accumulation
    via np.add.at, intercept column) so a streamed epoch consumes the SAME
    numbers the in-core read would build — pinned by
    tests/test_streaming.py's bitwise chunk-identity test.
    """

    def __init__(self, index_map, shard_config, dtype=np.float32):
        self.index_map = index_map
        self.shard_config = shard_config
        self.dtype = dtype

    def __call__(self, records: list, chunk_rows: int) -> LabeledPointBatch:
        from photon_ml_tpu.io.data_reader import (
            OFFSET,
            RESPONSE,
            WEIGHT,
            _apply_intercept,
            _record_bags,
            _scatter_dense,
        )
        from photon_ml_tpu.io.index_map import feature_key

        n = len(records)
        labels = np.zeros((n,), np.float64)
        offsets = np.zeros((n,), np.float64)
        weights = np.ones((n,), np.float64)
        triples: list[tuple[int, int, float]] = []
        imap = self.index_map
        for i, record in enumerate(records):
            label = record.get("label", record.get(RESPONSE))
            if label is None:
                raise ValueError("record has neither 'label' nor 'response'")
            labels[i] = float(label)
            offset = record.get(OFFSET)
            offsets[i] = 0.0 if offset is None else float(offset)
            weight = record.get(WEIGHT)
            weights[i] = 1.0 if weight is None else float(weight)
            bags = _record_bags(record)
            for bag in self.shard_config.feature_bags:
                for feat in bags.get(bag, ()):
                    j = imap.get_index(
                        feature_key(feat["name"], feat.get("term") or "")
                    )
                    if j >= 0:
                        triples.append((i, j, float(feat["value"])))
        t = np.asarray(triples, dtype=np.float64) if triples else np.zeros((0, 3))
        x = _scatter_dense(n, imap.size, t[:, 0], t[:, 1], t[:, 2], self.dtype)
        if self.shard_config.has_intercept:
            _apply_intercept(x, imap, "features", {})
        return _pad_dense_chunk(
            x,
            labels.astype(self.dtype),
            offsets.astype(self.dtype),
            weights.astype(self.dtype),
            chunk_rows,
        )


def plan_chunks(
    files: Sequence[str],
    chunk_records: int,
    *,
    on_corrupt: str = "raise",
    indexes: "list[list[tuple[int, int, int]]] | None" = None,
    block_subset: "Sequence[tuple[int, int]] | None" = None,
) -> tuple[list[ChunkSpec], "list[list[tuple[int, int, int]]]"]:
    """Group contiguous container blocks into chunks of at most
    ``chunk_records`` records (a single over-budget block still forms its
    own chunk — blocks are the atomic decode unit). Costs one header
    decode + one seek per block (``avro.scan_block_index``), never a data
    read. ``block_subset``: optional (file_idx, block_idx) list — a rank's
    assignment from the partitioned planner; the epoch then streams only
    those blocks. Returns (specs, per-file block indexes) so loads skip
    the re-scan.
    """
    if chunk_records <= 0:
        raise ValueError(f"chunk_records must be positive, got {chunk_records}")
    if indexes is None:
        indexes = [
            avro_io.scan_block_index(f, on_corrupt=on_corrupt) for f in files
        ]
    if not any(len(ix) for ix in indexes):
        raise ValueError("no Avro blocks to stream")
    blocks = (
        list(block_subset)
        if block_subset is not None
        else [
            (fi, bi)
            for fi, file_index in enumerate(indexes)
            for bi in range(len(file_index))
        ]
    )
    specs: list[ChunkSpec] = []
    cur: list[tuple[int, int]] = []
    cur_records = 0

    def flush():
        nonlocal cur, cur_records
        if not cur:
            return
        runs: list[tuple[str, int, int]] = []
        for fi, group in itertools.groupby(cur, key=lambda b: b[0]):
            bis = [bi for _, bi in group]
            # split a file's blocks into contiguous runs (a gap — e.g. a
            # quarantined span or a partitioned subset — starts a new
            # seek range)
            run_start = prev = bis[0]
            for bi in bis[1:] + [None]:
                if bi is None or bi != prev + 1:
                    runs.append((files[fi], run_start, prev - run_start + 1))
                    run_start = bi
                prev = bi if bi is not None else prev
        specs.append(
            ChunkSpec(
                index=len(specs), num_records=cur_records, runs=tuple(runs)
            )
        )
        cur, cur_records = [], 0

    for fi, bi in blocks:
        n_rec = indexes[fi][bi][0]
        if cur and cur_records + n_rec > chunk_records:
            flush()
        cur.append((fi, bi))
        cur_records += n_rec
    flush()
    # an explicitly empty subset (a rank assigned no blocks) is a valid
    # zero-chunk plan — its epochs contribute zero to the cross-rank sum
    return specs, indexes


class AvroChunkSource(ChunkSource):
    """Streams chunks from Avro container files through a record
    assembler, decoding only each chunk's block ranges per load (the PR 2
    block planner's seek-to-payload reads)."""

    sparse = False

    def __init__(
        self,
        files: Sequence[str],
        assembler: Callable[[list, int], LabeledPointBatch],
        *,
        chunk_records: int,
        on_corrupt: str = "raise",
        indexes=None,
        block_subset=None,
        dim: int | None = None,
    ):
        self.files = [str(f) for f in files]
        self.assembler = assembler
        self.on_corrupt = on_corrupt
        self.specs, self.indexes = plan_chunks(
            self.files, chunk_records, on_corrupt=on_corrupt,
            indexes=indexes, block_subset=block_subset,
        )
        self.chunk_rows = max(
            (s.num_records for s in self.specs), default=0
        )
        if dim is not None:
            self.dim = int(dim)
        else:
            imap = getattr(assembler, "index_map", None)
            self.dim = int(imap.size) if imap is not None else 0
        self._file_pos = {f: i for i, f in enumerate(self.files)}

    def load(self, spec: ChunkSpec) -> LabeledPointBatch:
        records: list = []
        payload_bytes = 0
        for path, start, count in spec.runs:
            index = self.indexes[self._file_pos[path]]
            payload_bytes += sum(sz for _, sz, _ in index[start:start + count])
            records.extend(
                avro_io.read_container_block_range(
                    path, start, count, index=index,
                    on_corrupt=self.on_corrupt,
                )
            )
        io_counters.record_bytes_decoded(payload_bytes)
        return self.assembler(records, self.chunk_rows)


_END = object()


class ChunkPrefetcher:
    """One epoch's chunk iterator: double-buffered decode behind the
    consumer (prefetch=True) or inline (prefetch=False), with classified
    retry, bounded timeouts, and per-epoch overlap telemetry.

    Use as a context manager; iterating yields each chunk batch once, in
    plan order. ``close()`` (idempotent, called by ``__exit__``) stops the
    producer with a bounded join — abandoning an epoch mid-way (solver
    line-search rejection never does, but errors might) cannot leak a
    wedged thread.
    """

    def __init__(
        self,
        source: ChunkSource,
        *,
        prefetch: bool = True,
        depth: int = 1,
        retry_policy: RetryPolicy | None = None,
        chunk_timeout: float = DEFAULT_CHUNK_TIMEOUT,
    ):
        self.source = source
        self.prefetch = bool(prefetch)
        self.depth = max(1, int(depth))
        self.policy = retry_policy if retry_policy is not None else default_io_policy()
        self.chunk_timeout = float(chunk_timeout)
        self.decode_seconds = 0.0
        self.wait_seconds = 0.0
        self._queue: queue.Queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- producer -------------------------------------------------------------

    def _load_timed(self, spec: ChunkSpec):
        t0 = time.perf_counter()
        # the decode span runs in whichever thread loads (producer when
        # prefetching, consumer inline otherwise) — per-thread trace
        # buffers keep both readable in the timeline
        with tracing.span("io/decode_chunk", cat="stream",
                          chunk=spec.index, records=spec.num_records):
            batch = self.policy.call(
                self.source.load, spec,
                description=f"decode chunk {spec.index}",
            )
        dt = time.perf_counter() - t0
        self.decode_seconds += dt
        stream_counters.record_chunk_decode_ms(dt * 1e3)
        return batch

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _producer(self) -> None:
        for spec in self.source.specs:
            if self._stop.is_set():
                return
            try:
                batch = self._load_timed(spec)
            except Exception as e:
                # the retry policy already classified and retried what was
                # transient; forward the surviving failure to the consumer's
                # stack — a thread cannot re-raise usefully, and swallowing
                # it would hang the epoch (reviewed allowlist entry in
                # dev/lint_parity.py check 5)
                classify_exception(e)
                try:
                    e._chunk_spec = spec
                except AttributeError:
                    pass  # __slots__ exception types lose the attribution
                self._put((None, e))
                return
            if not self._put((spec, batch)):
                return
        self._put((None, _END))

    # -- consumer -------------------------------------------------------------

    def __enter__(self) -> "ChunkPrefetcher":
        if self.prefetch:
            self._thread = threading.Thread(
                target=self._producer, name="chunk-prefetch", daemon=True
            )
            self._thread.start()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            # drain so a blocked put can finish, then bounded join
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=JOIN_TIMEOUT)
            self._thread = None

    def _next_prefetched(self):
        deadline = time.perf_counter() + self.chunk_timeout
        t0 = time.perf_counter()
        # consumer-side queue wait: the complement of io/decode_chunk in
        # the overlap story (overlap ≈ 1 - wait/decode, the
        # stream/overlap_fraction gauge — the spans reproduce it)
        with tracing.span("io/chunk_wait", cat="stream"):
            while True:
                try:
                    item = self._queue.get(timeout=0.2)
                    self.wait_seconds += time.perf_counter() - t0
                    return item
                except queue.Empty:
                    if self._thread is not None and not self._thread.is_alive():
                        raise StreamDecodeError(
                            "prefetch thread died without forwarding a result"
                        ) from None
                    if time.perf_counter() > deadline:
                        raise StreamDecodeError(
                            f"no chunk arrived within "
                            f"{self.chunk_timeout:.0f}s (wedged decode?)"
                        ) from None

    def __iter__(self):
        if not self.prefetch:
            for spec in self.source.specs:
                try:
                    yield self._load_timed(spec)
                except Exception as e:
                    raise self._attributed(e, spec) from e
            self._finish_epoch()
            return
        while True:
            spec, item = self._next_prefetched()
            if item is _END:
                break
            if isinstance(item, BaseException):
                failed = self._failed_spec(item)
                raise self._attributed(item, failed) from item
            yield item
        self._finish_epoch()

    def _failed_spec(self, exc) -> ChunkSpec | None:
        return getattr(exc, "_chunk_spec", None)

    def _attributed(self, exc, spec: ChunkSpec | None):
        where = (
            f"chunk {spec.index} (records={spec.num_records}, "
            f"runs={list(spec.runs)})" if spec is not None else "a chunk"
        )
        return StreamDecodeError(
            f"streaming epoch failed decoding {where}: "
            f"{type(exc).__name__}: {exc}"
        )

    def _finish_epoch(self) -> None:
        stream_counters.set_chunks_per_epoch(self.source.num_chunks)
        if self.prefetch and self.decode_seconds > 0.0:
            hidden = max(0.0, self.decode_seconds - self.wait_seconds)
            stream_counters.set_overlap_fraction(hidden / self.decode_seconds)
        else:
            stream_counters.set_overlap_fraction(0.0)


def build_streaming_index_maps(
    files: Sequence[str],
    shard_configs: Mapping[str, object],
    *,
    on_corrupt: str = "raise",
):
    """Global feature index maps from one streaming pass over the input —
    records are decoded and DISCARDED (memory stays O(vocabulary), the
    out-of-core requirement), exactly the keyset+sort rule the full read
    applies (io.data_reader.build_index_maps)."""
    from photon_ml_tpu.io.data_reader import build_index_maps

    return build_index_maps(
        itertools.chain.from_iterable(
            avro_io.read_container(f, on_corrupt=on_corrupt) for f in files
        ),
        shard_configs,
    )


def plan_partitioned_stream(
    path,
    shard_configs: Mapping[str, object],
    *,
    exchange,
    chunk_records: int,
    on_corrupt: str = "raise",
    dtype=np.float32,
    tag: str = "stream",
):
    """The --partitioned-io × --streaming-chunks composition: each rank
    gets a chunk source over ITS contiguous block assignment, with
    globally consistent index maps agreed over the metadata exchange —
    the same assignment rule (size-balanced contiguous block runs,
    ``partitioned_reader.assign_contiguous``) and the same
    key-union/sort map agreement the partitioned full read applies, so
    rank plans are verified identical by fingerprint and every rank's
    prefetcher decodes ~1/P of the bytes.

    The rank-local vocab pass decodes ONLY this rank's blocks (discarding
    records); ONE allgather unions the key sets. Dense feature shards
    (the GLM driver's layout). Returns
    ``(source, index_maps, intercept_indices)``; train with
    ``estimators.train_glm_streaming(source, ..., exchange=exchange)`` so
    the per-epoch accumulators sum across ranks in rank order.
    """
    from photon_ml_tpu.io.data_reader import build_index_maps
    from photon_ml_tpu.io.index_map import INTERCEPT_KEY, IndexMap
    from photon_ml_tpu.io.partitioned_reader import (
        _local_keys,
        _plan_fingerprint,
        assign_contiguous,
    )

    files = avro_io.list_avro_files(path)
    sizes = [int(os.path.getsize(f)) for f in files]
    io_counters.set_input_bytes_total(int(sum(sizes)))
    indexes = [
        avro_io.scan_block_index(f, on_corrupt=on_corrupt) for f in files
    ]
    blocks = [
        (fi, bi, payload)
        for fi, file_index in enumerate(indexes)
        for bi, (_, payload, _) in enumerate(file_index)
    ]
    if not blocks:
        raise ValueError(f"no Avro blocks under {path!r}")
    ranges = assign_contiguous([b[2] for b in blocks], exchange.num_ranks)
    lo, hi = ranges[exchange.rank]
    my_blocks = [(fi, bi) for fi, bi, _ in blocks[lo:hi]]

    def my_records():
        for spec_fi, group in itertools.groupby(my_blocks, key=lambda b: b[0]):
            bis = [bi for _, bi in group]
            yield from avro_io.read_container_block_range(
                files[spec_fi], bis[0], len(bis), index=indexes[spec_fi],
                on_corrupt=on_corrupt,
            )

    local_maps = build_index_maps(my_records(), shard_configs)
    payload = {
        "fingerprint": _plan_fingerprint(
            files, sizes, "stream-blocks", ranges
        ),
        "keys": {
            shard: _local_keys(local_maps[shard], cfg)
            for shard, cfg in shard_configs.items()
        },
    }
    with tracing.span("partitioned/stream_plan_exchange", cat="partitioned",
                      tag=tag, rank=exchange.rank):
        gathered = exchange.allgather(f"stream_plan/{tag}", payload)
    fingerprints = {g["fingerprint"] for g in gathered}
    if len(fingerprints) != 1:
        raise RuntimeError(
            f"ranks disagree on the streaming block plan ({fingerprints}); "
            "the input listing must be identical on every rank"
        )
    index_maps: dict[str, IndexMap] = {}
    intercepts: dict[str, int] = {}
    for shard, cfg in shard_configs.items():
        union: set[str] = set()
        for g in gathered:
            union.update(g["keys"][shard])
        imap = IndexMap.from_keys(union, add_intercept=cfg.has_intercept)
        index_maps[shard] = imap
        if cfg.has_intercept:
            ii = imap.get_index(INTERCEPT_KEY)
            if ii >= 0:
                intercepts[shard] = ii
    shard = next(iter(shard_configs))
    source = AvroChunkSource(
        files,
        DenseRecordAssembler(index_maps[shard], shard_configs[shard], dtype),
        chunk_records=chunk_records,
        on_corrupt=on_corrupt,
        indexes=indexes,
        block_subset=my_blocks,
    )
    return source, index_maps, intercepts
