"""Chunked out-of-core ingestion: double-buffered Avro decode behind compute.

Reference parity: photon-client data/avro/AvroDataReader.scala — the
reference never materializes the full input on one machine; Spark streams
HDFS splits through executor tasks while the driver aggregates. Here the
equivalent for a single host feeding an accelerator is an exact chunked
EPOCH: a background thread decodes the NEXT contiguous run of Avro
container blocks (the PR 2 block planner — ``avro.scan_block_index`` /
``read_container_block_range``) into host numpy buffers while the device
accumulates the CURRENT chunk's contribution (algorithm/streaming.py) —
the compute/ingest overlap Snap ML builds its hierarchy around
(arXiv:1803.06333).

Design rules (all enforced somewhere):

- **Fixed chunk shapes.** Every chunk pads to the plan's ``chunk_rows``
  (zero-weight rows — the framework padding contract), and sparse chunks
  share one ELL width / flat-entry length / hot-column count, so the
  device accumulator compiles ONCE and every chunk rides the same jit
  signature as an ARGUMENT (never a closed-over constant — the measured
  HTTP-413 landmine; dev/lint_parity.py check 9 statically bans nested
  jit in the streaming modules).
- **Prefetch is bounded and hang-free.** The producer thread and the
  consumer exchange through a depth-bounded queue with timeouts both
  ways plus a bounded join on close — a wedged side surfaces as a typed
  :class:`StreamDecodeError`, never an unbounded hang (the chaos suite
  has no pytest-timeout to save it).
- **Failures are classified.** Chunk decode runs under a
  ``resilience.RetryPolicy`` (transient I/O heals, fatal corruption
  surfaces attributed with the chunk's file/block span); the prefetch
  thread never swallows — it forwards the classified error to the
  consumer, which re-raises it on the caller's stack.
- **Observable.** Per-chunk decode ms, per-epoch chunk count, and the
  epoch's overlap fraction feed the process-wide registry
  (telemetry/stream_counters.py) — the run-journal evidence that decode
  actually hid behind device time.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import json
import os
import queue
import threading
import time
from typing import Callable, Mapping, Sequence

import numpy as np

from photon_ml_tpu.data.batch import LabeledPointBatch
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.resilience import RetryPolicy, classify_exception, default_io_policy
from photon_ml_tpu.telemetry import io_counters, stream_counters, tracing

#: consumer-side wait bound per chunk (seconds): generous enough for a slow
#: multi-GB chunk decode, bounded enough that a wedged producer fails
#: attributed instead of hanging a run forever (same rationale as
#: parallel/multihost.DEFAULT_EXCHANGE_TIMEOUT)
DEFAULT_CHUNK_TIMEOUT = 120.0

#: bounded join for the producer thread at close
JOIN_TIMEOUT = 10.0


class StreamDecodeError(RuntimeError):
    """A chunk failed to decode (after classified retries) or the prefetch
    pipeline wedged; carries the chunk attribution in the message."""


@dataclasses.dataclass(frozen=True)
class ChunkSpec:
    """One planned chunk: ``runs`` are contiguous (file, start_block,
    num_blocks) container-block ranges whose records fill the chunk."""

    index: int
    num_records: int
    runs: tuple[tuple[str, int, int], ...] = ()


class ChunkSource:
    """Protocol for streaming chunk sources.

    specs:      the epoch's chunk plan (fixed, re-iterable)
    chunk_rows: fixed padded row count every ``load`` result carries
    dim:        feature-space dimension
    sparse:     True when ``load`` yields SparseLabeledPointBatch chunks
    load(spec): decode + assemble one chunk — pure and idempotent (it is
                retried on transient failures), padded to ``chunk_rows``
    """

    specs: "list[ChunkSpec]"
    chunk_rows: int
    dim: int
    sparse: bool = False

    def load(self, spec: ChunkSpec):
        raise NotImplementedError

    @property
    def num_chunks(self) -> int:
        return len(self.specs)

    @property
    def total_records(self) -> int:
        return int(sum(s.num_records for s in self.specs))


def _pad_dense_chunk(
    features: np.ndarray,
    labels: np.ndarray,
    offsets: np.ndarray,
    weights: np.ndarray,
    chunk_rows: int,
) -> LabeledPointBatch:
    """Host-side zero-weight padding to the fixed chunk shape (numpy — the
    producer thread must not touch the device)."""
    n = features.shape[0]
    pad = chunk_rows - n
    if pad < 0:
        raise ValueError(f"chunk has {n} rows > plan chunk_rows {chunk_rows}")
    if pad:
        features = np.pad(features, ((0, pad), (0, 0)))
        labels = np.pad(labels, (0, pad))
        offsets = np.pad(offsets, (0, pad))
        weights = np.pad(weights, (0, pad))
    return LabeledPointBatch(
        features=features, labels=labels, offsets=offsets, weights=weights
    )


class ArrayChunkSource(ChunkSource):
    """Dense in-memory source: chunks a host [n, d] array by row ranges.

    The reference workload for tests/bench: ``decode_hook`` (called once
    per ``load`` in whichever thread loads) injects host decode cost or
    faults — e.g. a sleep standing in for disk/decompress latency, or a
    ``dev.faultinject.flaky`` transient failure.
    """

    sparse = False

    def __init__(
        self,
        features: np.ndarray,
        labels: np.ndarray,
        *,
        offsets: np.ndarray | None = None,
        weights: np.ndarray | None = None,
        chunk_rows: int,
        decode_hook: Callable[[], None] | None = None,
    ):
        self.features = np.asarray(features)
        n = self.features.shape[0]
        self.labels = np.asarray(labels, dtype=self.features.dtype)
        self.offsets = (
            np.zeros((n,), self.features.dtype) if offsets is None
            else np.asarray(offsets, dtype=self.features.dtype)
        )
        self.weights = (
            np.ones((n,), self.features.dtype) if weights is None
            else np.asarray(weights, dtype=self.features.dtype)
        )
        self.chunk_rows = int(chunk_rows)
        self.dim = int(self.features.shape[1])
        self.decode_hook = decode_hook
        self.specs = [
            ChunkSpec(index=i, num_records=min(self.chunk_rows, n - lo))
            for i, lo in enumerate(range(0, n, self.chunk_rows))
        ]

    def load(self, spec: ChunkSpec) -> LabeledPointBatch:
        if self.decode_hook is not None:
            self.decode_hook()
        lo = spec.index * self.chunk_rows
        hi = lo + spec.num_records
        # copies, not views: a real decode materializes fresh buffers, and
        # the accumulator must never alias the source arrays
        return _pad_dense_chunk(
            np.array(self.features[lo:hi]),
            np.array(self.labels[lo:hi]),
            np.array(self.offsets[lo:hi]),
            np.array(self.weights[lo:hi]),
            self.chunk_rows,
        )


class SparseArrayChunkSource(ChunkSource):
    """Sparse in-memory source: chunks host COO triples by row ranges into
    fixed-layout ELL (+ optional hybrid dense-head) chunks.

    The LAYOUT is resolved once, globally, at construction — one ELL width
    (the max post-head row count over every chunk), one flat-tail entry
    length, and one hot-column id set ranked on the FULL data — so every
    chunk shares a single jit signature (the same global-layout-agreement
    rule io/partitioned_reader._resolve_global_sparse_layout applies
    across ranks, applied here across chunks).
    """

    sparse = True

    def __init__(
        self,
        rows,
        cols,
        vals,
        labels,
        *,
        dim: int,
        chunk_rows: int,
        offsets=None,
        weights=None,
        hybrid=None,
        dtype=np.float64,
        decode_hook: Callable[[], None] | None = None,
    ):
        from photon_ml_tpu.data.sparse_batch import (
            coalesce_coo,
            rank_hot_columns,
            resolve_hybrid_policy,
        )

        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        vals = np.asarray(vals, dtype=dtype)
        self.rows, self.cols, self.vals = coalesce_coo(rows, cols, vals)
        self.labels = np.asarray(labels, dtype=dtype)
        n = self.labels.shape[0]
        self.offsets = (
            np.zeros((n,), dtype) if offsets is None
            else np.asarray(offsets, dtype=dtype)
        )
        self.weights = (
            np.ones((n,), dtype) if weights is None
            else np.asarray(weights, dtype=dtype)
        )
        self.dim = int(dim)
        self.dtype = dtype
        self.chunk_rows = int(chunk_rows)
        self.decode_hook = decode_hook
        self.specs = [
            ChunkSpec(index=i, num_records=min(self.chunk_rows, n - lo))
            for i, lo in enumerate(range(0, n, self.chunk_rows))
        ]

        # ---- one global layout for every chunk ----
        policy = resolve_hybrid_policy(hybrid)
        if policy is not None and policy.hot_ids is None:
            uniq, cnt = np.unique(self.cols, return_counts=True)
            hot = rank_hot_columns(uniq, cnt, len(self.vals), policy)
            policy = dataclasses.replace(
                policy, hot_ids=tuple(int(c) for c in hot)
            )
        self.hybrid_policy = policy
        if policy is not None:
            hot_sorted = np.sort(np.asarray(policy.hot_ids, dtype=np.int64))
            pos = np.searchsorted(hot_sorted, self.cols)
            is_hot = (
                hot_sorted[np.minimum(pos, len(hot_sorted) - 1)] == self.cols
            )
            tail_rows = self.rows[~is_hot]
        else:
            tail_rows = self.rows
        counts = np.bincount(tail_rows, minlength=n) if n else np.zeros(0, int)
        self.ell_width = int(counts.max()) if len(counts) else 0
        # every row fits the agreed width, so the flat tail holds only the
        # inert minimum (one zero entry keeps the [nnz] axis non-empty)
        self.flat_nnz = 1

    def load(self, spec: ChunkSpec):
        from photon_ml_tpu.data.sparse_batch import SparseLabeledPointBatch

        if self.decode_hook is not None:
            self.decode_hook()
        lo = spec.index * self.chunk_rows
        hi = lo + spec.num_records
        sel = (self.rows >= lo) & (self.rows < hi)
        labels = np.zeros((self.chunk_rows,), self.dtype)
        offsets = np.zeros((self.chunk_rows,), self.dtype)
        weights = np.zeros((self.chunk_rows,), self.dtype)
        labels[: spec.num_records] = self.labels[lo:hi]
        offsets[: spec.num_records] = self.offsets[lo:hi]
        weights[: spec.num_records] = self.weights[lo:hi]
        return SparseLabeledPointBatch.from_coo(
            self.rows[sel] - lo,
            self.cols[sel],
            self.vals[sel],
            labels,
            dim=self.dim,
            offsets=offsets,
            weights=weights,
            dtype=self.dtype,
            ell=self.ell_width,
            pad_nnz_to=self.flat_nnz,
            hybrid=self.hybrid_policy,
        )


# ---------------------------------------------------------------------------
# Entity-clustered GAME chunks (ISSUE 11): the out-of-core GAME contract
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class GameChunk:
    """One decoded GAME chunk (host numpy, fixed ``chunk_rows`` padding).

    The GAME analogue of a :class:`LabeledPointBatch` chunk: per-shard
    feature blocks, per-sample scalars, per-RE-type entity indices (into
    the GLOBAL entity vocab, -1 for absent/padding), and each slot's
    GLOBAL sample row (``rows``, -1 padding) so host-resident [n] score
    vectors can be read/written per chunk. Padding rows carry weight 0 /
    zero features per the framework padding contract.
    """

    features: "dict[str, np.ndarray]"
    labels: np.ndarray
    offsets: np.ndarray
    weights: np.ndarray
    entity_idx: "dict[str, np.ndarray]"
    rows: np.ndarray
    num_records: int


def plan_entity_chunks(
    entity_idx: np.ndarray, chunk_records: int
) -> "list[np.ndarray]":
    """Entity-clustered chunk plan over in-memory rows: pack WHOLE
    entities (all rows sharing an entity index, in ascending row order)
    greedily into chunks of at most ``chunk_records`` rows — an entity
    larger than the budget forms its own chunk, like an over-budget Avro
    block in :func:`plan_chunks`. Rows with entity -1 (vocab-absent:
    scored, never trained) pack as singletons wherever they fall.

    This is what lets a random-effect bucket solve run per chunk with the
    chunk resident: every entity's rows co-reside in exactly ONE chunk,
    so its per-entity solve sees the identical padded block the in-core
    path builds (zero-weight cap padding is an exact no-op). Returns the
    per-chunk global row-index arrays.
    """
    if chunk_records <= 0:
        raise ValueError(f"chunk_records must be positive, got {chunk_records}")
    entity_idx = np.asarray(entity_idx)
    n = len(entity_idx)
    if n == 0:
        return []
    # stable sort groups each entity's rows contiguously while preserving
    # ascending row order within the entity (the in-core bucketing's order)
    order = np.argsort(entity_idx, kind="stable")
    ents = entity_idx[order]
    boundaries = np.concatenate(
        [[0], np.nonzero(ents[1:] != ents[:-1])[0] + 1, [n]]
    )
    chunks: list[np.ndarray] = []
    cur: list[np.ndarray] = []
    cur_n = 0

    def flush():
        nonlocal cur, cur_n
        if cur:
            chunks.append(np.concatenate(cur))
            cur, cur_n = [], 0

    for start, end in zip(boundaries[:-1], boundaries[1:]):
        if ents[start] < 0:
            # vocab-absent rows: no clustering constraint — split freely
            group_rows = order[start:end]
            for lo in range(0, len(group_rows), chunk_records):
                sub = group_rows[lo:lo + chunk_records]
                if cur and cur_n + len(sub) > chunk_records:
                    flush()
                cur.append(sub)
                cur_n += len(sub)
            continue
        group = order[start:end]
        if cur and cur_n + len(group) > chunk_records:
            flush()
        cur.append(group)
        cur_n += len(group)
    flush()
    return chunks


def entities_spanning_chunks(
    row_plan: "Sequence[np.ndarray]", entity_idx: np.ndarray
) -> np.ndarray:
    """Entity rows (vocab indices) whose samples land in MORE than one
    chunk of ``row_plan`` — the entities a per-chunk random-effect solve
    would silently train on partial data (last chunk wins). Empty means
    the plan entity-clusters this RE type."""
    entity_idx = np.asarray(entity_idx)
    chunk_of = np.full(len(entity_idx), -1, dtype=np.int64)
    for i, rows in enumerate(row_plan):
        chunk_of[rows] = i
    valid = entity_idx >= 0
    if not valid.any():
        return np.zeros((0,), dtype=np.int64)
    pairs = np.unique(
        np.stack([entity_idx[valid].astype(np.int64), chunk_of[valid]]),
        axis=1,
    )
    ents, counts = np.unique(pairs[0], return_counts=True)
    return ents[counts > 1]


class GameArrayChunkSource:
    """Entity-clustered in-memory GAME chunk source: host arrays chunked
    by whole-entity row groups (:func:`plan_entity_chunks`).

    The host-RAM >> HBM tier of the out-of-core hierarchy (Snap ML,
    arXiv:1803.06333): per-sample scalars ([n] labels/offsets/weights/
    entity indices and the score vectors the streamed GAME program keeps)
    stay host-resident, while the O(n·d) feature blocks enter the device
    one fixed-shape chunk at a time through the module-level jitted steps
    (algorithm/streaming_game.py — chunks as jit ARGUMENTS, lint check 9).

    ``cluster_by``: the RE type whose entities define chunk grouping
    (required when any random-effect coordinate trains from this source);
    other RE types must nest inside those groups —
    ``StreamingGameProgram`` verifies with :func:`entities_spanning_chunks`
    and fails fast otherwise. ``decode_hook`` runs once per load in the
    loading thread (prefetch-overlap and fault-injection seam, like
    :class:`ArrayChunkSource`).
    """

    sparse = False

    def __init__(
        self,
        *,
        features: "Mapping[str, np.ndarray]",
        labels: np.ndarray,
        entity_idx: "Mapping[str, np.ndarray]",
        offsets: np.ndarray | None = None,
        weights: np.ndarray | None = None,
        chunk_records: int,
        cluster_by: str | None = None,
        decode_hook: Callable[[], None] | None = None,
    ):
        self.features = {k: np.asarray(v) for k, v in features.items()}
        self.labels = np.asarray(labels)
        n = self.labels.shape[0]
        dtype = self.labels.dtype
        self.offsets = (
            np.zeros((n,), dtype) if offsets is None
            else np.asarray(offsets, dtype=dtype)
        )
        self.weights = (
            np.ones((n,), dtype) if weights is None
            else np.asarray(weights, dtype=dtype)
        )
        self.entity_idx = {
            t: np.asarray(v, dtype=np.int32) for t, v in entity_idx.items()
        }
        self.decode_hook = decode_hook
        if cluster_by is not None and cluster_by not in self.entity_idx:
            raise ValueError(
                f"cluster_by={cluster_by!r} is not an entity-index column "
                f"({sorted(self.entity_idx)})"
            )
        self.cluster_by = cluster_by
        if cluster_by is not None:
            self.row_plan = plan_entity_chunks(
                self.entity_idx[cluster_by], chunk_records
            )
        else:
            self.row_plan = [
                np.arange(lo, min(lo + chunk_records, n))
                for lo in range(0, n, chunk_records)
            ]
        self.specs = [
            ChunkSpec(index=i, num_records=len(rows))
            for i, rows in enumerate(self.row_plan)
        ]
        self.chunk_rows = max((len(r) for r in self.row_plan), default=0)
        self.dims = {k: int(v.shape[1]) for k, v in self.features.items()}

    @property
    def num_chunks(self) -> int:
        return len(self.specs)

    @property
    def total_records(self) -> int:
        return int(sum(s.num_records for s in self.specs))

    def load(self, spec: ChunkSpec) -> GameChunk:
        if self.decode_hook is not None:
            self.decode_hook()
        idx = self.row_plan[spec.index]
        pad = self.chunk_rows - len(idx)

        def pad1(a, fill=0):
            out = a[idx]
            if pad:
                out = np.concatenate(
                    [out, np.full((pad,) + out.shape[1:], fill, out.dtype)]
                )
            return out

        rows = idx.astype(np.int64)
        if pad:
            rows = np.concatenate([rows, np.full((pad,), -1, np.int64)])
        return GameChunk(
            # copies, not views (fancy indexing copies): the accumulator
            # must never alias the source arrays
            features={k: pad1(v) for k, v in self.features.items()},
            labels=pad1(self.labels),
            offsets=pad1(self.offsets),
            weights=pad1(self.weights),
            entity_idx={
                t: pad1(v, fill=-1) for t, v in self.entity_idx.items()
            },
            rows=rows,
            num_records=len(idx),
        )


def plan_entity_chunks_avro(
    files: Sequence[str],
    chunk_records: int,
    cluster_keys: np.ndarray,
    *,
    indexes: "list[list[tuple[int, int, int]]] | None" = None,
    on_corrupt: str = "raise",
):
    """Entity-clustered Avro chunk plan at RECORD granularity: a chunk is
    a record range whose end lands on the first clustering-entity CHANGE
    at or after ``chunk_records`` rows (``cluster_keys``: the per-record
    entity key of the cluster column in file+record order; "" — a missing
    id — is itself a vocab entity and clusters like any other), so an
    entity-sorted input yields chunks
    that hold whole entities without requiring entities to align to
    container-block boundaries. Each chunk's ``runs`` are the COVERING
    block ranges (a boundary block decodes for both neighbors — bounded
    extra decode, exact chunks); loads slice the decoded records to the
    range. An entity larger than the budget extends its chunk; unsorted
    input degrades to over-budget chunks rather than wrong solves
    (``StreamingGameProgram`` still verifies clustering per RE type).
    Returns (specs, per-file block indexes, per-chunk record starts,
    per-chunk leading-record skips into the first covering block).
    """
    if chunk_records <= 0:
        raise ValueError(f"chunk_records must be positive, got {chunk_records}")
    if indexes is None:
        indexes = [
            avro_io.scan_block_index(f, on_corrupt=on_corrupt) for f in files
        ]
    cluster_keys = np.asarray(cluster_keys).astype(str)
    total = sum(n for file_index in indexes for (n, _, _) in file_index)
    if len(cluster_keys) != total:
        raise ValueError(
            f"cluster_keys covers {len(cluster_keys)} records but the "
            f"block index holds {total}"
        )
    # "" (a record missing the id column) is a REAL vocab entity on the
    # decode path (np.unique of keys, the in-core build_game_dataset
    # rule), so "" runs cluster like any other entity — splitting them
    # freely would make the program's clustering verification reject an
    # input the in-core path trains fine
    splittable = np.ones(total + 1, dtype=bool)
    if total > 1:
        same = cluster_keys[1:] == cluster_keys[:-1]
        splittable[1:total] = ~same
    specs, starts, skips = _entity_chunks_over_blocks(
        files, indexes, chunk_records, splittable
    )
    return specs, indexes, starts, skips


def _entity_chunks_over_blocks(
    files: Sequence[str],
    indexes: "list[list[tuple[int, int, int]]]",
    chunk_records: int,
    splittable: np.ndarray,
):
    """The record-granular chunk loop shared by
    :func:`plan_entity_chunks_avro` (splittable mask from per-record
    cluster keys) and :func:`plan_partitioned_game_stream` (splittable
    mask reconstructed from the allgathered run-length encoding — never
    materializing [n] key strings). Returns (specs, starts, skips)."""
    blocks = [
        (fi, bi, file_index[bi][0])
        for fi, file_index in enumerate(indexes)
        for bi in range(len(file_index))
    ]
    if not blocks:
        raise ValueError("no Avro blocks to stream")
    total = sum(b[2] for b in blocks)
    if len(splittable) != total + 1:
        raise ValueError(
            f"boundary mask covers {len(splittable) - 1} records but the "
            f"block index holds {total}"
        )
    # global record offset at each block start
    block_starts = np.concatenate(
        [[0], np.cumsum([b[2] for b in blocks])]
    ).astype(np.int64)
    specs: list[ChunkSpec] = []
    starts: list[int] = []
    skips: list[int] = []
    pos = 0
    while pos < total:
        end = min(pos + chunk_records, total)
        while end < total and not splittable[end]:
            end += 1
        # covering blocks: those whose record ranges intersect [pos, end)
        first = int(np.searchsorted(block_starts, pos, side="right") - 1)
        last = int(np.searchsorted(block_starts, end, side="left") - 1)
        runs: list[tuple[str, int, int]] = []
        cover = [(blocks[i][0], blocks[i][1]) for i in range(first, last + 1)]
        for fi, group in itertools.groupby(cover, key=lambda b: b[0]):
            bis = [bi for _, bi in group]
            run_start = prev = bis[0]
            for bi in bis[1:] + [None]:
                if bi is None or bi != prev + 1:
                    runs.append((files[fi], run_start, prev - run_start + 1))
                    run_start = bi
                prev = bi if bi is not None else prev
        specs.append(
            ChunkSpec(index=len(specs), num_records=end - pos,
                      runs=tuple(runs))
        )
        starts.append(int(pos))
        skips.append(int(pos - block_starts[first]))
        pos = end
    return specs, starts, skips


class GameAvroChunkSource:
    """Streams GAME chunks from Avro container files, each chunk decoded
    through the SAME per-record assembly as the in-core read
    (io/data_reader.records_to_game_dataset with globally-agreed index
    maps and entity vocabs — label/response fallback, None offset/weight
    defaults, metadataMap id extraction), so a streamed epoch consumes
    the identical numbers the full read would build. Entity-clustered via
    :func:`plan_entity_chunks_avro` when ``cluster_by`` is given
    (reference AvroDataReader.scala never materializes the full input on
    one machine either; this is the single-host accelerator equivalent).
    """

    sparse = False

    def __init__(
        self,
        files: Sequence[str],
        shard_configs: "Mapping[str, object]",
        index_maps: "Mapping[str, object]",
        *,
        chunk_records: int,
        random_effect_id_columns: Sequence[str] = (),
        entity_vocabs: "Mapping[str, np.ndarray] | None" = None,
        cluster_by: str | None = None,
        cluster_keys: np.ndarray | None = None,
        indexes=None,
        on_corrupt: str = "raise",
        dtype=np.float32,
        chunk_plan=None,
    ):
        self.files = [str(f) for f in files]
        self.shard_configs = dict(shard_configs)
        self.index_maps = dict(index_maps)
        self.re_columns = tuple(random_effect_id_columns)
        self.entity_vocabs = dict(entity_vocabs or {})
        self.on_corrupt = on_corrupt
        self.dtype = dtype
        #: dynamic per-source decode evidence (the partitioned bench's
        #: per-rank decoded-bytes metric; io_counters stays process-global)
        self.bytes_decoded = 0
        if chunk_plan is not None:
            # a precomputed plan (plan_partitioned_game_stream's rank-local
            # slice of the exchange-agreed global plan): specs already
            # re-indexed 0..k-1, record starts in the rank's LOCAL row
            # universe, skips into each chunk's first covering block
            plan_specs, plan_starts, plan_skips = chunk_plan
            self.specs = list(plan_specs)
            self.record_starts = [int(s) for s in plan_starts]
            self._skips = [int(s) for s in plan_skips]
            self.indexes = (
                indexes if indexes is not None
                else [
                    avro_io.scan_block_index(f, on_corrupt=on_corrupt)
                    for f in self.files
                ]
            )
        elif cluster_by is not None:
            if cluster_keys is None:
                raise ValueError(
                    "cluster_by needs cluster_keys (the per-record entity "
                    "keys collected by scan_game_stream's vocab pass)"
                )
            self.specs, self.indexes, self.record_starts, self._skips = (
                plan_entity_chunks_avro(
                    self.files, chunk_records, cluster_keys,
                    indexes=indexes, on_corrupt=on_corrupt,
                )
            )
        else:
            self.specs, self.indexes = plan_chunks(
                self.files, chunk_records, on_corrupt=on_corrupt,
                indexes=indexes,
            )
            self.record_starts = list(
                np.concatenate(
                    [[0], np.cumsum([s.num_records for s in self.specs])[:-1]]
                ).astype(int)
            ) if self.specs else []
            self._skips = [0] * len(self.specs)
        self.cluster_by = cluster_by
        self.chunk_rows = max((s.num_records for s in self.specs), default=0)
        self.dims = {
            shard: int(self.index_maps[shard].size)
            for shard in self.shard_configs
        }
        self._file_pos = {f: i for i, f in enumerate(self.files)}

    @property
    def num_chunks(self) -> int:
        return len(self.specs)

    @property
    def total_records(self) -> int:
        return int(sum(s.num_records for s in self.specs))

    def load(self, spec: ChunkSpec) -> GameChunk:
        from photon_ml_tpu.io.data_reader import records_to_game_dataset

        records: list = []
        payload_bytes = 0
        for path, start, count in spec.runs:
            index = self.indexes[self._file_pos[path]]
            payload_bytes += sum(sz for _, sz, _ in index[start:start + count])
            records.extend(
                avro_io.read_container_block_range(
                    path, start, count, index=index,
                    on_corrupt=self.on_corrupt,
                )
            )
        io_counters.record_bytes_decoded(payload_bytes)
        self.bytes_decoded += payload_bytes
        # entity-clustered plans slice the covering blocks' records to the
        # chunk's exact record range (boundary blocks decode for both
        # neighbors)
        skip = self._skips[spec.index]
        records = records[skip:skip + spec.num_records]
        result = records_to_game_dataset(
            records, self.shard_configs, self.index_maps,
            random_effect_id_columns=self.re_columns,
            entity_vocabs=self.entity_vocabs,
            dtype=self.dtype,
        )
        ds = result.dataset
        n = spec.num_records
        pad = self.chunk_rows - n

        def pad1(a, fill=0):
            a = np.asarray(a)
            if pad:
                a = np.concatenate(
                    [a, np.full((pad,) + a.shape[1:], fill, a.dtype)]
                )
            return a

        start = self.record_starts[spec.index]
        rows = np.arange(start, start + n, dtype=np.int64)
        return GameChunk(
            features={
                k: pad1(ds.feature_shards[k]) for k in self.shard_configs
            },
            labels=pad1(ds.labels),
            offsets=pad1(ds.offsets),
            weights=pad1(ds.weights),
            entity_idx={
                t: pad1(ds.entity_idx[t], fill=-1) for t in self.re_columns
            },
            rows=pad1(rows, fill=-1),
            num_records=n,
        )


def scan_game_stream(
    files: Sequence[str],
    shard_configs: "Mapping[str, object]",
    random_effect_id_columns: Sequence[str],
    *,
    cluster_by: str | None = None,
    on_corrupt: str = "raise",
    dtype=np.float32,
):
    """One streaming pass over the input collecting everything a GAME
    chunk plan needs — records decoded and DISCARDED (memory stays
    O(vocabulary + [n] scalars), the out-of-core requirement):

    - global feature index maps (same keyset+sort rule as the full read,
      io/data_reader.build_index_maps),
    - entity vocabs per RE column (np.unique of observed keys — bitwise
      the in-core build_game_dataset rule),
    - per-record keys of the ``cluster_by`` column (the entity-clustered
      chunk planner's input), the per-file block indexes, and
    - the [n] per-sample SCALARS (labels/offsets/weights with the exact
      records_to_game_dataset defaults, plus per-RE-column entity
      indices into the vocabs) — so the streamed GAME program never has
      to re-decode the whole input just to collect them.

    Returns ``(index_maps, entity_vocabs, cluster_keys, indexes,
    scalars)``; ``scalars`` feeds ``StreamingGameProgram(scalars=...)``.
    """
    from photon_ml_tpu.io.data_reader import (
        META_DATA_MAP,
        OFFSET,
        RESPONSE,
        WEIGHT,
        build_index_maps,
    )

    indexes = [
        avro_io.scan_block_index(f, on_corrupt=on_corrupt) for f in files
    ]
    re_cols = tuple(random_effect_id_columns)
    keys: dict[str, list[str]] = {c: [] for c in re_cols}
    cluster: list[str] = []
    labels: list[float] = []
    offsets: list[float] = []
    weights: list[float] = []

    def records():
        for f in files:
            for record in avro_io.read_container(f, on_corrupt=on_corrupt):
                label = record.get("label", record.get(RESPONSE))
                if label is None:
                    raise ValueError(
                        "record has neither 'label' nor 'response'"
                    )
                labels.append(float(label))
                offset = record.get(OFFSET)
                offsets.append(0.0 if offset is None else float(offset))
                weight = record.get(WEIGHT)
                weights.append(1.0 if weight is None else float(weight))
                meta = record.get(META_DATA_MAP) or {}
                for c in re_cols:
                    value = meta.get(c, record.get(c))
                    keys[c].append("" if value is None else str(value))
                if cluster_by is not None:
                    value = meta.get(cluster_by, record.get(cluster_by))
                    cluster.append("" if value is None else str(value))
                yield record

    index_maps = build_index_maps(records(), shard_configs)
    vocabs = {c: np.unique(np.asarray(v).astype(str)) for c, v in keys.items()}
    cluster_keys = (
        np.asarray(cluster).astype(str) if cluster_by is not None else None
    )
    # vocab = np.unique(keys) is sorted with every key present, so
    # searchsorted IS the build_game_dataset index mapping
    entity_idx = {
        c: np.searchsorted(vocabs[c], np.asarray(v).astype(str)).astype(
            np.int32
        )
        for c, v in keys.items()
    }
    scalars = {
        "labels": np.asarray(labels, dtype=dtype),
        "offsets": np.asarray(offsets, dtype=dtype),
        "weights": np.asarray(weights, dtype=dtype),
        "entity_idx": entity_idx,
    }
    return index_maps, vocabs, cluster_keys, indexes, scalars


class DenseRecordAssembler:
    """TrainingExampleAvro record dicts -> one fixed-shape dense chunk.

    Mirrors ``io.data_reader.records_to_game_dataset``'s per-record
    semantics exactly (label/response fallback, None offset -> 0, None
    weight -> 1, name+term feature keys, duplicate (row, col) accumulation
    via np.add.at, intercept column) so a streamed epoch consumes the SAME
    numbers the in-core read would build — pinned by
    tests/test_streaming.py's bitwise chunk-identity test.
    """

    def __init__(self, index_map, shard_config, dtype=np.float32):
        self.index_map = index_map
        self.shard_config = shard_config
        self.dtype = dtype

    def __call__(self, records: list, chunk_rows: int) -> LabeledPointBatch:
        from photon_ml_tpu.io.data_reader import (
            OFFSET,
            RESPONSE,
            WEIGHT,
            _apply_intercept,
            _record_bags,
            _scatter_dense,
        )
        from photon_ml_tpu.io.index_map import feature_key

        n = len(records)
        labels = np.zeros((n,), np.float64)
        offsets = np.zeros((n,), np.float64)
        weights = np.ones((n,), np.float64)
        triples: list[tuple[int, int, float]] = []
        imap = self.index_map
        for i, record in enumerate(records):
            label = record.get("label", record.get(RESPONSE))
            if label is None:
                raise ValueError("record has neither 'label' nor 'response'")
            labels[i] = float(label)
            offset = record.get(OFFSET)
            offsets[i] = 0.0 if offset is None else float(offset)
            weight = record.get(WEIGHT)
            weights[i] = 1.0 if weight is None else float(weight)
            bags = _record_bags(record)
            for bag in self.shard_config.feature_bags:
                for feat in bags.get(bag, ()):
                    j = imap.get_index(
                        feature_key(feat["name"], feat.get("term") or "")
                    )
                    if j >= 0:
                        triples.append((i, j, float(feat["value"])))
        t = np.asarray(triples, dtype=np.float64) if triples else np.zeros((0, 3))
        x = _scatter_dense(n, imap.size, t[:, 0], t[:, 1], t[:, 2], self.dtype)
        if self.shard_config.has_intercept:
            _apply_intercept(x, imap, "features", {})
        return _pad_dense_chunk(
            x,
            labels.astype(self.dtype),
            offsets.astype(self.dtype),
            weights.astype(self.dtype),
            chunk_rows,
        )


def plan_chunks(
    files: Sequence[str],
    chunk_records: int,
    *,
    on_corrupt: str = "raise",
    indexes: "list[list[tuple[int, int, int]]] | None" = None,
    block_subset: "Sequence[tuple[int, int]] | None" = None,
) -> tuple[list[ChunkSpec], "list[list[tuple[int, int, int]]]"]:
    """Group contiguous container blocks into chunks of at most
    ``chunk_records`` records (a single over-budget block still forms its
    own chunk — blocks are the atomic decode unit). Costs one header
    decode + one seek per block (``avro.scan_block_index``), never a data
    read. ``block_subset``: optional (file_idx, block_idx) list — a rank's
    assignment from the partitioned planner; the epoch then streams only
    those blocks. Returns (specs, per-file block indexes) so loads skip
    the re-scan.
    """
    if chunk_records <= 0:
        raise ValueError(f"chunk_records must be positive, got {chunk_records}")
    if indexes is None:
        indexes = [
            avro_io.scan_block_index(f, on_corrupt=on_corrupt) for f in files
        ]
    if not any(len(ix) for ix in indexes):
        raise ValueError("no Avro blocks to stream")
    blocks = (
        list(block_subset)
        if block_subset is not None
        else [
            (fi, bi)
            for fi, file_index in enumerate(indexes)
            for bi in range(len(file_index))
        ]
    )
    specs: list[ChunkSpec] = []
    cur: list[tuple[int, int]] = []
    cur_records = 0

    def flush():
        nonlocal cur, cur_records
        if not cur:
            return
        runs: list[tuple[str, int, int]] = []
        for fi, group in itertools.groupby(cur, key=lambda b: b[0]):
            bis = [bi for _, bi in group]
            # split a file's blocks into contiguous runs (a gap — e.g. a
            # quarantined span or a partitioned subset — starts a new
            # seek range)
            run_start = prev = bis[0]
            for bi in bis[1:] + [None]:
                if bi is None or bi != prev + 1:
                    runs.append((files[fi], run_start, prev - run_start + 1))
                    run_start = bi
                prev = bi if bi is not None else prev
        specs.append(
            ChunkSpec(
                index=len(specs), num_records=cur_records, runs=tuple(runs)
            )
        )
        cur, cur_records = [], 0

    for fi, bi in blocks:
        n_rec = indexes[fi][bi][0]
        if cur and cur_records + n_rec > chunk_records:
            flush()
        cur.append((fi, bi))
        cur_records += n_rec
    flush()
    # an explicitly empty subset (a rank assigned no blocks) is a valid
    # zero-chunk plan — its epochs contribute zero to the cross-rank sum
    return specs, indexes


class AvroChunkSource(ChunkSource):
    """Streams chunks from Avro container files through a record
    assembler, decoding only each chunk's block ranges per load (the PR 2
    block planner's seek-to-payload reads)."""

    sparse = False

    def __init__(
        self,
        files: Sequence[str],
        assembler: Callable[[list, int], LabeledPointBatch],
        *,
        chunk_records: int,
        on_corrupt: str = "raise",
        indexes=None,
        block_subset=None,
        dim: int | None = None,
    ):
        self.files = [str(f) for f in files]
        self.assembler = assembler
        self.on_corrupt = on_corrupt
        self.specs, self.indexes = plan_chunks(
            self.files, chunk_records, on_corrupt=on_corrupt,
            indexes=indexes, block_subset=block_subset,
        )
        self.chunk_rows = max(
            (s.num_records for s in self.specs), default=0
        )
        if dim is not None:
            self.dim = int(dim)
        else:
            imap = getattr(assembler, "index_map", None)
            self.dim = int(imap.size) if imap is not None else 0
        self._file_pos = {f: i for i, f in enumerate(self.files)}

    def load(self, spec: ChunkSpec) -> LabeledPointBatch:
        records: list = []
        payload_bytes = 0
        for path, start, count in spec.runs:
            index = self.indexes[self._file_pos[path]]
            payload_bytes += sum(sz for _, sz, _ in index[start:start + count])
            records.extend(
                avro_io.read_container_block_range(
                    path, start, count, index=index,
                    on_corrupt=self.on_corrupt,
                )
            )
        io_counters.record_bytes_decoded(payload_bytes)
        return self.assembler(records, self.chunk_rows)


_END = object()


class ChunkPrefetcher:
    """One epoch's chunk iterator: double-buffered decode behind the
    consumer (prefetch=True) or inline (prefetch=False), with classified
    retry, bounded timeouts, and per-epoch overlap telemetry.

    Use as a context manager; iterating yields each chunk batch once, in
    plan order. ``close()`` (idempotent, called by ``__exit__``) stops the
    producer with a bounded join — abandoning an epoch mid-way (solver
    line-search rejection never does, but errors might) cannot leak a
    wedged thread.
    """

    def __init__(
        self,
        source: ChunkSource,
        *,
        prefetch: bool = True,
        depth: int = 1,
        retry_policy: RetryPolicy | None = None,
        chunk_timeout: float = DEFAULT_CHUNK_TIMEOUT,
    ):
        self.source = source
        self.prefetch = bool(prefetch)
        self.depth = max(1, int(depth))
        self.policy = retry_policy if retry_policy is not None else default_io_policy()
        self.chunk_timeout = float(chunk_timeout)
        self.decode_seconds = 0.0
        self.wait_seconds = 0.0
        self._queue: queue.Queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- producer -------------------------------------------------------------

    def _load_timed(self, spec: ChunkSpec):
        t0 = time.perf_counter()
        # the decode span runs in whichever thread loads (producer when
        # prefetching, consumer inline otherwise) — per-thread trace
        # buffers keep both readable in the timeline
        with tracing.span("io/decode_chunk", cat="stream",
                          chunk=spec.index, records=spec.num_records):
            batch = self.policy.call(
                self.source.load, spec,
                description=f"decode chunk {spec.index}",
            )
        dt = time.perf_counter() - t0
        self.decode_seconds += dt
        stream_counters.record_chunk_decode_ms(dt * 1e3)
        return batch

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _producer(self) -> None:
        for spec in self.source.specs:
            if self._stop.is_set():
                return
            try:
                batch = self._load_timed(spec)
            except Exception as e:
                # the retry policy already classified and retried what was
                # transient; forward the surviving failure to the consumer's
                # stack — a thread cannot re-raise usefully, and swallowing
                # it would hang the epoch (reviewed allowlist entry in
                # dev/lint_parity.py check 5)
                classify_exception(e)
                try:
                    e._chunk_spec = spec
                except AttributeError:
                    pass  # __slots__ exception types lose the attribution
                self._put((None, e))
                return
            if not self._put((spec, batch)):
                return
        self._put((None, _END))

    # -- consumer -------------------------------------------------------------

    def __enter__(self) -> "ChunkPrefetcher":
        if self.prefetch:
            self._thread = threading.Thread(
                target=self._producer, name="chunk-prefetch", daemon=True
            )
            self._thread.start()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            # drain so a blocked put can finish, then bounded join
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=JOIN_TIMEOUT)
            self._thread = None

    def _next_prefetched(self):
        deadline = time.perf_counter() + self.chunk_timeout
        t0 = time.perf_counter()
        # consumer-side queue wait: the complement of io/decode_chunk in
        # the overlap story (overlap ≈ 1 - wait/decode, the
        # stream/overlap_fraction gauge — the spans reproduce it)
        with tracing.span("io/chunk_wait", cat="stream"):
            while True:
                try:
                    item = self._queue.get(timeout=0.2)
                    self.wait_seconds += time.perf_counter() - t0
                    return item
                except queue.Empty:
                    if self._thread is not None and not self._thread.is_alive():
                        raise StreamDecodeError(
                            "prefetch thread died without forwarding a result"
                        ) from None
                    if time.perf_counter() > deadline:
                        raise StreamDecodeError(
                            f"no chunk arrived within "
                            f"{self.chunk_timeout:.0f}s (wedged decode?)"
                        ) from None

    def __iter__(self):
        if not self.prefetch:
            for spec in self.source.specs:
                try:
                    yield self._load_timed(spec)
                except Exception as e:
                    raise self._attributed(e, spec) from e
            self._finish_epoch()
            return
        while True:
            spec, item = self._next_prefetched()
            if item is _END:
                break
            if isinstance(item, BaseException):
                failed = self._failed_spec(item)
                raise self._attributed(item, failed) from item
            yield item
        self._finish_epoch()

    def _failed_spec(self, exc) -> ChunkSpec | None:
        return getattr(exc, "_chunk_spec", None)

    def _attributed(self, exc, spec: ChunkSpec | None):
        where = (
            f"chunk {spec.index} (records={spec.num_records}, "
            f"runs={list(spec.runs)})" if spec is not None else "a chunk"
        )
        return StreamDecodeError(
            f"streaming epoch failed decoding {where}: "
            f"{type(exc).__name__}: {exc}"
        )

    def _finish_epoch(self) -> None:
        stream_counters.set_chunks_per_epoch(self.source.num_chunks)
        if self.prefetch and self.decode_seconds > 0.0:
            hidden = max(0.0, self.decode_seconds - self.wait_seconds)
            stream_counters.set_overlap_fraction(hidden / self.decode_seconds)
        else:
            stream_counters.set_overlap_fraction(0.0)


def build_streaming_index_maps(
    files: Sequence[str],
    shard_configs: Mapping[str, object],
    *,
    on_corrupt: str = "raise",
):
    """Global feature index maps from one streaming pass over the input —
    records are decoded and DISCARDED (memory stays O(vocabulary), the
    out-of-core requirement), exactly the keyset+sort rule the full read
    applies (io.data_reader.build_index_maps)."""
    from photon_ml_tpu.io.data_reader import build_index_maps

    return build_index_maps(
        itertools.chain.from_iterable(
            avro_io.read_container(f, on_corrupt=on_corrupt) for f in files
        ),
        shard_configs,
    )


def plan_partitioned_stream(
    path,
    shard_configs: Mapping[str, object],
    *,
    exchange,
    chunk_records: int,
    on_corrupt: str = "raise",
    dtype=np.float32,
    tag: str = "stream",
):
    """The --partitioned-io × --streaming-chunks composition: each rank
    gets a chunk source over ITS contiguous block assignment, with
    globally consistent index maps agreed over the metadata exchange —
    the same assignment rule (size-balanced contiguous block runs,
    ``partitioned_reader.assign_contiguous``) and the same
    key-union/sort map agreement the partitioned full read applies, so
    rank plans are verified identical by fingerprint and every rank's
    prefetcher decodes ~1/P of the bytes.

    The rank-local vocab pass decodes ONLY this rank's blocks (discarding
    records); ONE allgather unions the key sets. Dense feature shards
    (the GLM driver's layout). Returns
    ``(source, index_maps, intercept_indices)``; train with
    ``estimators.train_glm_streaming(source, ..., exchange=exchange)`` so
    the per-epoch accumulators sum across ranks in rank order.
    """
    from photon_ml_tpu.io.data_reader import build_index_maps
    from photon_ml_tpu.io.index_map import INTERCEPT_KEY, IndexMap
    from photon_ml_tpu.io.partitioned_reader import (
        _local_keys,
        _plan_fingerprint,
        assign_contiguous,
    )

    files = avro_io.list_avro_files(path)
    sizes = [int(os.path.getsize(f)) for f in files]
    io_counters.set_input_bytes_total(int(sum(sizes)))
    indexes = [
        avro_io.scan_block_index(f, on_corrupt=on_corrupt) for f in files
    ]
    blocks = [
        (fi, bi, payload)
        for fi, file_index in enumerate(indexes)
        for bi, (_, payload, _) in enumerate(file_index)
    ]
    if not blocks:
        raise ValueError(f"no Avro blocks under {path!r}")
    ranges = assign_contiguous([b[2] for b in blocks], exchange.num_ranks)
    lo, hi = ranges[exchange.rank]
    my_blocks = [(fi, bi) for fi, bi, _ in blocks[lo:hi]]

    def my_records():
        for spec_fi, group in itertools.groupby(my_blocks, key=lambda b: b[0]):
            bis = [bi for _, bi in group]
            yield from avro_io.read_container_block_range(
                files[spec_fi], bis[0], len(bis), index=indexes[spec_fi],
                on_corrupt=on_corrupt,
            )

    local_maps = build_index_maps(my_records(), shard_configs)
    payload = {
        "fingerprint": _plan_fingerprint(
            files, sizes, "stream-blocks", ranges
        ),
        "keys": {
            shard: _local_keys(local_maps[shard], cfg)
            for shard, cfg in shard_configs.items()
        },
    }
    with tracing.span("partitioned/stream_plan_exchange", cat="partitioned",
                      tag=tag, rank=exchange.rank):
        gathered = exchange.allgather(f"stream_plan/{tag}", payload)
    fingerprints = {g["fingerprint"] for g in gathered}
    if len(fingerprints) != 1:
        raise RuntimeError(
            f"ranks disagree on the streaming block plan ({fingerprints}); "
            "the input listing must be identical on every rank"
        )
    index_maps: dict[str, IndexMap] = {}
    intercepts: dict[str, int] = {}
    for shard, cfg in shard_configs.items():
        union: set[str] = set()
        for g in gathered:
            union.update(g["keys"][shard])
        imap = IndexMap.from_keys(union, add_intercept=cfg.has_intercept)
        index_maps[shard] = imap
        if cfg.has_intercept:
            ii = imap.get_index(INTERCEPT_KEY)
            if ii >= 0:
                intercepts[shard] = ii
    shard = next(iter(shard_configs))
    source = AvroChunkSource(
        files,
        DenseRecordAssembler(index_maps[shard], shard_configs[shard], dtype),
        chunk_records=chunk_records,
        on_corrupt=on_corrupt,
        indexes=indexes,
        block_subset=my_blocks,
    )
    return source, index_maps, intercepts


@dataclasses.dataclass(frozen=True)
class GameStreamPartition:
    """The exchange-agreed multi-rank streamed-GAME plan: every field is
    IDENTICAL on every rank (a deterministic function of the allgathered
    payloads), so per-rank programs can fingerprint checkpoints, drive one
    global DuHL schedule, and map global chunk ids to their local slice
    without further coordination.

    ``chunk_ranges[rank]`` is the rank's [lo, hi) slice of GLOBAL chunk
    ids (whole chunks — hence whole entities — per rank);
    ``payload_bytes[rank]`` is the deduped covering-block payload a full
    pass over that slice decodes (the per-rank I/O evidence: strictly
    less than ``input_bytes`` whenever the plan actually partitions).
    """

    rank: int
    num_ranks: int
    num_chunks: int
    chunk_ranges: "tuple[tuple[int, int], ...]"
    chunk_rows: int
    total_records: int
    payload_bytes: "tuple[int, ...]"
    input_bytes: int
    fingerprint: str

    def chunk_range(self) -> "tuple[int, int]":
        return self.chunk_ranges[self.rank]


def plan_partitioned_game_stream(
    path,
    shard_configs: Mapping[str, object],
    random_effect_id_columns: Sequence[str],
    *,
    exchange,
    chunk_records: int,
    cluster_by: str,
    schedule_budget: "Mapping[str, object] | None" = None,
    on_corrupt: str = "raise",
    dtype=np.float32,
    tag: str = "stream_game",
):
    """The --partitioned-io × --streaming-chunks composition for GAME
    (ISSUE 17): entity-granular per-rank chunk assignments agreed over the
    metadata exchange, so one streamed-GAME job spans the fleet's disks.

    Each rank decodes ONLY a provisional contiguous block slice
    (``assign_contiguous`` over payload sizes, the PR 6 rule) collecting
    its feature keys, RE entity keys, and a run-length encoding of the
    ``cluster_by`` column — O(vocabulary + entities) metadata, never the
    [n] sample axis. ONE allgather unions the key sets and concatenates
    the cluster runs in rank order (boundary runs of the same entity
    merge), after which every rank deterministically rebuilds the SAME
    global entity-clustered chunk plan (:func:`plan_entity_chunks_avro`
    semantics, reconstructed from run boundaries) and assigns WHOLE
    chunks — hence whole entities — contiguously to ranks. The agreed
    plan fields (input fingerprint, chunk budget, cluster column, rank
    geometry, schedule budget) are compared FIELD-WISE across ranks; any
    disagreement fails fast naming the differing fields and their
    per-rank values — a run never trains on a silently-disagreed plan.

    Returns ``(source, index_maps, entity_vocabs, partition)``: a
    rank-local :class:`GameAvroChunkSource` over this rank's chunks (rows
    renumbered into the rank's LOCAL universe — the streamed program's
    scalars stay O(n_rank)), the globally-agreed feature index maps and
    entity vocabs, and the :class:`GameStreamPartition` every rank agrees
    on. Feed all four to ``StreamingGameProgram(..., exchange=exchange,
    partition=partition, num_entities={t: len(vocabs[t])})``.
    """
    from photon_ml_tpu.io.data_reader import META_DATA_MAP, build_index_maps
    from photon_ml_tpu.io.index_map import IndexMap
    from photon_ml_tpu.io.partitioned_reader import (
        _local_keys,
        _plan_fingerprint,
        assign_contiguous,
    )

    if cluster_by is None:
        raise ValueError(
            "plan_partitioned_game_stream needs cluster_by (the RE type "
            "whose entities define chunk grouping) — a multi-rank streamed "
            "GAME run without entity clustering would split entities "
            "across ranks"
        )
    re_cols = tuple(random_effect_id_columns)
    files = avro_io.list_avro_files(path)
    sizes = [int(os.path.getsize(f)) for f in files]
    io_counters.set_input_bytes_total(int(sum(sizes)))
    indexes = [
        avro_io.scan_block_index(f, on_corrupt=on_corrupt) for f in files
    ]
    blocks = [
        (fi, bi, payload)
        for fi, file_index in enumerate(indexes)
        for bi, (_, payload, _) in enumerate(file_index)
    ]
    if not blocks:
        raise ValueError(f"no Avro blocks under {path!r}")
    ranges = assign_contiguous([b[2] for b in blocks], exchange.num_ranks)
    lo, hi = ranges[exchange.rank]
    my_blocks = [(fi, bi) for fi, bi, _ in blocks[lo:hi]]

    re_keys: "dict[str, set]" = {c: set() for c in re_cols}
    cluster_runs: "list[list]" = []  # [key, count] run-length pairs
    scan_bytes = 0

    def my_records():
        for spec_fi, group in itertools.groupby(my_blocks, key=lambda b: b[0]):
            bis = [bi for _, bi in group]
            for record in avro_io.read_container_block_range(
                files[spec_fi], bis[0], len(bis), index=indexes[spec_fi],
                on_corrupt=on_corrupt,
            ):
                meta = record.get(META_DATA_MAP) or {}
                for c in re_cols:
                    value = meta.get(c, record.get(c))
                    re_keys[c].add("" if value is None else str(value))
                value = meta.get(cluster_by, record.get(cluster_by))
                key = "" if value is None else str(value)
                if cluster_runs and cluster_runs[-1][0] == key:
                    cluster_runs[-1][1] += 1
                else:
                    cluster_runs.append([key, 1])
                yield record

    local_maps = build_index_maps(my_records(), shard_configs)
    scan_bytes = sum(
        indexes[fi][bi][1] for fi, bi in my_blocks
    )
    budget = (
        None if schedule_budget is None
        else {k: schedule_budget[k] for k in sorted(schedule_budget)}
    )
    plan_fields = {
        "input": _plan_fingerprint(files, sizes, "stream-game-blocks",
                                   ranges),
        "chunk_records": int(chunk_records),
        "cluster_by": str(cluster_by),
        "re_columns": list(re_cols),
        "num_ranks": int(exchange.num_ranks),
        "schedule": budget,
    }
    payload = {
        "plan": plan_fields,
        "keys": {
            shard: _local_keys(local_maps[shard], cfg)
            for shard, cfg in shard_configs.items()
        },
        "entities": {c: sorted(re_keys[c]) for c in re_cols},
        "cluster_runs": cluster_runs,
    }
    with tracing.span("partitioned/game_stream_plan_exchange",
                      cat="partitioned", tag=tag, rank=exchange.rank):
        gathered = exchange.allgather(f"stream_game_plan/{tag}", payload)
    diffs = []
    fields = sorted(set().union(*[set(g["plan"]) for g in gathered]))
    for field in fields:
        values = [g["plan"].get(field) for g in gathered]
        if any(v != values[0] for v in values[1:]):
            diffs.append(
                f"{field}: " + ", ".join(
                    f"rank{r}={v!r}" for r, v in enumerate(values)
                )
            )
    if diffs:
        raise RuntimeError(
            "ranks disagree on the partitioned GAME stream plan — refusing "
            "to train on a silently-disagreed plan; differing fields: "
            + "; ".join(diffs)
        )

    index_maps: "dict[str, IndexMap]" = {}
    for shard, cfg in shard_configs.items():
        union: "set[str]" = set()
        for g in gathered:
            union.update(g["keys"][shard])
        index_maps[shard] = IndexMap.from_keys(
            union, add_intercept=cfg.has_intercept
        )
    vocabs = {
        c: np.unique(
            np.asarray(
                sorted(set().union(*[set(g["entities"][c]) for g in gathered]))
            ).astype(str)
        )
        for c in re_cols
    }

    # global cluster runs: rank-order concatenation, merging boundary runs
    # of the same entity (an entity spanning a provisional block boundary
    # must still land in ONE chunk)
    run_keys: "list[str]" = []
    run_counts: "list[int]" = []
    for g in gathered:
        for key, count in g["cluster_runs"]:
            if run_keys and run_keys[-1] == key:
                run_counts[-1] += int(count)
            else:
                run_keys.append(key)
                run_counts.append(int(count))
    total = int(sum(run_counts))
    index_total = sum(n for file_index in indexes for (n, _, _) in file_index)
    if total != index_total:
        raise RuntimeError(
            f"rank-local scans cover {total} records but the block index "
            f"holds {index_total} — the input changed between the block "
            "scan and the key scan; re-run against a quiesced input"
        )
    splittable = np.zeros(total + 1, dtype=bool)
    splittable[0] = True
    splittable[total] = True
    if run_counts:
        ends = np.cumsum(np.asarray(run_counts, dtype=np.int64))
        splittable[ends[:-1]] = True
    if chunk_records <= 0:
        raise ValueError(f"chunk_records must be positive, got {chunk_records}")
    specs, starts, skips = _entity_chunks_over_blocks(
        files, indexes, chunk_records, splittable
    )
    chunk_ranges = assign_contiguous(
        [s.num_records for s in specs], exchange.num_ranks
    )
    empty = [r for r, (clo, chi) in enumerate(chunk_ranges) if chi <= clo]
    if empty:
        raise ValueError(
            f"the entity-clustered plan has {len(specs)} chunks for "
            f"{exchange.num_ranks} ranks — ranks {empty} would stream "
            "nothing; use a smaller --streaming-chunks budget (more "
            "chunks) or fewer ranks"
        )

    file_pos = {f: i for i, f in enumerate(files)}

    def rank_payload(clo: int, chi: int) -> int:
        cover: "set[tuple[int, int]]" = set()
        for s in specs[clo:chi]:
            for run_path, start, count in s.runs:
                fi = file_pos[run_path]
                cover.update((fi, bi) for bi in range(start, start + count))
        return int(sum(indexes[fi][bi][1] for fi, bi in cover))

    payload_bytes = tuple(rank_payload(clo, chi) for clo, chi in chunk_ranges)
    fingerprint = hashlib.sha256(
        json.dumps(
            [plan_fields, starts, [list(r) for r in chunk_ranges]],
            sort_keys=True,
        ).encode()
    ).hexdigest()[:16]
    partition = GameStreamPartition(
        rank=int(exchange.rank),
        num_ranks=int(exchange.num_ranks),
        num_chunks=len(specs),
        chunk_ranges=tuple((int(a), int(b)) for a, b in chunk_ranges),
        chunk_rows=max(s.num_records for s in specs),
        total_records=total,
        payload_bytes=payload_bytes,
        input_bytes=int(sum(sizes)),
        fingerprint=fingerprint,
    )
    clo, chi = chunk_ranges[exchange.rank]
    local_specs = [
        dataclasses.replace(s, index=i)
        for i, s in enumerate(specs[clo:chi])
    ]
    base = starts[clo]
    local_starts = [starts[c] - base for c in range(clo, chi)]
    local_skips = [skips[c] for c in range(clo, chi)]
    source = GameAvroChunkSource(
        files, shard_configs, index_maps,
        chunk_records=chunk_records,
        random_effect_id_columns=re_cols,
        entity_vocabs=vocabs,
        cluster_by=cluster_by,
        indexes=indexes,
        on_corrupt=on_corrupt,
        dtype=dtype,
        chunk_plan=(local_specs, local_starts, local_skips),
    )
    source.scan_bytes = scan_bytes
    return source, index_maps, vocabs, partition
