"""GAME model persistence in the reference's on-disk layout.

Reference parity: photon-client data/avro/ModelProcessingUtils.scala —
save (:75-128) / load (:141-254) of:

    <dir>/model-metadata.json                      {"modelType": ..., ...}
    <dir>/fixed-effect/<name>/id-info              [featureShardId]
    <dir>/fixed-effect/<name>/coefficients/*.avro  BayesianLinearModelAvro
    <dir>/random-effect/<name>/id-info             [reType, featureShardId]
    <dir>/random-effect/<name>/coefficients/*.avro one record per entity

plus the text model writer (photon-client util/IOUtils writeModelsInText),
the feature-stats writer (:515-586, FeatureSummarizationResultAvro), and the
score writer (ScoreProcessingUtils.scala, ScoringResultAvro). A model saved
by this module is directory-compatible with one saved by the reference.
"""

from __future__ import annotations

import io
import json
import logging
import os
from typing import Iterable, Mapping

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import photon_schemas as schemas
from photon_ml_tpu.io.index_map import IndexMap, feature_key, split_feature_key
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.models.matrix_factorization import MatrixFactorizationModel
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.types import TaskType

logger = logging.getLogger(__name__)

FIXED_EFFECT = "fixed-effect"
RANDOM_EFFECT = "random-effect"
MATRIX_FACTORIZATION = "matrix-factorization"
ID_INFO = "id-info"
COEFFICIENTS = "coefficients"
ROW_LATENT_FACTORS = "row-latent-factors"
COL_LATENT_FACTORS = "col-latent-factors"
METADATA_FILE = "model-metadata.json"

#: Default sparsity threshold below which coefficients are not persisted
#: (reference VectorUtils.DEFAULT_SPARSITY_THRESHOLD).
DEFAULT_SPARSITY_THRESHOLD = 1e-4

#: Random-effect coordinates whose feature space exceeds this load as
#: compact per-entity tables (the ONE default shared by the library loaders
#: and both CLI drivers — keep them from drifting).
DEFAULT_COMPACT_RE_THRESHOLD = 1_000_000

#: JVM class names used in the modelClass field, for interchange with the
#: reference's loader (supervised/model hierarchy).
_MODEL_CLASS = {
    TaskType.LOGISTIC_REGRESSION:
        "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel",
    TaskType.LINEAR_REGRESSION:
        "com.linkedin.photon.ml.supervised.regression.LinearRegressionModel",
    TaskType.POISSON_REGRESSION:
        "com.linkedin.photon.ml.supervised.regression.PoissonRegressionModel",
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        "com.linkedin.photon.ml.supervised.classification.SmoothedHingeLossLinearSVMModel",
}
_CLASS_TO_TASK = {v: k for k, v in _MODEL_CLASS.items()}


def _coefficients_to_name_term_values(
    means: np.ndarray, index_map: IndexMap, threshold: float
) -> list[dict]:
    out = []
    for j, v in enumerate(means):
        if abs(v) >= threshold or threshold == 0.0:
            key = index_map.get_feature_name(j)
            if key is None:
                continue
            name, term = split_feature_key(key)
            out.append({"name": name, "term": term, "value": float(v)})
    return out


def _compact_row_to_record(
    model_id: str,
    values: np.ndarray,  # [K]
    cols: np.ndarray,  # [K] global columns, pad = dim
    var_row: np.ndarray | None,
    task,
    index_map: IndexMap,
    threshold: float,
    dim: int,
) -> dict:
    """One compact (giant-d_re) entity row as the standard per-feature
    name-term-value record — on disk it matches a dense row exactly."""
    def ntv(vals: np.ndarray, thr: float) -> list[dict]:
        out = []
        for j, v in zip(cols.tolist(), np.asarray(vals).tolist()):
            if j >= dim or (thr != 0.0 and abs(v) < thr):
                continue
            key = index_map.get_feature_name(int(j))
            if key is None:
                continue
            name, term = split_feature_key(key)
            out.append({"name": name, "term": term, "value": float(v)})
        return out

    return {
        "modelId": model_id,
        "modelClass": _MODEL_CLASS.get(task),
        "means": ntv(values, threshold),
        "variances": None if var_row is None else ntv(var_row, 0.0),
        "lossFunction": None,
    }


def _glm_to_record(
    model_id: str,
    glm: GeneralizedLinearModel,
    index_map: IndexMap,
    threshold: float,
) -> dict:
    means = np.asarray(glm.coefficients.means)
    record = {
        "modelId": model_id,
        "modelClass": _MODEL_CLASS.get(glm.task),
        "means": _coefficients_to_name_term_values(means, index_map, threshold),
        "variances": None,
        "lossFunction": None,
    }
    if glm.coefficients.variances is not None:
        record["variances"] = _coefficients_to_name_term_values(
            np.asarray(glm.coefficients.variances), index_map, 0.0
        )
    return record


def _coordinate_dirs(base: str) -> list[str]:
    """Coordinate subdirectory names under a fixed-effect/random-effect/
    matrix-factorization level, skipping stray files and Spark/OS markers
    (_SUCCESS, .crc, .DS_Store) that a reference-written directory may hold."""
    return sorted(
        name
        for name in os.listdir(base)
        if os.path.isdir(os.path.join(base, name))
        and not name.startswith(("_", "."))
    )


def _read_id_info(base: str, n_lines: int) -> list[str]:
    """Read ``<base>/id-info`` and require at least ``n_lines`` lines,
    raising an error that names the malformed coordinate directory."""
    with open(os.path.join(base, ID_INFO)) as f:
        lines = f.read().strip().splitlines()
    if len(lines) < n_lines:
        raise ValueError(
            f"malformed id-info in '{base}': expected at least {n_lines} "
            f"line(s), got {len(lines)}"
        )
    return lines


def _has_part_files(directory: str) -> bool:
    """True if the directory holds at least one .avro part file (Spark may
    leave empty dirs with only _SUCCESS markers for untrained coordinates).
    Same filter as avro.read_directory, so emptiness test and reader agree."""
    return os.path.isdir(directory) and any(
        f.endswith(".avro") and not f.startswith(("_", "."))
        for f in os.listdir(directory)
    )


def _write_chunked(
    directory: str, schema: dict, records: Iterable[dict], per_file: int
) -> None:
    """Write records into part-NNNNN.avro files of at most per_file records
    (reference randomEffectModelFileLimit). Always emits at least one
    (possibly empty) part file so the directory stays readable."""
    it = iter(records)
    part = 0
    while True:
        chunk = []
        for record in it:
            chunk.append(record)
            if len(chunk) >= per_file:
                break
        if not chunk and part > 0:
            break
        avro_io.write_container(
            os.path.join(directory, f"part-{part:05d}.avro"), schema, chunk
        )
        part += 1
        if len(chunk) < per_file:
            break


def _load_compact_random_effect(
    records: list[dict], re_type: str, shard_id: str,
    index_map: IndexMap, task, dtype,
) -> RandomEffectModel:
    """Decode per-entity records into the compact [E, K] layout (sorted
    active global columns per entity; K = widest entity; pad = dim)."""
    dim = index_map.size
    keys = sorted(r["modelId"] for r in records)
    row = {k: i for i, k in enumerate(keys)}
    per_entity: list[tuple[np.ndarray, np.ndarray, np.ndarray | None]] = [
        (np.zeros(0, np.int64), np.zeros(0, dtype), None)
    ] * len(keys)
    model_task = task
    any_var = False
    for record in records:
        cols, vals = [], []
        for ntv in record["means"]:
            j = index_map.get_index(
                feature_key(ntv["name"], ntv.get("term") or "")
            )
            if j >= 0:
                cols.append(j)
                vals.append(ntv["value"])
        cols = np.asarray(cols, dtype=np.int64)
        vals = np.asarray(vals, dtype=dtype)
        order = np.argsort(cols)
        cols, vals = cols[order], vals[order]
        var = None
        if record.get("variances"):
            vmap = {}
            for ntv in record["variances"]:
                j = index_map.get_index(
                    feature_key(ntv["name"], ntv.get("term") or "")
                )
                if j >= 0:
                    vmap[j] = ntv["value"]
            # unlisted active columns default to 0.0, matching the dense
            # loader (_record_to_coefficients); NaN stays reserved for pad
            # slots / entities without the field
            var = np.asarray([vmap.get(int(c), 0.0) for c in cols], dtype)
            any_var = True
        per_entity[row[record["modelId"]]] = (cols, vals, var)
        model_task = _CLASS_TO_TASK.get(record.get("modelClass"), model_task)
    k_width = max((len(c) for c, _, _ in per_entity), default=0) or 1
    e = len(keys)
    active = np.full((e, k_width), dim, dtype=np.int32)
    table = np.zeros((e, k_width), dtype=dtype)
    var_table = np.full((e, k_width), np.nan, dtype=dtype) if any_var else None
    for i, (cols, vals, var) in enumerate(per_entity):
        active[i, : len(cols)] = cols
        table[i, : len(cols)] = vals
        if var is not None:
            var_table[i, : len(cols)] = var
    return RandomEffectModel(
        coefficients=jnp.asarray(table),
        entity_keys=np.asarray(keys),
        random_effect_type=re_type,
        feature_shard_id=shard_id,
        task=model_task,
        variances=None if var_table is None else jnp.asarray(var_table),
        active_cols=active,
        feature_dim=dim,
    )


def _record_to_coefficients(record: dict, index_map: IndexMap, dtype) -> Coefficients:
    d = index_map.size
    means = np.zeros((d,), dtype=dtype)
    # `or ""`: a null term must map to the empty term, matching
    # index_maps_from_model's key harvesting
    for ntv in record["means"]:
        j = index_map.get_index(feature_key(ntv["name"], ntv.get("term") or ""))
        if j >= 0:
            means[j] = ntv["value"]
    variances = None
    if record.get("variances"):
        variances = np.zeros((d,), dtype=dtype)
        for ntv in record["variances"]:
            j = index_map.get_index(feature_key(ntv["name"], ntv.get("term") or ""))
            if j >= 0:
                variances[j] = ntv["value"]
    return Coefficients(
        means=jnp.asarray(means),
        variances=None if variances is None else jnp.asarray(variances),
    )


def save_game_model(
    output_dir: str | os.PathLike,
    game_model: GameModel,
    index_maps: Mapping[str, IndexMap],
    *,
    optimization_configurations: dict | None = None,
    sparsity_threshold: float = DEFAULT_SPARSITY_THRESHOLD,
    random_effect_records_per_file: int = 65536,
) -> None:
    """Save a GAME model in the reference directory layout."""
    output_dir = str(output_dir)
    os.makedirs(output_dir, exist_ok=True)
    task = game_model.task
    with open(os.path.join(output_dir, METADATA_FILE), "w") as f:
        json.dump(
            {
                "modelType": task.value,
                "optimizationConfigurations": optimization_configurations or {},
            },
            f,
            indent=2,
        )

    for name, model in game_model.models.items():
        if isinstance(model, FixedEffectModel):
            base = os.path.join(output_dir, FIXED_EFFECT, name)
            os.makedirs(os.path.join(base, COEFFICIENTS), exist_ok=True)
            with open(os.path.join(base, ID_INFO), "w") as f:
                f.write(model.feature_shard_id + "\n")
            index_map = index_maps[model.feature_shard_id]
            avro_io.write_container(
                os.path.join(base, COEFFICIENTS, "part-00000.avro"),
                schemas.BAYESIAN_LINEAR_MODEL_AVRO,
                [_glm_to_record(name, model.glm, index_map, sparsity_threshold)],
            )
        elif isinstance(model, RandomEffectModel):
            base = os.path.join(output_dir, RANDOM_EFFECT, name)
            os.makedirs(os.path.join(base, COEFFICIENTS), exist_ok=True)
            with open(os.path.join(base, ID_INFO), "w") as f:
                f.write(model.random_effect_type + "\n")
                f.write(model.feature_shard_id + "\n")
            index_map = index_maps[model.feature_shard_id]
            table = np.asarray(model.coefficients)
            var_table = (
                np.asarray(model.variances) if model.variances is not None else None
            )
            keys = [str(k) for k in np.asarray(model.entity_keys).tolist()]
            active_cols = (
                np.asarray(model.active_cols)
                if model.active_cols is not None else None
            )

            def records() -> Iterable[dict]:
                for i, key in enumerate(keys):
                    # NaN rows mark "no variance computed" for this entity
                    # (e.g. below active_data_lower_bound) — drop the field
                    # rather than persist a false number. Compact rows check
                    # only the ACTIVE slots (their pad slots are NaN by
                    # construction and are never written to disk anyway).
                    var_row = None
                    if var_table is not None:
                        if active_cols is not None:
                            live = active_cols[i] < model.dim
                            finite = bool(
                                np.all(np.isfinite(var_table[i][live]))
                            ) if live.any() else False
                        else:
                            finite = bool(np.all(np.isfinite(var_table[i])))
                        if finite:
                            var_row = var_table[i]
                    if active_cols is not None:
                        # compact rows: table slot j is GLOBAL column
                        # active_cols[i, j]; the wire format is already
                        # per-feature name-term-value, so compact and dense
                        # models are indistinguishable on disk
                        yield _compact_row_to_record(
                            key, table[i], active_cols[i], var_row,
                            model.task, index_map, sparsity_threshold,
                            model.dim,
                        )
                        continue
                    glm = GeneralizedLinearModel(
                        Coefficients(means=table[i], variances=var_row),
                        model.task,
                    )
                    yield _glm_to_record(key, glm, index_map, sparsity_threshold)

            _write_chunked(
                os.path.join(base, COEFFICIENTS),
                schemas.BAYESIAN_LINEAR_MODEL_AVRO,
                records(),
                random_effect_records_per_file,
            )
        elif isinstance(model, MatrixFactorizationModel):
            # LatentFactorAvro (the reference's declared-but-unimplemented MF
            # wire format, LatentFactorAvro.avsc): effectId + latentFactor.
            base = os.path.join(output_dir, MATRIX_FACTORIZATION, name)
            os.makedirs(base, exist_ok=True)
            with open(os.path.join(base, ID_INFO), "w") as f:
                f.write(model.row_effect_type + "\n")
                f.write(model.col_effect_type + "\n")
            for sub, factors, keys in (
                (ROW_LATENT_FACTORS, model.row_factors, model.row_keys),
                (COL_LATENT_FACTORS, model.col_factors, model.col_keys),
            ):
                table = np.asarray(factors)
                key_list = [str(k) for k in np.asarray(keys).tolist()]
                os.makedirs(os.path.join(base, sub), exist_ok=True)

                def lf_records() -> Iterable[dict]:
                    for i, key in enumerate(key_list):
                        yield {
                            "effectId": key,
                            "latentFactor": [float(v) for v in table[i]],
                        }

                _write_chunked(
                    os.path.join(base, sub),
                    schemas.LATENT_FACTOR_AVRO,
                    lf_records(),
                    random_effect_records_per_file,
                )
        else:
            raise TypeError(f"cannot save coordinate '{name}' of type {type(model)}")


def load_game_model(
    models_dir: str | os.PathLike,
    index_maps: Mapping[str, IndexMap] | None = None,
    *,
    coordinates_to_load: set[str] | None = None,
    dtype=np.float32,
    compact_random_effect_threshold: int = DEFAULT_COMPACT_RE_THRESHOLD,
) -> GameModel:
    """Load a GAME model saved in the reference layout.

    ``index_maps=None`` reconstructs per-shard index maps from the model's
    own coefficient records in the same pass (each part file is decoded
    exactly once; the keys come from the cached records rather than a
    second read) — the way to load a reference-written model whose index
    stores are JVM-only PalDB.
    """
    return load_game_model_and_index_maps(
        models_dir, index_maps,
        coordinates_to_load=coordinates_to_load, dtype=dtype,
        compact_random_effect_threshold=compact_random_effect_threshold,
    )[0]


def load_game_model_and_index_maps(
    models_dir: str | os.PathLike,
    index_maps: Mapping[str, IndexMap] | None = None,
    *,
    coordinates_to_load: set[str] | None = None,
    dtype=np.float32,
    compact_random_effect_threshold: int = DEFAULT_COMPACT_RE_THRESHOLD,
) -> tuple[GameModel, dict[str, IndexMap]]:
    """Like :func:`load_game_model` but also returns the index maps in use —
    callers that need the maps afterwards (e.g. to read scoring data in the
    model's feature space) avoid a second decode pass."""
    models_dir = str(models_dir)
    meta_path = os.path.join(models_dir, METADATA_FILE)
    task = TaskType.NONE
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        task = TaskType(meta.get("modelType", "NONE"))

    record_cache: dict[str, list[dict]] = {}

    def read_records(coeff_dir: str) -> list[dict]:
        if coeff_dir not in record_cache:
            record_cache[coeff_dir] = list(avro_io.read_directory(coeff_dir))
        return record_cache[coeff_dir]

    if index_maps is None:
        # single pass: decode every coordinate's records once (cached for
        # the table-filling loops below) and harvest per-shard feature keys
        index_maps = _harvest_index_maps(models_dir, read_records)

    models: dict[str, object] = {}

    fe_dir = os.path.join(models_dir, FIXED_EFFECT)
    if os.path.isdir(fe_dir):
        for name in _coordinate_dirs(fe_dir):
            if coordinates_to_load is not None and name not in coordinates_to_load:
                continue
            base = os.path.join(fe_dir, name)
            shard_id = _read_id_info(base, 1)[0]
            if shard_id not in index_maps:
                raise ValueError(
                    f"missing feature shard definition '{shard_id}' for coordinate '{name}'"
                )
            index_map = index_maps[shard_id]
            records = read_records(os.path.join(base, COEFFICIENTS))
            if len(records) != 1:
                raise ValueError(f"expected 1 fixed-effect record for '{name}', got {len(records)}")
            record = records[0]
            model_task = _CLASS_TO_TASK.get(record.get("modelClass"), task)
            glm = GeneralizedLinearModel(
                _record_to_coefficients(record, index_map, dtype), model_task
            )
            models[name] = FixedEffectModel(glm=glm, feature_shard_id=shard_id)

    re_dir = os.path.join(models_dir, RANDOM_EFFECT)
    if os.path.isdir(re_dir):
        for name in _coordinate_dirs(re_dir):
            if coordinates_to_load is not None and name not in coordinates_to_load:
                continue
            base = os.path.join(re_dir, name)
            lines = _read_id_info(base, 2)
            re_type, shard_id = lines[0], lines[1]
            if shard_id not in index_maps:
                raise ValueError(
                    f"missing feature shard definition '{shard_id}' for coordinate '{name}'"
                )
            index_map = index_maps[shard_id]
            coeff_dir = os.path.join(base, COEFFICIENTS)
            if not _has_part_files(coeff_dir):
                # a random-effect coordinate with no trained entities (seen
                # in reference fixtures): empty table, still scorable (every
                # entity is "unseen" and scores 0)
                logger.warning(
                    "random-effect coordinate '%s' has no coefficients "
                    "directory; loading as an empty (0-entity) model", name,
                )
                models[name] = RandomEffectModel(
                    coefficients=jnp.zeros((0, index_map.size), dtype=dtype),
                    entity_keys=np.asarray([], dtype=str),
                    random_effect_type=re_type,
                    feature_shard_id=shard_id,
                    task=task,
                )
                continue
            records = read_records(coeff_dir)
            if index_map.size > compact_random_effect_threshold:
                # giant-d_re coordinate: never materialize [E, dim] — load
                # straight into the compact [E, K] active-column layout
                models[name] = _load_compact_random_effect(
                    records, re_type, shard_id, index_map, task, dtype
                )
                continue
            keys = sorted(r["modelId"] for r in records)
            row = {k: i for i, k in enumerate(keys)}
            table = np.zeros((len(keys), index_map.size), dtype=dtype)
            var_table = None
            model_task = task
            for record in records:
                coeffs = _record_to_coefficients(record, index_map, dtype)
                table[row[record["modelId"]]] = np.asarray(coeffs.means)
                if coeffs.variances is not None:
                    if var_table is None:
                        # NaN = "record carried no variances": keeps entities
                        # without the field distinguishable from genuinely
                        # tiny variances
                        var_table = np.full_like(table, np.nan)
                    var_table[row[record["modelId"]]] = np.asarray(coeffs.variances)
                model_task = _CLASS_TO_TASK.get(record.get("modelClass"), model_task)
            models[name] = RandomEffectModel(
                coefficients=jnp.asarray(table),
                entity_keys=np.asarray(keys),
                random_effect_type=re_type,
                feature_shard_id=shard_id,
                task=model_task,
                variances=None if var_table is None else jnp.asarray(var_table),
            )

    mf_dir = os.path.join(models_dir, MATRIX_FACTORIZATION)
    if os.path.isdir(mf_dir):
        for name in _coordinate_dirs(mf_dir):
            if coordinates_to_load is not None and name not in coordinates_to_load:
                continue
            base = os.path.join(mf_dir, name)
            lines = _read_id_info(base, 2)
            row_type, col_type = lines[0], lines[1]

            def read_factors(sub: str) -> tuple[np.ndarray, np.ndarray]:
                recs = list(avro_io.read_directory(os.path.join(base, sub)))
                keys = sorted(r["effectId"] for r in recs)
                row_of = {k: i for i, k in enumerate(keys)}
                k_dim = len(recs[0]["latentFactor"]) if recs else 0
                table = np.zeros((len(keys), k_dim), dtype=dtype)
                for r in recs:
                    table[row_of[r["effectId"]]] = r["latentFactor"]
                return table, np.asarray(keys)

            row_table, row_keys = read_factors(ROW_LATENT_FACTORS)
            col_table, col_keys = read_factors(COL_LATENT_FACTORS)
            models[name] = MatrixFactorizationModel(
                row_factors=jnp.asarray(row_table),
                col_factors=jnp.asarray(col_table),
                row_effect_type=row_type,
                col_effect_type=col_type,
                row_keys=row_keys,
                col_keys=col_keys,
                task=task,
            )

    if not models:
        raise ValueError(f"No models could be loaded from given path: {models_dir}")
    return GameModel(models=models), dict(index_maps)


def _harvest_index_maps(models_dir: str, read_records) -> dict[str, IndexMap]:
    """Per-shard index maps from a model's own coefficient records
    (``read_records(coeff_dir) -> list[dict]`` supplies/caches decoding)."""
    keys_per_shard: dict[str, set[str]] = {}

    def scan(base: str, shard_line: int) -> None:
        if not os.path.isdir(base):
            return
        for name in _coordinate_dirs(base):
            sub = os.path.join(base, name)
            shard_id = _read_id_info(sub, shard_line + 1)[shard_line]
            keys = keys_per_shard.setdefault(shard_id, set())
            coeff_dir = os.path.join(sub, COEFFICIENTS)
            if not _has_part_files(coeff_dir):
                continue  # empty coordinate (seen in reference fixtures)
            for record in read_records(coeff_dir):
                for field in ("means", "variances"):
                    for ntv in record.get(field) or ():
                        keys.add(feature_key(ntv["name"], ntv.get("term") or ""))

    scan(os.path.join(models_dir, FIXED_EFFECT), 0)
    scan(os.path.join(models_dir, RANDOM_EFFECT), 1)
    return {
        shard: IndexMap.from_keys(keys, add_intercept=False)
        for shard, keys in keys_per_shard.items()
    }


def index_maps_from_model(
    models_dir: str | os.PathLike,
) -> dict[str, IndexMap]:
    """Reconstruct per-shard index maps from a saved model's own coefficient
    records (name/term keys).

    The reference persists its index maps as PalDB stores, which only the
    JVM can read; the model files themselves carry every feature key, so a
    reference-written model directory becomes loadable without its stores.
    Column order follows IndexMap.from_keys (sorted), which both loaders
    use consistently. (``load_game_model(dir)`` with no maps does this in
    the same decode pass as the load itself.)
    """
    return _harvest_index_maps(
        str(models_dir), lambda d: avro_io.read_directory(d)
    )


def write_glm_text(
    path: str | os.PathLike,
    models: Mapping[float, GeneralizedLinearModel],
    index_map: IndexMap,
) -> None:
    """Per-λ text model dump (reference IOUtils.writeModelsInText: one file
    per regularization weight, 'name\\tterm\\tvalue' lines)."""
    os.makedirs(path, exist_ok=True)
    for lam, glm in models.items():
        means = np.asarray(glm.coefficients.means)
        with open(os.path.join(str(path), f"{lam}.txt"), "w", encoding="utf-8") as f:
            for j in np.argsort(-np.abs(means)):
                key = index_map.get_feature_name(int(j))
                if key is None:
                    continue
                name, term = split_feature_key(key)
                f.write(f"{name}\t{term}\t{float(means[j])!r}\n")


def write_feature_stats(
    path: str | os.PathLike,
    stats: Mapping[str, np.ndarray],
    index_map: IndexMap,
) -> None:
    """Feature summary as FeatureSummarizationResultAvro (reference
    ModelProcessingUtils.writeBasicStatistics:515-586)."""
    metrics_per_feature = {}
    d = index_map.size
    for metric, values in stats.items():
        arr = np.asarray(values)
        if arr.ndim == 1 and arr.shape[0] == d:
            metrics_per_feature[metric] = arr

    def records():
        for j in range(d):
            key = index_map.get_feature_name(j)
            if key is None:
                continue
            name, term = split_feature_key(key)
            yield {
                "featureName": name,
                "featureTerm": term,
                "metrics": {m: float(v[j]) for m, v in metrics_per_feature.items()},
            }

    os.makedirs(os.path.dirname(str(path)) or ".", exist_ok=True)
    avro_io.write_container(path, schemas.FEATURE_SUMMARIZATION_RESULT_AVRO, records())


def write_scores(
    path: str | os.PathLike,
    scores: np.ndarray,
    *,
    model_id: str = "",
    uids: np.ndarray | None = None,
    labels: np.ndarray | None = None,
    weights: np.ndarray | None = None,
    records_per_file: int | None = None,
) -> None:
    """Scored-item output as ScoringResultAvro (reference
    ScoreProcessingUtils.saveScoredItemsToHDFS).

    ``records_per_file``: when set, ``path`` is treated as a directory and
    scores split into part-NNNNN.avro files (the reference's partitioned
    score output)."""
    n = len(scores)

    def records():
        for i in range(n):
            yield {
                "uid": None if uids is None else str(uids[i]),
                "label": None if labels is None else float(labels[i]),
                "modelId": model_id,
                "predictionScore": float(scores[i]),
                "weight": None if weights is None else float(weights[i]),
                "metadataMap": None,
            }

    encoded = _encode_score_blocks(
        scores, model_id, uids, labels, weights
    )
    if records_per_file is not None:
        os.makedirs(str(path), exist_ok=True)
        if encoded is not None:
            for part, lo in enumerate(range(0, n, records_per_file)):
                chunk = encoded[lo:lo + records_per_file]
                avro_io.write_container_blocks(
                    os.path.join(str(path), f"part-{part:05d}.avro"),
                    schemas.SCORING_RESULT_AVRO,
                    [(len(chunk), chunk.tobytes())],
                )
            if n == 0:  # keep the directory readable, like _write_chunked
                avro_io.write_container(
                    os.path.join(str(path), "part-00000.avro"),
                    schemas.SCORING_RESULT_AVRO, [],
                )
            return
        _write_chunked(
            str(path), schemas.SCORING_RESULT_AVRO, records(), records_per_file
        )
        return
    os.makedirs(os.path.dirname(str(path)) or ".", exist_ok=True)
    if encoded is not None:
        # an empty block list still writes a valid header-only container
        avro_io.write_container_blocks(
            path, schemas.SCORING_RESULT_AVRO,
            [(n, encoded.tobytes())] if n else [],
        )
        return
    avro_io.write_container(path, schemas.SCORING_RESULT_AVRO, records())


def _encode_score_blocks(
    scores: np.ndarray,
    model_id: str,
    uids: np.ndarray | None,
    labels: np.ndarray | None,
    weights: np.ndarray | None,
):
    """Vectorized Avro-binary encoding of ScoringResultAvro records.

    The schema is fixed and flat, so the whole record stream assembles as
    numpy byte scatters (~20x the per-record BinaryEncoder — the write-side
    analogue of the native reader; pure numpy, no compiler needed). Returns
    a sliceable per-record object (numpy array of VOID rows is unsuitable
    because uid lengths vary, so this returns a `_RaggedBytes` with
    per-record boundaries), or None when the inputs are outside the fast
    subset (non-ASCII or >8 KB uids).
    """
    n = len(scores)
    if n == 0:
        return _RaggedBytes(np.zeros(0, np.uint8), np.zeros(1, np.int64))
    scores = np.ascontiguousarray(scores, dtype="<f8")

    # ---- uid segment (the only variable-width part)
    if uids is not None:
        u = np.asarray(uids)
        if u.dtype.kind in "iu" and (u >= 0).all():
            # vectorized decimal digits (numpy's int->str astype is the
            # profile's hot spot): RIGHT-aligned [n, maxlen] digit matrix.
            # Digit count via exact integer thresholds — float64 log10
            # overcounts just below powers of ten beyond 2^53
            pow10 = np.array([10 ** k for k in range(1, 19)], dtype=np.uint64)
            ulen = (
                np.searchsorted(pow10, u.astype(np.uint64), side="right") + 1
            ).astype(np.int64)
            width = int(ulen.max())
            ub_bytes = (
                (u[:, None] // 10 ** np.arange(width - 1, -1, -1, dtype=u.dtype))
                % 10
            ).astype(np.uint8) + ord("0")
            right_aligned = True
        elif u.dtype.kind == "S" or (
            u.dtype == object and any(isinstance(x, bytes) for x in u)
        ):
            return None  # str(bytes) renders the b'...' repr — generic's job
        else:
            ustr = u.astype("U") if u.dtype.kind != "U" else u
            try:
                ub = ustr.astype("S")  # ASCII-only fast encode
            except UnicodeEncodeError:
                return None
            ulen = np.char.str_len(ustr).astype(np.int64)
            ub_bytes = ub.view(np.uint8).reshape(n, -1)
            right_aligned = False
        if (ulen >= 8192).any():
            return None  # >2-byte varint lengths: generic writer's job
        two = ulen >= 64  # zigzag(len) needs 2 varint bytes
        uid_seg = 1 + 1 + two.astype(np.int64) + ulen  # tag + varint + bytes
    else:
        ulen = np.zeros(n, np.int64)
        two = np.zeros(n, bool)
        uid_seg = np.ones(n, np.int64)  # null tag only

    mid = model_id.encode("utf-8")
    buf = io.BytesIO()
    avro_io.write_long(buf, len(mid))
    mid_prefix = buf.getvalue() + mid
    tail = (
        (9 if labels is not None else 1)
        + len(mid_prefix) + 8
        + (9 if weights is not None else 1)
        + 1
    )
    sizes = uid_seg + tail
    starts = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(sizes, out=starts[1:])
    out = np.zeros(int(starts[-1]), dtype=np.uint8)

    # uid union tag + varint + bytes
    if uids is not None:
        out[starts[:-1]] = 2  # union branch 1 (string)
        z = ulen * 2  # zigzag
        out[starts[:-1] + 1] = np.where(two, (z & 0x7F) | 0x80, z)
        p2 = starts[:-1][two] + 2
        out[p2] = (ulen[two] * 2) >> 7
        # ragged scatter of the uid bytes
        width = ub_bytes.shape[1]
        if width:
            uid_start = starts[:-1] + 2 + two.astype(np.int64)
            total = int(ulen.sum())
            rows = np.repeat(np.arange(n), ulen)
            intra = np.arange(total) - np.repeat(np.cumsum(ulen) - ulen, ulen)
            src_col = intra + (width - ulen[rows] if right_aligned else 0)
            out[np.repeat(uid_start, ulen) + intra] = ub_bytes[rows, src_col]
    # fixed tail as one [n, tail] byte matrix
    tail_mat = np.zeros((n, tail), dtype=np.uint8)
    pos = 0
    if labels is not None:
        tail_mat[:, 0] = 2
        tail_mat[:, 1:9] = (
            np.ascontiguousarray(labels, "<f8").view(np.uint8).reshape(n, 8)
        )
        pos = 9
    else:
        pos = 1
    tail_mat[:, pos:pos + len(mid_prefix)] = np.frombuffer(mid_prefix, np.uint8)
    pos += len(mid_prefix)
    tail_mat[:, pos:pos + 8] = scores.view(np.uint8).reshape(n, 8)
    pos += 8
    if weights is not None:
        tail_mat[:, pos] = 2
        tail_mat[:, pos + 1:pos + 9] = (
            np.ascontiguousarray(weights, "<f8").view(np.uint8).reshape(n, 8)
        )
        pos += 9
    else:
        pos += 1
    # metadataMap null tag is the final zero byte — already zeroed
    tail_start = starts[1:] - tail
    out[tail_start[:, None] + np.arange(tail)] = tail_mat
    return _RaggedBytes(out, starts)


class _RaggedBytes:
    """Byte stream with per-record boundaries; slicing yields sub-streams
    (len() = record count, .tobytes() = the raw payload)."""

    def __init__(self, data: np.ndarray, starts: np.ndarray):
        self._data = data
        self._starts = starts

    def __len__(self) -> int:
        return len(self._starts) - 1

    def __getitem__(self, s: slice) -> "_RaggedBytes":
        lo, hi, step = s.indices(len(self))
        assert step == 1
        return _RaggedBytes(
            self._data[self._starts[lo]:self._starts[hi]],
            self._starts[lo:hi + 1] - self._starts[lo],
        )

    def tobytes(self) -> bytes:
        return self._data.tobytes()


def read_scores(path: str | os.PathLike) -> list[dict]:
    return list(avro_io.read_directory(path))
