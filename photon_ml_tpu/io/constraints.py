"""Coefficient box-constraint maps.

Reference parity: the legacy driver's constraint string (photon-client
io/deprecated/ConstraintMapKeys.scala, GLMSuite.createConstraintFeatureMap
:207-280, Params.constraintString) — a JSON list of maps with mandatory
``name``/``term`` and optional ``lowerBound``/``upperBound`` (missing bound
= ±inf). Wildcard semantics:

- name="*" and term="*": the bounds apply to every non-intercept feature and
  must be the only constraint given;
- term="*" with a concrete name: the bounds apply to every term of that
  name;
- wildcard in name alone is rejected.

Per-entry validation matches the reference: at least one finite bound, and
lower < upper. The output is a dense (lower[d], upper[d]) pair aligned to an
IndexMap, feeding the solvers' box projection (optim/optimizer.solve).
"""

from __future__ import annotations

import json
import logging

import numpy as np

from photon_ml_tpu.io.index_map import (
    INTERCEPT_KEY,
    IndexMap,
    feature_key,
    split_feature_key,
)

logger = logging.getLogger(__name__)

WILDCARD = "*"


def parse_constraint_maps(constraint_string: str) -> list[dict]:
    """Parse and validate the JSON constraint list (bounds defaulted)."""
    parsed = json.loads(constraint_string)
    if not isinstance(parsed, list):
        raise ValueError(
            f"constraint string must be a JSON list of maps, got {type(parsed).__name__}"
        )
    out = []
    for entry in parsed:
        if not isinstance(entry, dict) or "name" not in entry or "term" not in entry:
            raise ValueError(
                f"each constraint map needs 'name' and 'term' fields; got {entry!r}"
            )
        lower = float(entry.get("lowerBound", -np.inf))
        upper = float(entry.get("upperBound", np.inf))
        if not (np.isfinite(lower) or np.isfinite(upper)):
            raise ValueError(
                f"constraint for name={entry['name']!r} term={entry['term']!r} "
                "has neither bound finite"
            )
        if lower >= upper:
            raise ValueError(
                f"lower bound {lower} must be < upper bound {upper} for "
                f"name={entry['name']!r} term={entry['term']!r}"
            )
        out.append(
            {"name": str(entry["name"]), "term": str(entry["term"]),
             "lower": lower, "upper": upper}
        )
    return out


def build_bound_arrays(
    constraint_string: str,
    index_map: IndexMap,
    *,
    dtype=np.float64,
) -> tuple[np.ndarray, np.ndarray]:
    """Dense (lower[d], upper[d]) arrays from a constraint string."""
    entries = parse_constraint_maps(constraint_string)
    d = index_map.size
    lower = np.full((d,), -np.inf, dtype=dtype)
    upper = np.full((d,), np.inf, dtype=dtype)
    constrained: set[int] = set()

    def apply(j: int, lo: float, hi: float, name: str, term: str) -> None:
        if j in constrained:
            raise ValueError(
                f"conflicting constraints: feature name={name!r} term={term!r} "
                "was bounded more than once"
            )
        constrained.add(j)
        lower[j], upper[j] = lo, hi

    # one pass over the forward map (no reverse-lookup scans per entry)
    key_index = [(key, index_map[key]) for key in index_map]
    for entry in entries:
        name, term = entry["name"], entry["term"]
        if name == WILDCARD:
            if term != WILDCARD:
                raise ValueError(
                    "a wildcard feature name requires a wildcard term too"
                )
            if len(entries) > 1:
                raise ValueError(
                    "a full-wildcard constraint must be the only constraint"
                )
            for key, j in key_index:
                if key != INTERCEPT_KEY:
                    apply(j, entry["lower"], entry["upper"], name, term)
        elif term == WILDCARD:
            hits = [
                j for key, j in key_index
                if key != INTERCEPT_KEY and split_feature_key(key)[0] == name
            ]
            if not hits:
                logger.warning(
                    "constraint name=%r term=* matched no feature in the "
                    "index map — it will have no effect", name,
                )
            for j in hits:
                apply(j, entry["lower"], entry["upper"], name, term)
        else:
            j = index_map.get_index(feature_key(name, term))
            if j >= 0:
                apply(j, entry["lower"], entry["upper"], name, term)
            else:
                logger.warning(
                    "constraint for name=%r term=%r names a feature absent "
                    "from the index map — it will have no effect (typo?)",
                    name, term,
                )
    return lower, upper
