"""Partitioned host ingestion: each rank decodes ~1/P of the input bytes.

Reference parity: photon-client data/avro/AvroDataReader.scala:125-200 —
the reference reads Avro per PARTITION on executors (Spark hands each task
a split of the input files/blocks) and assembles per-partition rows; only
feature-index metadata is shared via the driver. The host periphery here
was the last full-read funnel: both CLI drivers called ``read_merged`` on
EVERY process and only then sharded, so a multi-host run multiplied the
full-input decode by the process count (at the measured ~54 MB/s native
rate, a 1 TB input costs hours *per rank* before step 1 — BASELINE.md).

This module gives each rank a deterministic, order-preserving slice:

- **Assignment**: the sorted part files split into P contiguous,
  size-balanced runs (every rank computes the identical plan from the
  identical listing; a fingerprint allgather verifies it). Inputs with
  fewer files than ranks split by container *blocks* instead — the block
  index costs one header decode + one seek per block to scan
  (avro.scan_block_index), never a data read.
- **Decode**: only the local assignment flows through the existing
  native/Python reader stack (``read_merged`` on the file subset, or the
  block-range record iterator) — the ~13x native columnar decoder keeps
  working per rank.
- **Consistency**: feature index maps and entity vocabularies are made
  globally consistent by ONE small metadata allgather (distinct feature
  keys; entity ids + counts) over the host-side coordination-service
  channel (parallel/multihost.MetadataExchange) — not by re-reading
  everything everywhere. ``IndexMap.from_keys`` sorts, so the union of
  per-rank key sets reproduces the full-read map exactly; local column
  indices are then remapped into the global space (a cheap column
  scatter of the already-assembled local blocks).
- **Layout**: every rank pads its local rows to the agreed common block
  length (zero-weight rows, the framework-wide padding contract), so the
  global sample axis is P equal blocks and each rank's block places
  directly as the local addressable shards of the global sharded arrays
  (parallel/multihost.assemble_partitioned).

Single-process (num_ranks == 1) delegates to ``read_merged`` unchanged —
this module is the ONE ingestion dispatcher the CLI drivers call
(dev/lint_parity.py bans direct ``read_merged`` calls in cli/).

Per-rank decode progress is observable: the ``io/partitioned/*`` telemetry
counters record bytes decoded vs the total input (telemetry/io_counters).
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import itertools
import json
import logging
import os
from typing import Mapping, Sequence

import numpy as np

from photon_ml_tpu.data.game_data import GameDataset, pad_game_dataset_to
from photon_ml_tpu.data.sparse_batch import SparseShard
from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io.data_reader import (
    FeatureShardConfiguration,
    ReadResult,
    build_index_maps,
    read_merged,
    records_to_game_dataset,
)
from photon_ml_tpu.io.index_map import INTERCEPT_KEY, IndexMap
from photon_ml_tpu.telemetry import io_counters, tracing

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class PartitionInfo:
    """Rank geometry of one partitioned read: the global sample axis is
    ``num_ranks`` blocks of ``block_rows`` rows; rank r's true rows are
    the first ``local_rows[r]`` of block r, the rest zero-weight padding."""

    rank: int
    num_ranks: int
    local_rows: tuple[int, ...]
    block_rows: int

    @property
    def global_rows(self) -> int:
        return self.num_ranks * self.block_rows

    @property
    def total_true_rows(self) -> int:
        return int(sum(self.local_rows))

    @property
    def base_row(self) -> int:
        return self.rank * self.block_rows

    @property
    def local_n(self) -> int:
        return int(self.local_rows[self.rank])

    def true_row_mask(self) -> np.ndarray:
        """[global_rows] bool: True on real rows, False on block padding."""
        mask = np.zeros(self.global_rows, dtype=bool)
        for r, n in enumerate(self.local_rows):
            mask[r * self.block_rows: r * self.block_rows + n] = True
        return mask


@dataclasses.dataclass
class PartitionedReadResult:
    """One rank's slice of a partitioned read.

    result: the LOCAL dataset (padded to ``partition.block_rows``) with
        GLOBALLY consistent index maps / entity vocabs / intercepts.
    entity_rank_presence: RE type -> [num_entities] int — on how many
        ranks each entity has samples. Entities spanning ranks make the
        rank-local random-effect view deviate from the full-read solve
        (data/game_data.build_random_effect_dataset_partitioned documents
        the semantics); entity-clustered inputs keep this at <= 1.
    """

    result: ReadResult
    partition: PartitionInfo
    mode: str  # "single" | "files" | "blocks"
    local_files: list[str]
    bytes_decoded: int
    input_bytes_total: int
    entity_rank_presence: dict[str, np.ndarray] = dataclasses.field(
        default_factory=dict
    )


def assign_contiguous(weights: Sequence[int], num_ranks: int) -> list[tuple[int, int]]:
    """Split items into ``num_ranks`` contiguous [lo, hi) runs with
    near-equal total weight: boundary r lands where the prefix sum first
    reaches r/P of the total. Contiguity is semantic, not cosmetic — it
    keeps the concatenation of rank slices in the full-read row order, so
    the partitioned global sample axis is a padded permutation-free image
    of the full read's."""
    weights = [max(int(w), 0) for w in weights]
    prefix = np.concatenate([[0], np.cumsum(weights, dtype=np.int64)])
    total = int(prefix[-1])
    bounds = [0]
    for r in range(1, num_ranks):
        target = total * r / num_ranks
        idx = int(np.searchsorted(prefix, target, side="left"))
        # boundary at whichever adjacent prefix sits closer to the target
        if idx > 0 and (
            idx > len(weights)
            or target - prefix[idx - 1] <= prefix[min(idx, len(weights))] - target
        ):
            idx -= 1
        bounds.append(min(max(idx, bounds[-1]), len(weights)))
    bounds.append(len(weights))
    return [(bounds[r], bounds[r + 1]) for r in range(num_ranks)]


def _list_input_files(path, fmt: str) -> list[str]:
    paths = [path] if isinstance(path, (str, os.PathLike)) else list(path)
    if fmt == "avro":
        files: list[str] = []
        for p in paths:
            files += avro_io.list_avro_files(p)
        return files
    raise ValueError(
        f"partitioned ingestion supports fmt='avro' (got {fmt!r}); "
        "LibSVM inputs read through the single-process path"
    )


def _local_keys(imap: IndexMap, cfg: FeatureShardConfiguration) -> list[str]:
    """The DATA feature keys of a locally built map: the synthetic
    intercept is stripped (each rank's map appends it; the global rebuild
    re-adds it once, reproducing the full-read map). A literal
    '(INTERCEPT)' feature key in the data is indistinguishable from the
    synthetic one here — that pathological case may order the intercept
    column differently from a full read."""
    keys = list(imap)
    if cfg.has_intercept:
        keys = [k for k in keys if k != INTERCEPT_KEY]
    return keys


def _remap_dense(x: np.ndarray, local_map: IndexMap,
                 global_map: IndexMap) -> np.ndarray:
    out = np.zeros((x.shape[0], global_map.size), dtype=x.dtype)
    if local_map.size:
        gidx = np.asarray(
            [global_map.get_index(local_map.get_feature_name(j))
             for j in range(local_map.size)],
            dtype=np.int64,
        )
        if (gidx < 0).any():
            raise ValueError("local feature key missing from the global map")
        out[:, gidx] = np.asarray(x)
    return out


def _remap_sparse(shard: SparseShard, local_map: IndexMap,
                  global_map: IndexMap) -> SparseShard:
    gidx = np.asarray(
        [global_map.get_index(local_map.get_feature_name(j))
         for j in range(local_map.size)],
        dtype=np.int64,
    )
    cols = np.asarray(shard.cols, dtype=np.int64)
    new_cols = gidx[cols] if len(cols) else cols
    return dataclasses.replace(
        shard, cols=new_cols, feature_dim=global_map.size,
        _device=None, _coalesced=None, _hybrid_cache=None,
    )


def _schema_lacks_uid(files: list[str]) -> bool:
    """True when the input records carry no uid field at all — the reader
    then auto-assigns ROW NUMBERS as unique ids, which are rank-local in a
    partitioned read and must be shifted to the global row space (the full
    read numbers 0..N-1; stable-id sampling and score-output uids depend
    on it). Decided from the FIRST file's schema so every rank agrees.
    A uid field that exists but holds null for some rows still falls back
    to local row numbers for those rows — a documented edge the metadata
    exchange cannot see; give such data real uids."""
    if not files:
        return False
    try:
        schema = avro_io.read_container_schema(files[0])
    except (avro_io.AvroError, OSError):
        return False
    fields = schema.get("fields", []) if isinstance(schema, dict) else []
    from photon_ml_tpu.io.data_reader import UID

    return not any(f.get("name") == UID for f in fields)


def _plan_fingerprint(files: list[str], sizes: list[int], mode: str,
                      ranges) -> str:
    blob = json.dumps(
        [[os.path.basename(f) for f in files], sizes, mode, list(ranges)]
    ).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def read_partitioned(
    path,
    shard_configs: Mapping[str, FeatureShardConfiguration],
    *,
    exchange=None,
    index_maps: Mapping[str, IndexMap] | None = None,
    random_effect_id_columns: Sequence[str] = (),
    evaluation_id_columns: Sequence[str] = (),
    entity_vocabs: Mapping[str, np.ndarray] | None = None,
    fmt: str = "avro",
    dtype=np.float32,
    pad_multiple: int = 1,
    tag: str = "read",
    on_corrupt: str = "raise",
) -> PartitionedReadResult:
    """Partition-aware ``read_merged``: decode only this rank's slice.

    on_corrupt="quarantine" (Avro only) skips-and-counts corrupt container
    blocks instead of failing the read (io/avro.py). In blocks mode the
    quarantining INDEX scan is the authoritative skip decision — every
    rank scans every file identically, so the plan (and its fingerprint)
    stays rank-consistent with corrupt spans excluded.

    exchange: parallel/multihost.MetadataExchange. ``None`` means DO NOT
    partition — the full read on this process, exactly as before (the
    drivers' non---partitioned-io paths and every single-process caller
    ride this default; partitioning is opt-in, so it must never engage
    just because the process happens to be in a multi-process run). Pass
    ``multihost.default_exchange()`` (or a specific transport) to
    partition. Every rank must then call with identical arguments — the
    metadata allgathers are collective. ``pad_multiple``: round the common
    per-rank block length up to this (callers pass the per-rank device
    count along the mesh "data" axis so device shards never cross rank
    blocks). ``tag`` namespaces the exchanges when one run reads several
    inputs (train/validation).

    num_ranks == 1 delegates to ``read_merged`` byte-for-byte — this is
    the one ingestion entry point CLI drivers use.
    """
    if exchange is None:
        from photon_ml_tpu.parallel.multihost import SingleProcessExchange

        exchange = SingleProcessExchange()
    rank, num_ranks = exchange.rank, exchange.num_ranks

    if num_ranks == 1:
        result = read_merged(
            path, shard_configs, index_maps=index_maps,
            random_effect_id_columns=random_effect_id_columns,
            evaluation_id_columns=evaluation_id_columns,
            entity_vocabs=entity_vocabs, fmt=fmt, dtype=dtype,
            on_corrupt=on_corrupt,
        )
        n = result.dataset.num_samples
        return PartitionedReadResult(
            result=result,
            partition=PartitionInfo(0, 1, (n,), n),
            mode="single",
            local_files=[],
            bytes_decoded=0,
            input_bytes_total=0,
        )

    files = _list_input_files(path, fmt)
    sizes = [os.path.getsize(f) for f in files]
    input_total = int(sum(sizes))
    io_counters.set_input_bytes_total(input_total)

    if len(files) >= num_ranks:
        mode = "files"
        ranges = assign_contiguous(sizes, num_ranks)
        lo, hi = ranges[rank]
        local_files = files[lo:hi]
        bytes_decoded = int(sum(sizes[lo:hi]))
        local = _read_local_files(
            local_files, shard_configs,
            index_maps=index_maps,
            random_effect_id_columns=random_effect_id_columns,
            evaluation_id_columns=evaluation_id_columns,
            entity_vocabs=entity_vocabs, fmt=fmt, dtype=dtype,
            on_corrupt=on_corrupt,
        )
    else:
        mode = "blocks"
        # few-large-files: split by container blocks. The index scan is
        # header + seeks only; every rank scans every file's index (cheap)
        # but decodes only its contiguous block run. Under quarantine the
        # scan validates framing and drops corrupt spans identically on
        # every rank (the plan fingerprint stays consistent).
        indexes = [
            avro_io.scan_block_index(f, on_corrupt=on_corrupt)
            for f in files
        ]
        blocks = []  # (file_idx, block_idx, payload_bytes)
        for fi, file_index in enumerate(indexes):
            for bi, (_, payload, _) in enumerate(file_index):
                blocks.append((fi, bi, payload))
        if not blocks:
            raise ValueError(f"no Avro blocks under {path!r}")
        ranges = assign_contiguous([b[2] for b in blocks], num_ranks)
        lo, hi = ranges[rank]
        my_blocks = blocks[lo:hi]
        bytes_decoded = int(sum(b[2] for b in my_blocks))
        local_files = sorted({files[b[0]] for b in my_blocks})

        def local_records():
            for fi, group in itertools.groupby(my_blocks, key=lambda b: b[0]):
                run = list(group)
                yield from avro_io.read_container_block_range(
                    files[fi], run[0][1], len(run), index=indexes[fi],
                    on_corrupt=on_corrupt,
                )

        local = _read_local_records(
            list(local_records()), shard_configs,
            index_maps=index_maps,
            random_effect_id_columns=random_effect_id_columns,
            evaluation_id_columns=evaluation_id_columns,
            entity_vocabs=entity_vocabs, dtype=dtype,
        )
    io_counters.record_bytes_decoded(bytes_decoded)

    # ---- ONE metadata allgather: plan fingerprint, row counts, feature
    # keys (when maps were built locally), entity ids + counts. SCALE
    # NOTE: this channel is for metadata — distinct feature keys and
    # entity ids, not sample data. When the caller already provides the
    # entity vocabularies (scoring against a trained model), only the
    # per-entity COUNT vectors ride the exchange (no id strings).
    local_n = local.dataset.num_samples
    payload = {
        "fingerprint": _plan_fingerprint(files, sizes, mode, ranges),
        "n": local_n,
    }
    if index_maps is None:
        payload["keys"] = {
            shard: _local_keys(local.index_maps[shard], cfg)
            for shard, cfg in shard_configs.items()
            if not cfg.pre_indexed
        }
    vocab_counts = {}
    for t in random_effect_id_columns:
        vocab = np.asarray(local.dataset.entity_vocabs[t]).astype(str)
        idx = np.asarray(local.dataset.host_array(f"entity_idx/{t}"))
        counts = (
            np.bincount(idx[idx >= 0], minlength=len(vocab))
            if len(vocab) else np.zeros(0, np.int64)
        )
        if entity_vocabs is not None and t in entity_vocabs:
            # the vocab is shared knowledge; counts align to it already
            vocab_counts[t] = (None, counts.astype(int).tolist())
        else:
            vocab_counts[t] = (vocab.tolist(), counts.astype(int).tolist())
    payload["entities"] = vocab_counts

    # named layout-agreement span around the metadata allgather (the
    # exchange's own span records the wait; this one names the seam)
    with tracing.span("partitioned/metadata_exchange", cat="partitioned",
                      tag=tag, rank=exchange.rank):
        gathered = exchange.allgather(f"partitioned_read/{tag}", payload)

    fingerprints = {g["fingerprint"] for g in gathered}
    if len(fingerprints) != 1:
        raise RuntimeError(
            f"ranks disagree on the partition plan ({fingerprints}); the "
            "input listing must be identical on every rank"
        )
    local_rows = tuple(int(g["n"]) for g in gathered)
    if sum(local_rows) == 0:
        raise ValueError(f"no samples decoded from {path!r} on any rank")
    block_rows = -(-max(max(local_rows), 1) // pad_multiple) * pad_multiple

    # ---- globally consistent index maps (+ column remap of local blocks)
    result = local
    if index_maps is None:
        global_maps: dict[str, IndexMap] = {}
        for shard, cfg in shard_configs.items():
            if cfg.pre_indexed:
                global_maps[shard] = local.index_maps[shard]
                continue
            union: set[str] = set()
            for g in gathered:
                union.update(g["keys"][shard])
            global_maps[shard] = IndexMap.from_keys(
                union, add_intercept=cfg.has_intercept
            )
        result = _remap_to_global_maps(local, shard_configs, global_maps)

    # ---- globally consistent entity vocabs (+ entity index remap)
    presence: dict[str, np.ndarray] = {}
    if random_effect_id_columns:
        result, presence = _remap_to_global_vocabs(
            result, random_effect_id_columns, gathered,
            provided_vocabs=entity_vocabs,
        )

    # ---- globally consistent sparse layout decisions (hybrid hot head,
    # ELL width): layout statistics are GLOBAL, a rank's 1/P block must
    # never elect its own (arXiv:2004.02414's per-partition-statistics-vs-
    # global-solution pitfall, solved the same way the vocabs were)
    result = _resolve_global_sparse_layout(result, exchange, tag,
                                           pad_multiple=pad_multiple)

    # ---- uid-less inputs: shift the reader's auto-assigned row-number
    # uids into the global row space (the full read numbers 0..N-1)
    if _schema_lacks_uid(files):
        base = int(sum(local_rows[:rank]))
        if base:
            ds = result.dataset
            result = ReadResult(
                dataset=dataclasses.replace(
                    ds, unique_ids=np.asarray(ds.unique_ids) + base
                ),
                index_maps=result.index_maps,
                intercept_indices=result.intercept_indices,
            )

    # ---- pad the local block to the agreed common length
    padded, _ = pad_game_dataset_to(result.dataset, block_rows)
    result = ReadResult(
        dataset=padded,
        index_maps=result.index_maps,
        intercept_indices=result.intercept_indices,
    )

    partition = PartitionInfo(rank, num_ranks, local_rows, block_rows)
    logger.info(
        "partitioned read rank %d/%d (%s mode): %d rows (block %d), "
        "%d/%d bytes decoded",
        rank, num_ranks, mode, local_n, block_rows, bytes_decoded,
        input_total,
    )
    return PartitionedReadResult(
        result=result,
        partition=partition,
        mode=mode,
        local_files=local_files,
        bytes_decoded=bytes_decoded,
        input_bytes_total=input_total,
        entity_rank_presence=presence,
    )


def _read_local_files(
    local_files, shard_configs, *, index_maps, random_effect_id_columns,
    evaluation_id_columns, entity_vocabs, fmt, dtype, on_corrupt="raise",
) -> ReadResult:
    if local_files:
        return read_merged(
            local_files, shard_configs, index_maps=index_maps,
            random_effect_id_columns=random_effect_id_columns,
            evaluation_id_columns=evaluation_id_columns,
            entity_vocabs=entity_vocabs, fmt=fmt, dtype=dtype,
            on_corrupt=on_corrupt,
        )
    return _read_local_records(
        [], shard_configs, index_maps=index_maps,
        random_effect_id_columns=random_effect_id_columns,
        evaluation_id_columns=evaluation_id_columns,
        entity_vocabs=entity_vocabs, dtype=dtype,
    )


def _read_local_records(
    records: list, shard_configs, *, index_maps, random_effect_id_columns,
    evaluation_id_columns, entity_vocabs, dtype,
) -> ReadResult:
    maps = index_maps or build_index_maps(records, shard_configs)
    return records_to_game_dataset(
        records, shard_configs, maps,
        random_effect_id_columns=random_effect_id_columns,
        evaluation_id_columns=evaluation_id_columns,
        entity_vocabs=entity_vocabs, dtype=dtype,
    )


def _remap_to_global_maps(
    local: ReadResult,
    shard_configs: Mapping[str, FeatureShardConfiguration],
    global_maps: Mapping[str, IndexMap],
) -> ReadResult:
    """Move the local dataset's feature columns into the global index
    space: a column scatter per dense shard, a column relabel per sparse
    shard. O(n * d) numpy on 1/P of the rows — negligible next to decode."""
    ds = local.dataset
    new_shards: dict[str, object] = {}
    host_cache = dict(ds.host_cache)
    intercepts: dict[str, int] = {}
    for shard, cfg in shard_configs.items():
        lmap, gmap = local.index_maps[shard], global_maps[shard]
        value = ds.feature_shards[shard]
        if cfg.pre_indexed or lmap is gmap:
            new_shards[shard] = value
        elif isinstance(value, SparseShard):
            new_shards[shard] = _remap_sparse(value, lmap, gmap)
            host_cache.pop(f"shard/{shard}", None)
        else:
            remapped = _remap_dense(
                ds.host_array(f"shard/{shard}"), lmap, gmap
            )
            new_shards[shard] = remapped
            host_cache[f"shard/{shard}"] = remapped
        if cfg.has_intercept:
            ii = gmap.get_index(INTERCEPT_KEY)
            if ii >= 0:
                intercepts[shard] = ii
    return ReadResult(
        dataset=dataclasses.replace(
            ds, feature_shards=new_shards, host_cache=host_cache
        ),
        index_maps=dict(global_maps),
        intercept_indices=intercepts,
    )


def _pack_i64(a: np.ndarray) -> str:
    """int64 array -> base64 string for the JSON exchange payloads: the
    hot-ranking histograms carry one entry per distinct column a rank
    observed (millions at giant d), and a per-int Python list would cost
    tens of MB of JSON per rank through the KV transport."""
    return base64.b64encode(
        np.ascontiguousarray(a, dtype="<i8").tobytes()
    ).decode("ascii")


def _unpack_i64(s: str) -> np.ndarray:
    return np.frombuffer(base64.b64decode(s), dtype="<i8")


def _resolve_global_sparse_layout(
    local: ReadResult,
    exchange,
    tag: str,
    *,
    pad_multiple: int = 1,
) -> ReadResult:
    """Make every sparse shard's LAYOUT decisions globally consistent:

    - **Hybrid hot head** (shards carrying a ``hybrid_policy``): each rank
      ships its local per-column nnz histogram — already in the GLOBAL
      column space after the index-map remap — through one metadata
      allgather; every rank sums the histograms and applies the identical
      ``rank_hot_columns`` sizing rule, so the resolved ``hot_ids`` (and
      therefore the [n, k_hot] head shape, column order, and
      parallel/column_sharded.py's per-block hot sub-blocks) agree bitwise
      across ranks. This is exactly how the entity vocabs were made
      globally consistent above, and the reason hybrid now composes with
      --partitioned-io instead of being rejected.
    - **ELL width + flat overflow length** (every sparse shard): each rank
      ships its post-hybrid-split per-row-count histogram (row counts over
      TRUE local rows) in the same allgather; the agreed width applies the
      full read's EXACT auto rule (``_ell_auto_width_from_hist`` — the
      98th-percentile/waste-cap rule evaluated on the summed histogram,
      with the zero-count rows train_distributed's mesh padding would
      append mirrored in, since the full read picks its width AFTER that
      padding), so the composed ELL/overflow split is bitwise what the
      unpartitioned read would build. Every rank's overflow beyond that width is also
      derivable from the same gathered histograms, so all ranks agree a
      common ``flat_block_nnz`` (max overflow, rounded up to
      ``pad_multiple`` so device shards never cross rank blocks) with no
      extra exchange — parallel/distributed._assemble_sparse_fe assembles
      that fixed-length flat tail across ranks. (Hybrid shards take two
      allgathers per shard: the tail histogram depends on the globally
      resolved hot head.)

    Histograms ride the existing exchange deadlines: a rank that never
    publishes surfaces as a rank-attributed ExchangeTimeout, never a hang
    (tests/test_resilience.py pins it with a WithholdingExchange).
    """
    ds = local.dataset
    sparse_shards = {
        k: v for k, v in ds.feature_shards.items()
        if isinstance(v, SparseShard)
    }
    if not sparse_shards:
        return local
    from photon_ml_tpu.data.sparse_batch import (
        _ell_auto_width_from_hist,
        rank_hot_columns,
    )
    from photon_ml_tpu.telemetry.layout import record_global_hot_ranking

    new_shards = dict(ds.feature_shards)
    for name in sorted(sparse_shards):  # fixed order: SPMD call discipline
        shard = sparse_shards[name]
        rows, cols, _ = shard.coalesced()
        rows = np.asarray(rows)
        cols = np.asarray(cols)
        policy = shard.hybrid_policy
        hot = None
        if policy is not None and policy.hot_ids is None:
            uniq, cnt = (
                np.unique(cols, return_counts=True) if len(cols)
                else (np.zeros(0, np.int64), np.zeros(0, np.int64))
            )
            # packed int64 bytes, not per-int Python lists: unique columns
            # reach millions at giant d, and a list-of-ints JSON payload
            # would cost tens of MB per rank through the KV transport
            with tracing.span("partitioned/hybrid_hot_exchange",
                              cat="partitioned", shard=name,
                              rank=exchange.rank):
                gathered_hist = exchange.allgather(
                    f"hybrid_hot/{tag}/{name}",
                    {"cols": _pack_i64(uniq), "counts": _pack_i64(cnt)},
                )
            all_cols = np.concatenate(
                [_unpack_i64(g["cols"]) for g in gathered_hist]
            )
            all_cnts = np.concatenate(
                [_unpack_i64(g["counts"]) for g in gathered_hist]
            )
            # sum per-rank histograms into the global one (sorted by id,
            # exactly what np.unique over the full read would produce)
            guniq, inv = np.unique(all_cols, return_inverse=True)
            gcnt = np.zeros(len(guniq), dtype=np.int64)
            np.add.at(gcnt, inv, all_cnts)
            gnnz = int(gcnt.sum())
            hot = rank_hot_columns(guniq, gcnt, gnnz, policy)
            if len(hot) == 0:
                raise ValueError(
                    f"feature shard '{name}': hybrid=true but no rank "
                    "decoded any nonzero entry — nothing to rank"
                )
            policy = dataclasses.replace(
                policy, hot_ids=tuple(int(c) for c in hot)
            )
            record_global_hot_ranking(
                policy.label, k_hot=len(hot), global_nnz=gnnz,
                num_ranks=exchange.num_ranks,
            )
        elif policy is not None:
            hot = np.asarray(policy.hot_ids, dtype=np.int64)

        # agreed ELL width + flat overflow length: the full read's EXACT
        # auto rule evaluated on the summed per-row-count histograms
        if hot is not None and len(cols):
            pos = np.searchsorted(hot, cols)
            is_hot = hot[np.minimum(pos, len(hot) - 1)] == cols
            tail_rows = rows[~is_hot]
        else:
            tail_rows = rows
        n_local = int(shard.num_samples)
        counts = (
            np.bincount(tail_rows, minlength=n_local).astype(np.int64)
            if n_local else np.zeros(0, np.int64)
        )
        freq = np.bincount(counts) if n_local else np.zeros(1, np.int64)
        with tracing.span("partitioned/ell_width_exchange",
                          cat="partitioned", shard=name,
                          rank=exchange.rank):
            gathered_rows = exchange.allgather(
                f"ell_width/{tag}/{name}",
                {"freq": freq.astype(int).tolist(), "n": n_local},
            )
        depth = max(len(g["freq"]) for g in gathered_rows)
        gfreq = np.zeros(depth, dtype=np.int64)
        rank_freqs = []
        for g in gathered_rows:
            f = np.zeros(depth, dtype=np.int64)
            f[: len(g["freq"])] = np.asarray(g["freq"], dtype=np.int64)
            rank_freqs.append(f)
            gfreq += f
        gn = int(sum(int(g["n"]) for g in gathered_rows))
        widths = np.arange(depth, dtype=np.int64)
        gnnz = int((gfreq * widths).sum())
        # the full read computes its auto width AFTER train_distributed
        # pads the sample axis to a mesh-data-axis multiple (data_axis =
        # pad_multiple * num_ranks, the documented read contract): mirror
        # those zero-count padding rows in the histogram, or the agreed
        # width drifts from the full read's whenever the global row count
        # is not a mesh multiple (the 0.98 quantile shifts down as zero
        # rows are appended)
        data_axis = pad_multiple * exchange.num_ranks
        pad0 = (-gn) % data_axis
        if pad0:
            gfreq[0] += pad0
            gn += pad0
        width = _ell_auto_width_from_hist(gfreq, gn, gnnz)
        # per-rank overflow beyond the agreed width, from the SAME
        # gathered histograms — every rank lands on one flat block length
        flat = max(
            int((f * np.maximum(widths - width, 0)).sum())
            for f in rank_freqs
        )
        if flat:
            flat = -(-flat // pad_multiple) * pad_multiple
        new_shards[name] = dataclasses.replace(
            shard, hybrid_policy=policy, ell_width=width,
            flat_block_nnz=int(flat),
            _device=None, _hybrid_cache=None,
        )
    return ReadResult(
        dataset=dataclasses.replace(ds, feature_shards=new_shards),
        index_maps=local.index_maps,
        intercept_indices=local.intercept_indices,
    )


def _remap_to_global_vocabs(
    local: ReadResult,
    re_types: Sequence[str],
    gathered: list[dict],
    *,
    provided_vocabs,
) -> tuple[ReadResult, dict[str, np.ndarray]]:
    """Union per-rank entity vocabularies into the sorted global vocab
    (identical to a full read's np.unique over all keys) and remap the
    local entity index column; also tally on how many ranks each entity
    appears (cross-rank entities change rank-local RE semantics)."""
    ds = local.dataset
    new_vocabs = dict(ds.entity_vocabs)
    new_idx = dict(ds.entity_idx)
    host_cache = dict(ds.host_cache)
    presence: dict[str, np.ndarray] = {}
    for t in re_types:
        rank_counts = [np.asarray(g["entities"][t][1], dtype=np.int64)
                       for g in gathered]
        if provided_vocabs is not None and t in provided_vocabs:
            # vocab was shared knowledge: no id strings crossed the wire,
            # every rank's counts already align to it
            global_vocab = np.asarray(provided_vocabs[t]).astype(str)
            remap_needed = False
            pres = np.zeros(len(global_vocab), dtype=np.int64)
            for c in rank_counts:
                pres += (c > 0).astype(np.int64)
        else:
            rank_vocabs = [np.asarray(g["entities"][t][0], dtype=str)
                           for g in gathered]
            global_vocab = np.unique(np.concatenate(
                [v for v in rank_vocabs if len(v)] or [np.zeros(0, str)]
            ))
            remap_needed = True
            pres = np.zeros(len(global_vocab), dtype=np.int64)
            for v, c in zip(rank_vocabs, rank_counts):
                if len(v):
                    pos = np.searchsorted(global_vocab, v)
                    pos = np.minimum(pos, max(len(global_vocab) - 1, 0))
                    hit = (
                        global_vocab[pos] == v if len(global_vocab)
                        else np.zeros(len(v), bool)
                    )
                    np.add.at(pres, pos[hit], (c[hit] > 0).astype(np.int64))
        presence[t] = pres
        if remap_needed:
            local_vocab = np.asarray(ds.entity_vocabs[t]).astype(str)
            idx = np.asarray(ds.host_array(f"entity_idx/{t}"))
            if len(local_vocab):
                lookup = np.searchsorted(global_vocab, local_vocab)
                remapped = np.where(
                    idx >= 0, lookup[np.maximum(idx, 0)], -1
                ).astype(np.int32)
            else:
                remapped = idx.astype(np.int32)
            new_idx[t] = remapped
            host_cache[f"entity_idx/{t}"] = remapped
            new_vocabs[t] = global_vocab
    return (
        ReadResult(
            dataset=dataclasses.replace(
                ds, entity_idx=new_idx, entity_vocabs=new_vocabs,
                host_cache=host_cache,
            ),
            index_maps=local.index_maps,
            intercept_indices=local.intercept_indices,
        ),
        presence,
    )
