"""Per-rank score output: every rank writes its own part files.

Reference parity: photon-client ScoreProcessingUtils.scala — the reference
saves ScoringResultAvro per PARTITION (each executor task writes its own
part-NNNNN file into the shared directory; the driver only creates the
directory). The pre-partitioned path here funneled the full [n] score
vector to every host through ``process_allgather`` with only rank 0
writing (parallel/distributed._host_scores) — an O(n) collective plus a
single-host encode that undoes the mesh's scoring parallelism.

``ShardedScoreWriter`` restores the reference layout: rank 0 creates the
output directory, a barrier publishes it, then each rank encodes and
writes ONLY its local score shard as ``part-{rank:05d}.avro`` (the
vectorized ScoringResultAvro encoder from io/model_io.py). Because the
partitioned reader's rank blocks preserve the full-read row order,
concatenating the parts in filename order reproduces the rank-0 writer's
record order exactly. Single-process (num_ranks == 1) keeps today's
``write_scores`` byte layout unchanged.

Bytes written per rank land on the ``io/partitioned/score_bytes_written``
counter (telemetry/io_counters) — the output-side half of the "each rank
touches ~1/P of the bytes" evidence.
"""

from __future__ import annotations

import logging
import os

import numpy as np

from photon_ml_tpu.io.model_io import write_scores
from photon_ml_tpu.telemetry import io_counters

logger = logging.getLogger(__name__)


class ShardedScoreWriter:
    """Writes one rank's score shard into a shared scores directory.

    exchange: parallel/multihost.MetadataExchange. ``None`` = the single-
    rank writer (``write_scores`` layout) regardless of topology — sharded
    writing is opt-in via an explicit exchange (``default_exchange()``),
    mirroring the reader. Directory creation follows the multi-process
    rules: only rank 0 creates the shared directory; every rank then
    writes ITS OWN part file after the barrier (the reference's
    per-partition task writes).
    """

    def __init__(self, output_dir: str | os.PathLike, *, exchange=None):
        if exchange is None:
            from photon_ml_tpu.parallel.multihost import (
                SingleProcessExchange,
            )

            exchange = SingleProcessExchange()
        self.exchange = exchange
        self.output_dir = str(output_dir)

    def write(
        self,
        scores: np.ndarray,
        *,
        model_id: str = "",
        uids: np.ndarray | None = None,
        labels: np.ndarray | None = None,
        weights: np.ndarray | None = None,
        records_per_file: int = 1 << 20,
    ) -> list[str]:
        """Write this rank's local ``scores`` (+ aligned columns); returns
        the paths written. Single-rank keeps the ``write_scores`` layout
        (part files of ``records_per_file``); multi-rank writes exactly
        ``part-{rank:05d}.avro`` so part order == rank order == global row
        order."""
        ex = self.exchange
        if ex.num_ranks == 1:
            write_scores(
                self.output_dir, scores, model_id=model_id, uids=uids,
                labels=labels, weights=weights,
                records_per_file=records_per_file,
            )
            # report only the files THIS call produced (the writer's
            # deterministic part naming) — a reused output directory may
            # hold stale parts from a previous, larger run
            num_parts = max(1, -(-len(scores) // records_per_file))
            paths = [
                os.path.join(self.output_dir, f"part-{i:05d}.avro")
                for i in range(num_parts)
            ]
            io_counters.record_score_bytes_written(
                sum(os.path.getsize(p) for p in paths)
            )
            return paths

        if ex.rank == 0:
            os.makedirs(self.output_dir, exist_ok=True)
            # a reused directory may hold parts from a previous (larger-P)
            # run; stale part files would silently ride along in any
            # concatenate-in-part-order consumer. Rank 0 owns the shared
            # namespace before the barrier — clear them.
            for name in os.listdir(self.output_dir):
                if name.startswith("part-") and name.endswith(".avro"):
                    os.unlink(os.path.join(self.output_dir, name))
        # the directory must exist (and be clean) before any rank writes
        ex.barrier("score_writer/dir")
        part = os.path.join(self.output_dir, f"part-{ex.rank:05d}.avro")
        # one part per rank: each rank's shard is the reference's
        # "partition" (records_per_file splitting stays the single-process
        # writer's concern — a rank re-shards by re-running partitioned)
        write_scores(
            part, scores, model_id=model_id, uids=uids,
            labels=labels, weights=weights,
        )
        written = os.path.getsize(part)
        io_counters.record_score_bytes_written(written)
        logger.info(
            "rank %d/%d wrote %d scores (%d bytes) to %s",
            ex.rank, ex.num_ranks, len(scores), written, part,
        )
        return [part]
