"""I/O layer: Avro wire formats, feature index maps, data readers, model
persistence — the TPU-native replacement for photon-client's Avro stack
(reference photon-client data/avro/*, photon-avro-schemas)."""
