"""Columnar Avro decode via the native C++ fast path.

The pure-Python reader (io/avro.py) decodes ~4k records/s single-core —
fine for tests, a real bottleneck for production-scale ingestion (the
reference reads Avro through JVM-compiled decoders inside Spark executors,
photon-client data/avro/AvroDataReader.scala). This module compiles the
container's writer schema into a PLAN (a prefix-serialized op tree), hands
it to ``native/avro_decoder.cpp``, and gets back columns:

    numeric top-level fields -> float64 arrays (NaN for null branches)
    string  top-level fields -> interned uint32 ids + a unique-string table
    feature bags             -> (row, key_id, value) + "name\\x01term" table
    string maps              -> (row, key_id, value_id) + two tables

Strings are interned in C++; Python only materializes the UNIQUE tables.
Schema shapes outside the supported subset raise
:class:`AvroNativeUnsupported` and callers fall back to the Python reader —
both paths are pinned byte-identical by tests/test_avro_native.py.
"""

from __future__ import annotations

import ctypes
import dataclasses
import os

import numpy as np

from photon_ml_tpu.io.avro import AvroError, parse_schema, read_container_schema
from photon_ml_tpu.native.build import avro_native_available, load_avro_library

# op codes — must match native/avro_decoder.cpp
OP_RECORD, OP_UNION, OP_ARRAY, OP_MAP = 1, 2, 3, 4
OP_NULL, OP_BOOL, OP_INT, OP_LONG = 5, 6, 7, 8
OP_FLOAT, OP_DOUBLE, OP_STRING, OP_BYTES, OP_FIXED = 9, 10, 11, 12, 13
OP_COL_DOUBLE, OP_COL_FLOAT, OP_COL_INT, OP_COL_LONG, OP_COL_BOOL = (
    20, 21, 22, 23, 24,
)
OP_COL_NULLNUM, OP_COL_STR, OP_COL_NULLSTR = 25, 26, 27
OP_MAP_COLLECT, OP_MAPVAL_STR, OP_MAPVAL_NULL = 28, 29, 30
OP_BAG, OP_BAG_NAME, OP_BAG_TERM, OP_BAG_TERM_NULL, OP_BAG_VALUE = (
    31, 32, 33, 34, 35,
)
OP_COL_STRNUM, OP_COL_LONGSTR, OP_COL_BOOLSTR = 36, 37, 38
OP_MAPVAL_LONGSTR, OP_MAPVAL_BOOLSTR, OP_MAPVAL_BAD = 39, 40, 41

NULL_ID = 0xFFFFFFFF

_NUM_KINDS = {"double": 0, "float": 1, "int": 2, "long": 2, "boolean": 3}
_SKIP_OPS = {
    "null": OP_NULL, "boolean": OP_BOOL, "int": OP_INT, "long": OP_LONG,
    "float": OP_FLOAT, "double": OP_DOUBLE, "string": OP_STRING,
    "bytes": OP_BYTES,
}


class AvroNativeUnsupported(AvroError):
    """Schema shape outside the native decoder's subset — use the Python
    reader instead."""


@dataclasses.dataclass
class AvroPlan:
    ops: np.ndarray  # int64 prefix tree
    num_fields: dict[str, int]  # field name -> numeric slot
    str_fields: dict[str, int]
    bag_fields: dict[str, int]
    map_fields: dict[str, int]
    #: every top-level field name (callers detect "requested bag exists in
    #: the schema but was NOT bag-shaped" and fall back)
    all_fields: frozenset[str] = frozenset()
    #: numeric fields whose schema admits float/double/boolean values —
    #: their f64 columns cannot reproduce Python's str() rendering, so they
    #: must not serve as id-column fallbacks (callers fall back instead)
    unfaithful_id_fields: frozenset[str] = frozenset()
    #: numeric fields with a string branch (OP_COL_STRNUM): a NaN may mean
    #: "unparseable string" (where Python raises) rather than null — callers
    #: must fall back on NaN instead of applying defaults
    strnum_fields: frozenset[str] = frozenset()
    #: numeric fields with a null branch: their NaNs are (usually) the null
    #: sentinel, but a genuine NaN double is indistinguishable — callers
    #: fall back when NaNs appear so Python applies its exact semantics
    nullable_num_fields: frozenset[str] = frozenset()

    def same_semantics(self, other: "AvroPlan") -> bool:
        return (
            np.array_equal(self.ops, other.ops)
            and self.num_fields == other.num_fields
            and self.str_fields == other.str_fields
            and self.bag_fields == other.bag_fields
            and self.map_fields == other.map_fields
            and self.all_fields == other.all_fields
            and self.unfaithful_id_fields == other.unfaithful_id_fields
            and self.strnum_fields == other.strnum_fields
            and self.nullable_num_fields == other.nullable_num_fields
        )


def _tname(schema) -> str:
    if isinstance(schema, str):
        return schema
    if isinstance(schema, list):
        return "union"
    return schema.get("type", "")


def _compile_skip(schema, registry, out: list[int], depth: int = 0) -> None:
    """Ops that decode-and-discard an arbitrary (supported) type."""
    if depth > 16:
        raise AvroNativeUnsupported("schema nesting too deep")
    schema = registry.resolve(schema)
    t = _tname(schema)
    if t in _SKIP_OPS:
        out.append(_SKIP_OPS[t])
    elif isinstance(schema, list):
        out.append(OP_UNION)
        out.append(len(schema))
        for branch in schema:
            _compile_skip(branch, registry, out, depth + 1)
    elif t == "record":
        out.append(OP_RECORD)
        out.append(len(schema["fields"]))
        for f in schema["fields"]:
            _compile_skip(f["type"], registry, out, depth + 1)
    elif t == "array":
        out.append(OP_ARRAY)
        _compile_skip(schema["items"], registry, out, depth + 1)
    elif t == "map":
        out.append(OP_MAP)
        _compile_skip(schema["values"], registry, out, depth + 1)
    elif t == "enum":
        out.append(OP_INT)
    elif t == "fixed":
        out.append(OP_FIXED)
        out.append(int(schema["size"]))
    else:
        raise AvroNativeUnsupported(f"cannot skip schema type {t!r}")


def _nullable(schema) -> tuple[bool, int, object]:
    """(is 2-union with null, null branch index, the other branch)."""
    if isinstance(schema, list) and len(schema) == 2:
        names = [_tname(b) for b in schema]
        if "null" in names:
            ni = names.index("null")
            return True, ni, schema[1 - ni]
    return False, -1, None


def _compile_bag_item(item, registry, out: list[int]) -> bool:
    """Emit the OP_BAG item-record node if `item` looks like a feature
    record (name [+term] + numeric value); False if not bag-shaped."""
    item = registry.resolve(item)
    if _tname(item) != "record":
        return False
    fields = item["fields"]
    names = {f["name"] for f in fields}
    if "name" not in names or "value" not in names:
        return False
    probe: list[int] = []
    probe.append(OP_RECORD)
    probe.append(len(fields))
    for f in fields:
        ft = registry.resolve(f["type"])
        nullable, ni, inner = _nullable(ft)
        if f["name"] == "name":
            if _tname(ft) != "string":
                return False
            probe.append(OP_BAG_NAME)
        elif f["name"] == "term":
            if _tname(ft) == "string":
                probe.append(OP_BAG_TERM)
            elif nullable and _tname(registry.resolve(inner)) == "string":
                probe.append(OP_UNION)
                probe.append(2)
                for b in range(2):
                    probe.append(OP_BAG_TERM_NULL if b == ni else OP_BAG_TERM)
            else:
                return False
        elif f["name"] == "value":
            t = _tname(ft)
            if t in _NUM_KINDS:
                probe.append(OP_BAG_VALUE)
                probe.append(_NUM_KINDS[t])
            elif nullable and _tname(registry.resolve(inner)) in _NUM_KINDS:
                # nullable value: null contributes 0.0 (python float(None)
                # would raise; refuse instead of diverging)
                return False
            else:
                return False
        else:
            _compile_skip(ft, registry, probe)
    out.extend(probe)
    return True


def compile_plan(schema: dict) -> AvroPlan:
    """Compile a top-level record schema into the decoder plan."""
    top, registry = parse_schema(schema)
    top = registry.resolve(top)
    if _tname(top) != "record":
        raise AvroNativeUnsupported("top-level schema is not a record")
    ops: list[int] = [OP_RECORD, len(top["fields"])]
    num_fields: dict[str, int] = {}
    str_fields: dict[str, int] = {}
    bag_fields: dict[str, int] = {}
    map_fields: dict[str, int] = {}

    unfaithful: set[str] = set()
    strnum_fields: set[str] = set()
    nullable_num: set[str] = set()

    def scalar_branches(ft) -> list | None:
        """The union branch list when every branch is a scalar (or the
        1-element list for a bare scalar); None otherwise. ``bytes`` is
        excluded: Python renders bytes via repr (b'...'), which the native
        tables cannot reproduce — such fields stay skip-only."""
        branches = ft if isinstance(ft, list) else [ft]
        names = [_tname(registry.resolve(b)) for b in branches]
        ok = {"null", "boolean", "int", "long", "float", "double", "string"}
        if all(nm in ok for nm in names):
            return [registry.resolve(b) for b in branches]
        return None

    # rendering op per branch type, numeric-column vs string-column modes
    NUM_BRANCH = {
        "double": OP_COL_DOUBLE, "float": OP_COL_FLOAT, "int": OP_COL_INT,
        "long": OP_COL_LONG, "boolean": OP_COL_BOOL, "null": OP_COL_NULLNUM,
        # numeric strings parse (python float(label) does the same); junk
        # strings become NaN and callers fall back
        "string": OP_COL_STRNUM,
    }
    STR_BRANCH = {
        "string": OP_COL_STR, "null": OP_COL_NULLSTR,
        "int": OP_COL_LONGSTR, "long": OP_COL_LONGSTR,
        "boolean": OP_COL_BOOLSTR,
    }

    for f in top["fields"]:
        name = f["name"]
        ft = registry.resolve(f["type"])
        t = _tname(ft)
        nullable, ni, inner = _nullable(ft)
        inner_res = registry.resolve(inner) if nullable else None
        scalars = scalar_branches(ft)
        if scalars is not None:
            names = [_tname(b) for b in scalars]
            # floats force a numeric column (f64 is what Python's float()
            # produces anyway); otherwise a string or LONG branch makes it a
            # string column — longs render as exact decimals in C++ (an f64
            # column would corrupt snowflake-scale ids past 2^53, where the
            # Python reader is exact)
            if any(nm in ("float", "double") for nm in names):
                slot = len(num_fields)
                num_fields[name] = slot
                table = NUM_BRANCH
                unfaithful.add(name)
                if "string" in names:
                    strnum_fields.add(name)
                if "null" in names:
                    nullable_num.add(name)
            elif any(nm in ("string", "long") for nm in names):
                slot = len(str_fields)
                str_fields[name] = slot
                table = STR_BRANCH
            else:  # null / boolean / int only — exact in f64
                slot = len(num_fields)
                num_fields[name] = slot
                table = NUM_BRANCH
                if "boolean" in names:
                    unfaithful.add(name)
                if "null" in names:
                    nullable_num.add(name)
            # a union stays a union on the wire even with ONE branch (the
            # branch-index varint is still encoded — seen in the
            # reference's own bad-weights fixtures, label: ["double"])
            if isinstance(ft, list):
                ops += [OP_UNION, len(scalars)]
                for nm in names:
                    ops += [table[nm], slot]
            else:
                ops += [table[names[0]], slot]
        elif t == "array" or (nullable and _tname(inner_res) == "array"):
            arr = ft if t == "array" else inner_res
            probe: list[int] = []
            slot = len(bag_fields)
            probe += [OP_BAG, slot]
            if _compile_bag_item(arr["items"], registry, probe):
                bag_fields[name] = slot
                if nullable:
                    ops += [OP_UNION, 2]
                    for b in range(2):
                        if b == ni:
                            ops.append(OP_NULL)
                        else:
                            ops += probe
                else:
                    ops += probe
            else:
                # not a feature bag: decode-and-discard
                sk: list[int] = []
                _compile_skip(f["type"], registry, sk)
                ops += sk
        elif t == "map" or (nullable and _tname(inner_res) == "map"):
            mp = ft if t == "map" else inner_res
            values = registry.resolve(mp["values"])
            MV = {
                "string": OP_MAPVAL_STR,
                "null": OP_MAPVAL_NULL,
                "int": OP_MAPVAL_LONGSTR, "long": OP_MAPVAL_LONGSTR,
                "boolean": OP_MAPVAL_BOOLSTR,
                # float/double values can't reproduce Python's str()
                # rendering — decoded files that actually CONTAIN one fail
                # at runtime and the caller falls back
                "float": OP_MAPVAL_BAD, "double": OP_MAPVAL_BAD,
            }
            vbranches = values if isinstance(values, list) else [values]
            vnames = [_tname(registry.resolve(b)) for b in vbranches]
            collect: list[int] | None = None
            if all(nm in MV for nm in vnames):
                if isinstance(values, list):  # unions of ANY arity keep
                    collect = [OP_UNION, len(vbranches)]  # their branch index
                    for nm in vnames:
                        collect.append(MV[nm])
                else:
                    collect = [MV[vnames[0]]]
            if collect is not None:
                slot = len(map_fields)
                map_fields[name] = slot
                body = [OP_MAP_COLLECT, slot] + collect
            else:
                body = []
                _compile_skip(mp, registry, body)
            if nullable:
                ops += [OP_UNION, 2]
                for b in range(2):
                    if b == ni:
                        ops.append(OP_NULL)
                    else:
                        ops += body
            else:
                ops += body
        else:
            sk = []
            _compile_skip(f["type"], registry, sk)
            ops += sk

    return AvroPlan(
        ops=np.asarray(ops, dtype=np.int64),
        num_fields=num_fields,
        str_fields=str_fields,
        bag_fields=bag_fields,
        map_fields=map_fields,
        all_fields=frozenset(f["name"] for f in top["fields"]),
        unfaithful_id_fields=frozenset(unfaithful),
        strnum_fields=frozenset(strnum_fields),
        nullable_num_fields=frozenset(nullable_num),
    )


def _table(blob: bytes, offsets: np.ndarray) -> list[str]:
    try:
        return [
            blob[offsets[i]:offsets[i + 1]].decode("utf-8")
            for i in range(len(offsets) - 1)
        ]
    except UnicodeDecodeError as e:
        # the Python reader raises on invalid UTF-8 — it is authoritative
        raise AvroNativeUnsupported(f"invalid UTF-8 in string table: {e}")


@dataclasses.dataclass
class AvroColumns:
    """Columnar decode of one container file (or a concatenation)."""

    n: int
    num: dict[str, np.ndarray]  # field -> [n] float64
    num_null: dict[str, np.ndarray]  # field -> [n] bool, True where null
    str_ids: dict[str, np.ndarray]  # field -> [n] uint32 (NULL_ID = null)
    str_tables: dict[str, list[str]]
    bags: dict[str, tuple[np.ndarray, np.ndarray, np.ndarray]]  # rows, keys, vals
    bag_tables: dict[str, list[str]]  # "name\x01term" keys
    maps: dict[str, tuple[np.ndarray, np.ndarray, np.ndarray]]  # rows, kid, vid
    map_key_tables: dict[str, list[str]]
    map_val_tables: dict[str, list[str]]


def decode_columns(path: str | os.PathLike, plan: AvroPlan | None = None) -> AvroColumns:
    """Decode one container file through the native decoder."""
    if plan is None:
        plan = compile_plan(read_container_schema(path))
    lib = load_avro_library()
    err = ctypes.create_string_buffer(512)
    ops = np.ascontiguousarray(plan.ops, dtype=np.int64)
    handle = lib.avdec_open(
        os.fsencode(str(path)),
        ops.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(ops),
        len(plan.num_fields), len(plan.str_fields),
        len(plan.bag_fields), len(plan.map_fields),
        err, ctypes.c_uint64(len(err)),
    )
    if not handle:
        raise AvroError(f"{path}: native decode failed: {err.value.decode()}")
    try:
        n = int(lib.avdec_num_records(handle))

        def np_copy(ptr, count, dtype):
            if count == 0 or not ptr:
                return np.zeros(0, dtype=dtype)
            return np.ctypeslib.as_array(ptr, shape=(count,)).astype(dtype, copy=True)

        num, num_null = {}, {}
        for name, slot in plan.num_fields.items():
            dp = ctypes.POINTER(ctypes.c_double)()
            mp = ctypes.POINTER(ctypes.c_uint8)()
            cnt = lib.avdec_numcol(handle, slot, ctypes.byref(dp),
                                   ctypes.byref(mp))
            col = np_copy(dp, cnt, np.float64)
            if cnt != n:
                raise AvroError(f"{path}: field '{name}' count {cnt} != {n}")
            num[name] = col
            num_null[name] = np_copy(mp, cnt, np.uint8).astype(bool)
        str_ids, str_tables = {}, {}
        for name, slot in plan.str_fields.items():
            ip = ctypes.POINTER(ctypes.c_uint32)()
            bp = ctypes.c_char_p()
            op = ctypes.POINTER(ctypes.c_uint64)()
            tn = ctypes.c_uint64()
            cnt = lib.avdec_strcol(
                handle, slot, ctypes.byref(ip), ctypes.byref(bp),
                ctypes.byref(op), ctypes.byref(tn),
            )
            if cnt != n:
                raise AvroError(f"{path}: field '{name}' count {cnt} != {n}")
            offs = np_copy(op, tn.value + 1, np.uint64)
            blob = ctypes.string_at(bp, int(offs[-1])) if tn.value else b""
            str_ids[name] = np_copy(ip, cnt, np.uint32)
            str_tables[name] = _table(blob, offs)
        bags, bag_tables = {}, {}
        for name, slot in plan.bag_fields.items():
            rp = ctypes.POINTER(ctypes.c_uint32)()
            kp = ctypes.POINTER(ctypes.c_uint32)()
            vp = ctypes.POINTER(ctypes.c_double)()
            bp = ctypes.c_char_p()
            op = ctypes.POINTER(ctypes.c_uint64)()
            tn = ctypes.c_uint64()
            cnt = lib.avdec_bag(
                handle, slot, ctypes.byref(rp), ctypes.byref(kp),
                ctypes.byref(vp), ctypes.byref(bp), ctypes.byref(op),
                ctypes.byref(tn),
            )
            offs = np_copy(op, tn.value + 1, np.uint64)
            blob = ctypes.string_at(bp, int(offs[-1])) if tn.value else b""
            bags[name] = (
                np_copy(rp, cnt, np.uint32),
                np_copy(kp, cnt, np.uint32),
                np_copy(vp, cnt, np.float64),
            )
            bag_tables[name] = _table(blob, offs)
        maps, mk_tables, mv_tables = {}, {}, {}
        for name, slot in plan.map_fields.items():
            rp = ctypes.POINTER(ctypes.c_uint32)()
            kp = ctypes.POINTER(ctypes.c_uint32)()
            vp = ctypes.POINTER(ctypes.c_uint32)()
            kb = ctypes.c_char_p()
            ko = ctypes.POINTER(ctypes.c_uint64)()
            kn = ctypes.c_uint64()
            vb = ctypes.c_char_p()
            vo = ctypes.POINTER(ctypes.c_uint64)()
            vn = ctypes.c_uint64()
            cnt = lib.avdec_map(
                handle, slot, ctypes.byref(rp), ctypes.byref(kp),
                ctypes.byref(vp), ctypes.byref(kb), ctypes.byref(ko),
                ctypes.byref(kn), ctypes.byref(vb), ctypes.byref(vo),
                ctypes.byref(vn),
            )
            koffs = np_copy(ko, kn.value + 1, np.uint64)
            voffs = np_copy(vo, vn.value + 1, np.uint64)
            maps[name] = (
                np_copy(rp, cnt, np.uint32),
                np_copy(kp, cnt, np.uint32),
                np_copy(vp, cnt, np.uint32),
            )
            mk_tables[name] = _table(
                ctypes.string_at(kb, int(koffs[-1])) if kn.value else b"", koffs
            )
            mv_tables[name] = _table(
                ctypes.string_at(vb, int(voffs[-1])) if vn.value else b"", voffs
            )
        return AvroColumns(
            n=n, num=num, num_null=num_null, str_ids=str_ids,
            str_tables=str_tables, bags=bags, bag_tables=bag_tables,
            maps=maps, map_key_tables=mk_tables, map_val_tables=mv_tables,
        )
    finally:
        lib.avdec_free(handle)


def concat_columns(parts: list[AvroColumns]) -> AvroColumns:
    """Concatenate per-file columns, re-interning tables globally."""
    if len(parts) == 1:
        return parts[0]
    n = sum(p.n for p in parts)
    field_sets = [
        set(parts[0].num), set(parts[0].str_ids), set(parts[0].bags),
        set(parts[0].maps),
    ]
    for p in parts[1:]:
        if [set(p.num), set(p.str_ids), set(p.bags), set(p.maps)] != field_sets:
            raise AvroNativeUnsupported(
                "part files disagree on schema fields"
            )

    def merge_tables(tables: list[list[str]]):
        global_ids: dict[str, int] = {}
        remaps = []
        for t in tables:
            remap = np.zeros(len(t) + 1, dtype=np.uint32)
            for i, s in enumerate(t):
                remap[i] = global_ids.setdefault(s, len(global_ids))
            remaps.append(remap)
        return list(global_ids), remaps

    num = {
        k: np.concatenate([p.num[k] for p in parts]) for k in parts[0].num
    }
    num_null = {
        k: np.concatenate([p.num_null[k] for p in parts])
        for k in parts[0].num_null
    }
    str_ids, str_tables = {}, {}
    for k in parts[0].str_ids:
        table, remaps = merge_tables([p.str_tables[k] for p in parts])
        cols = []
        for p, remap in zip(parts, remaps):
            ids = p.str_ids[k]
            out = np.where(ids == NULL_ID, NULL_ID, remap[np.minimum(ids, len(remap) - 1)])
            cols.append(out.astype(np.uint32))
        str_ids[k] = np.concatenate(cols)
        str_tables[k] = table
    bags, bag_tables = {}, {}
    row_offsets = np.cumsum([0] + [p.n for p in parts])
    for k in parts[0].bags:
        table, remaps = merge_tables([p.bag_tables[k] for p in parts])
        rows, keys, vals = [], [], []
        for p, remap, off in zip(parts, remaps, row_offsets):
            r, kk, v = p.bags[k]
            rows.append(r.astype(np.int64) + off)
            keys.append(remap[kk])
            vals.append(v)
        bags[k] = (
            np.concatenate(rows), np.concatenate(keys), np.concatenate(vals)
        )
        bag_tables[k] = table
    maps, mk_tables, mv_tables = {}, {}, {}
    for k in parts[0].maps:
        ktable, kremaps = merge_tables([p.map_key_tables[k] for p in parts])
        vtable, vremaps = merge_tables([p.map_val_tables[k] for p in parts])
        rows, kids, vids = [], [], []
        for p, kr, vr, off in zip(parts, kremaps, vremaps, row_offsets):
            r, ki, vi = p.maps[k]
            rows.append(r.astype(np.int64) + off)
            kids.append(kr[ki])
            vids.append(
                np.where(vi == NULL_ID, NULL_ID,
                         vr[np.minimum(vi, len(vr) - 1)]).astype(np.uint32)
            )
        maps[k] = (
            np.concatenate(rows), np.concatenate(kids), np.concatenate(vids)
        )
        mk_tables[k] = ktable
        mv_tables[k] = vtable
    return AvroColumns(
        n=n, num=num, num_null=num_null, str_ids=str_ids,
        str_tables=str_tables, bags=bags, bag_tables=bag_tables, maps=maps,
        map_key_tables=mk_tables, map_val_tables=mv_tables,
    )


__all__ = [
    "AvroColumns", "AvroNativeUnsupported", "AvroPlan",
    "avro_native_available", "compile_plan", "concat_columns",
    "decode_columns", "NULL_ID",
]
