"""Read-only parser for PalDB stores (the reference's off-heap index maps).

Reference parity: photon-api index/PalDBIndexMap.scala:26-56 — production
Photon-ML feature index maps are hash-partitioned PalDB stores
(``paldb-partition-<namespace>-<i>.dat``), each holding BOTH directions:
``name\\u0001term -> int`` (local index) and ``int -> name\\u0001term``.
Global index = local index + offset, where partition i's offset is the
number of features in partitions < i (PalDBIndexMap.load:82-99).

PalDB itself is LinkedIn's JVM read-only key-value store. This module
implements a from-scratch reader for its V1 binary format so a migrating
user's existing stores load directly — no JVM required. Format (reverse-
engineered from the public fixtures; all integers big-endian):

    header:
      writeUTF("PALDB_V1")              2-byte length + bytes
      long   creation timestamp
      int    key count (both directions, so 2x the feature count)
      int    distinct serialized-key-length count
      int    max serialized-key length
      per distinct key length:
        int  key length   int key count   int slot count
        int  slot size    int index offset (into index section)
        long data offset  (into data section)
      long   index section start (absolute file offset)
      long   data section start  (absolute file offset)
    index section: per key length, an open-addressing hash table of
      fixed-size slots [serialized key | LongPacker data offset]; offset 0
      (and all-zero slots) = empty. Offsets are 1-based into the group's
      data region.
    data section: per group, a leading 0x00 guard byte then value blobs
      [LongPacker size | serialized value].

Value/key serialization (MapDB-style type bytes; every rule below is
verified against the 15k-feature GameIntegTest fixtures, which exercise
multi-byte varints):
    int 0..8   -> single byte 0x05 + value
    int 9..254 -> 0x0e, unsigned byte
    int 255+   -> 0x10, LongPacker varint
    string     -> 0x67, LongPacker BYTE count, then that many UTF-8 bytes
                  (all fixture keys are ASCII, where byte count == char
                  count; non-ASCII names are untested territory)
(The strings are full feature keys, name + "\\u0001" + term, so they map
1:1 onto io/index_map.feature_key.) LongPacker varints are 7 bits per
byte, least-significant group first, 0x80 = continuation.

Loading scans every slot once and materializes a plain dict — exactly what
a migration wants; no JVM hash probing is reproduced.
"""

from __future__ import annotations

import os
import re
import struct
from dataclasses import dataclass

from photon_ml_tpu.io.index_map import IndexMap

_MAGIC = b"PALDB_V1"
_INT_SMALL_BASE = 0x05  # ints 0..8 inline
_INT_SMALL_MAX = 8
_INT_BYTE = 0x0E  # unsigned byte follows (ints 9..254)
_INT_PACKED = 0x10  # LongPacker varint follows (ints 255+)
_STRING = 0x67

PARTITION_RE = re.compile(r"^paldb-partition-(?P<ns>.+)-(?P<idx>\d+)\.dat$")


def _unpack_longpacker(buf: bytes, pos: int) -> tuple[int, int]:
    """PalDB LongPacker varint: 7 bits per byte, LEAST-significant group
    first, 0x80 = continuation (protobuf-style; verified against multi-byte
    offsets in the reference GameIntegTest stores). Returns (value, pos)."""
    value = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        value |= (b & 0x7F) << shift
        if not b & 0x80:
            return value, pos
        shift += 7


def _deserialize(buf: bytes, pos: int, end: int):
    """One serialized key/value in [pos, end); returns the Python value."""
    t = buf[pos]
    if _INT_SMALL_BASE <= t <= _INT_SMALL_BASE + _INT_SMALL_MAX:
        return t - _INT_SMALL_BASE
    if t == _INT_BYTE:
        if pos + 1 >= end:
            raise ValueError(
                f"corrupt PalDB blob at offset {pos}: INT_BYTE payload "
                "overruns its region"
            )
        return buf[pos + 1]
    if t == _INT_PACKED:
        value, p = _unpack_longpacker(buf, pos + 1)
        if p > end:
            raise ValueError(
                f"corrupt PalDB blob at offset {pos}: packed int of "
                f"{p - pos - 1} bytes overruns its {end - pos}-byte region"
            )
        return value
    if t == _STRING:
        n, p = _unpack_longpacker(buf, pos + 1)
        if p + n > end:
            raise ValueError(
                f"corrupt PalDB blob at offset {pos}: string of {n} bytes "
                f"overruns its {end - pos}-byte region"
            )
        return buf[p : p + n].decode("utf-8")
    raise ValueError(
        f"unsupported PalDB serialization type byte 0x{t:02x} at offset "
        f"{pos} (photon index stores hold only ints and strings; rebuild "
        "the map with feature_indexing_driver if the store uses an "
        "encoding these fixtures never exercised)"
    )


@dataclass
class PalDBPartition:
    """Parsed contents of one paldb-partition-*.dat file."""

    name_to_local: dict[str, int]
    local_to_name: dict[int, str]

    @property
    def size(self) -> int:
        return len(self.name_to_local)


def read_partition(path: str | os.PathLike) -> PalDBPartition:
    """Parse one PalDB store file into its two direction maps."""
    with open(path, "rb") as f:
        buf = f.read()
    pos = 0
    n = struct.unpack_from(">H", buf, pos)[0]
    pos += 2
    if buf[pos : pos + n] != _MAGIC:
        raise ValueError(
            f"{path}: not a PalDB V1 store (magic {buf[pos:pos+n]!r})"
        )
    pos += n + 8  # magic + timestamp
    key_count, length_count, _max_len = struct.unpack_from(">iii", buf, pos)
    pos += 12
    groups = []
    for _ in range(length_count):
        key_len, cnt, slots, slot_size, index_off = struct.unpack_from(
            ">iiiii", buf, pos
        )
        pos += 20
        (data_off,) = struct.unpack_from(">q", buf, pos)
        pos += 8
        groups.append((key_len, cnt, slots, slot_size, index_off, data_off))
    index_start, data_start = struct.unpack_from(">qq", buf, pos)

    name_to_local: dict[str, int] = {}
    local_to_name: dict[int, str] = {}
    found = 0
    for key_len, cnt, slots, slot_size, index_off, data_off in groups:
        base = index_start + index_off
        for s in range(slots):
            slot_pos = base + s * slot_size
            slot = buf[slot_pos : slot_pos + slot_size]
            if not any(slot):
                continue
            offset, _ = _unpack_longpacker(slot, key_len)
            if offset == 0:
                continue
            key = _deserialize(slot, 0, key_len)
            blob_pos = data_start + data_off + offset
            size, p = _unpack_longpacker(buf, blob_pos)
            value = _deserialize(buf, p, p + size)
            found += 1
            if isinstance(key, str):
                name_to_local[key] = int(value)
            else:
                local_to_name[int(key)] = str(value)
    if found != key_count:
        raise ValueError(
            f"{path}: slot scan found {found} entries, header says {key_count}"
        )
    if len(name_to_local) != len(local_to_name):
        raise ValueError(
            f"{path}: direction maps disagree "
            f"({len(name_to_local)} names vs {len(local_to_name)} indices)"
        )
    for name, local in name_to_local.items():
        if local_to_name.get(local) != name:
            raise ValueError(
                f"{path}: inconsistent store — '{name}' -> {local} but "
                f"{local} -> {local_to_name.get(local)!r}"
            )
    return PalDBPartition(name_to_local=name_to_local, local_to_name=local_to_name)


def discover_stores(directory: str | os.PathLike) -> dict[str, dict[int, str]]:
    """namespace -> {partition index: file path}, for every PalDB store in
    the directory (reference partitionFilename naming).

    Partition-set validation happens per namespace at LOAD time, not here —
    one unrelated broken store must not block loading a healthy one."""
    directory = str(directory)
    found: dict[str, dict[int, str]] = {}
    for fname in os.listdir(directory):
        m = PARTITION_RE.match(fname)
        if m:
            found.setdefault(m.group("ns"), {})[int(m.group("idx"))] = os.path.join(
                directory, fname
            )
    return found


def load_paldb_index_map(
    directory: str | os.PathLike, namespace: str
) -> IndexMap:
    """Load a partitioned PalDB index store as a plain IndexMap.

    Global index = partition-local index + offset, offsets being the
    cumulative feature counts of preceding partitions — the reference's
    offset arithmetic (PalDBIndexMap.load:82-99, getIndex:145-155).
    """
    stores = discover_stores(directory)
    if namespace not in stores:
        raise FileNotFoundError(
            f"no PalDB store for namespace '{namespace}' in {directory} "
            f"(found: {sorted(stores) or 'none'})"
        )
    parts = stores[namespace]
    if set(parts) != set(range(len(parts))):
        raise ValueError(
            f"PalDB store '{namespace}' in {directory} has partitions "
            f"{sorted(parts)}; expected contiguous 0..{len(parts) - 1}"
        )
    mapping: dict[str, int] = {}
    offset = 0
    for path in (parts[i] for i in range(len(parts))):
        part = read_partition(path)
        for name, local in part.name_to_local.items():
            mapping[name] = local + offset
        offset += part.size
    if sorted(mapping.values()) != list(range(len(mapping))):
        # gapped partition-local indices would silently alias two features
        # onto one global column under the offset arithmetic
        raise ValueError(
            f"PalDB store '{namespace}' in {directory} yields non-contiguous "
            "global indices — partition-local indices are gapped or "
            "duplicated (corrupt or truncated store)"
        )
    return IndexMap(mapping)
