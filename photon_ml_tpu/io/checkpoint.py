"""Mid-training checkpoint / resume for GAME coordinate descent.

The reference has **no** mid-training checkpoints: recovery is Spark lineage
recompute plus coarse warm-start from models saved per optimization config
(SURVEY.md §5; GameTrainingDriver.scala:748-815, GameEstimator.scala:392-411).
This module goes beyond it with first-class checkpoint/resume:

- ``TrainingCheckpointer`` writes one atomic step directory per save
  (``step_<k>/`` with ``arrays.npz`` + ``meta.json`` + per-coordinate entity
  key vocabularies), prunes to ``max_to_keep``, and restores the latest
  intact step. Atomicity = write to a temp dir, ``os.replace`` into place —
  a crash mid-save never corrupts the latest good checkpoint.
- ``run_coordinate_descent(..., checkpointer=...)`` (algorithm/
  coordinate_descent.py) saves after every coordinate update and fast-
  forwards past completed updates on resume.
- ``train_distributed(..., checkpointer=...)`` (parallel/distributed.py)
  saves the mesh-sharded ``GameTrainState`` per CD sweep; arrays are pulled
  to host with ``jax.device_get`` (works for sharded arrays — all shards on
  this host are gathered) and re-sharded on restore by the caller's
  ``shard_inputs``.
- ``SolverCheckpointer`` extends the same atomic contract to STREAMING
  solves (``estimators.train_glm_streaming``): the ``host_loop`` solver
  bodies run from Python with host-visible state, so the full optimizer
  state struct + λ-grid position + epoch cursor persist at every epoch
  boundary and a killed run fast-forwards past completed λs and resumes
  MID-SOLVE — the workload most likely to run for hours on a preemptible
  pool no longer restarts from scratch.
- ``commit_checkpoint`` is the ONE write site for training loops
  (dev/lint_parity.py check 10): rank-0-gated per the multi-process
  convention, and — when a ``MetadataExchange`` is attached — gated by
  its rank-attributed deadline barriers so a checkpoint commits only when
  EVERY rank reached the same step (exchange-consistent; a wedged rank
  surfaces as an ``ExchangeTimeout`` naming it, never a torn commit).

Checkpoints are plain numpy + JSON: portable across backends (save on TPU,
restore on CPU), no framework version pinning, diffable metadata.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shutil
import tempfile
import zipfile
from typing import Any, Mapping

import jax
import numpy as np

logger = logging.getLogger(__name__)

from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (
    DatumScoringModel,
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.models.matrix_factorization import MatrixFactorizationModel
from photon_ml_tpu.types import TaskType

_STEP_PREFIX = "step_"
_META_FILE = "meta.json"
_ARRAYS_FILE = "arrays.npz"


@dataclasses.dataclass
class Checkpoint:
    """One restored checkpoint: step id, array pytree, JSON metadata."""

    step: int
    arrays: dict[str, np.ndarray]
    meta: dict[str, Any]


class TrainingCheckpointer:
    """Atomic, pruned, numbered checkpoints under one directory.

    Layout::

        <directory>/
          step_00000007/
            arrays.npz     flat {key: array} — numeric state
            meta.json      structure + scalars (task types, shard ids, ...)
          step_00000008/
            ...

    ``save`` never leaves a partially-written ``step_*`` dir: content goes to
    a ``tmp.*`` sibling first and is renamed into place, then older steps are
    pruned down to ``max_to_keep``.
    """

    def __init__(self, directory: str | os.PathLike, *, max_to_keep: int = 3):
        self.directory = str(directory)
        self.max_to_keep = max(1, int(max_to_keep))
        os.makedirs(self.directory, exist_ok=True)

    # -- core save/restore ---------------------------------------------------

    def save(self, step: int, arrays: Mapping[str, np.ndarray], meta: dict) -> str:
        step_dir = os.path.join(self.directory, f"{_STEP_PREFIX}{step:08d}")
        tmp_dir = tempfile.mkdtemp(prefix="tmp.", dir=self.directory)
        try:
            host_arrays = {k: np.asarray(jax.device_get(v)) for k, v in arrays.items()}
            np.savez(os.path.join(tmp_dir, _ARRAYS_FILE), **host_arrays)
            with open(os.path.join(tmp_dir, _META_FILE), "w") as f:
                json.dump({"step": step, **meta}, f, indent=2, default=str)
            if os.path.isdir(step_dir):
                shutil.rmtree(step_dir)
            os.replace(tmp_dir, step_dir)
        except BaseException:
            shutil.rmtree(tmp_dir, ignore_errors=True)
            raise
        self._prune()
        return step_dir

    def steps(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith(_STEP_PREFIX):
                path = os.path.join(self.directory, name)
                # intact = both files present (a partially-pruned or
                # partially-deleted dir must not be offered for restore)
                if os.path.isfile(os.path.join(path, _META_FILE)) and os.path.isfile(
                    os.path.join(path, _ARRAYS_FILE)
                ):
                    try:
                        out.append(int(name[len(_STEP_PREFIX):]))
                    except ValueError:
                        continue
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def newest_loadable_step(self) -> int | None:
        """Newest step that passes the cheap integrity probe — what
        coordinated rollback (resilience/coordinated.py) resolves on rank
        0 and publishes to every rank: barrier-committed saves are the
        only writers here, so the newest INTACT step is by construction a
        step every rank completed. None when no step would load."""
        for step in reversed(self.steps()):
            if self._loadable(step):
                return step
        return None

    #: everything a truncated/garbled step file can raise during load:
    #: zip directory damage (BadZipFile), npz entry damage (zlib via
    #: ValueError/OSError), meta damage (JSONDecodeError is a ValueError)
    _CORRUPT_ERRORS = (
        OSError,
        EOFError,
        ValueError,
        KeyError,
        zipfile.BadZipFile,
    )

    def _load(self, step: int) -> Checkpoint:
        step_dir = os.path.join(self.directory, f"{_STEP_PREFIX}{step:08d}")
        with open(os.path.join(step_dir, _META_FILE)) as f:
            meta = json.load(f)
        with np.load(os.path.join(step_dir, _ARRAYS_FILE), allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files}
        return Checkpoint(step=step, arrays=arrays, meta=meta)

    def restore(self, step: int | None = None) -> Checkpoint | None:
        """Restore ``step`` (default: NEWEST step that actually loads).

        A ``step_<k>/`` dir whose ``arrays.npz`` or ``meta.json`` is
        truncated/garbled (external damage — the atomic save never
        produces one) is skipped with a warning and the next older step is
        tried, so resume degrades to the newest INTACT step instead of
        aborting. Returns None when no step loads; raises ValueError for
        an explicitly-requested step that is missing, and the underlying
        error for one that is present but corrupt (an explicit request
        must not silently resolve to a different step).
        """
        if step is not None:
            if step not in self.steps():
                raise ValueError(
                    f"checkpoint step {step} not found (intact steps: "
                    f"{self.steps()})"
                )
            return self._load(step)
        for candidate in reversed(self.steps()):
            try:
                return self._load(candidate)
            except self._CORRUPT_ERRORS as e:
                logger.warning(
                    "checkpoint step %d at %s is corrupt (%s: %s); falling "
                    "back to the previous step",
                    candidate, self.directory, type(e).__name__, e,
                )
        return None

    def _loadable(self, step: int) -> bool:
        """Cheap integrity probe for pruning decisions: meta parses and the
        npz's zip central directory (stored at end of file — the first
        casualty of truncation) reads. Full CRC verification is restore's
        job; pruning must not re-read multi-GB arrays."""
        step_dir = os.path.join(self.directory, f"{_STEP_PREFIX}{step:08d}")
        try:
            with open(os.path.join(step_dir, _META_FILE)) as f:
                json.load(f)
            with zipfile.ZipFile(os.path.join(step_dir, _ARRAYS_FILE)) as z:
                z.namelist()
            return True
        except self._CORRUPT_ERRORS:
            return False

    def _prune(self) -> None:
        steps = self.steps()
        doomed = steps[: -self.max_to_keep]
        if not doomed:
            return
        kept = steps[-self.max_to_keep:]
        if not any(self._loadable(s) for s in kept):
            # every kept step is damaged: protect the newest loadable step
            # among the prune candidates — pruning must never delete the
            # last checkpoint a resume could actually restore
            for s in reversed(doomed):
                if self._loadable(s):
                    logger.warning(
                        "keeping checkpoint step %d beyond max_to_keep=%d: "
                        "it is the newest loadable step (%s newer steps "
                        "are corrupt)",
                        s, self.max_to_keep, len(kept),
                    )
                    doomed = [d for d in doomed if d != s]
                    break
        for s in doomed:
            shutil.rmtree(
                os.path.join(self.directory, f"{_STEP_PREFIX}{s:08d}"),
                ignore_errors=True,
            )


def commit_checkpoint(
    checkpointer,
    step: int,
    arrays: Mapping[str, np.ndarray],
    meta: dict,
    *,
    exchange=None,
) -> str | None:
    """The ONE checkpoint write site for training loops: rank-0-gated and
    (with an exchange) barrier-committed. dev/lint_parity.py check 10
    statically bans direct ``checkpointer.save(...)`` calls in parallel/
    and algorithm/ so multi-rank write sites cannot drift from this
    contract.

    EVERY rank must call (the barriers are collective-like; the state
    gathers feeding ``arrays`` already are). Protocol:

    1. pre-commit barrier — the checkpoint commits only when every rank
       reached this step with its collectives complete (a rank that
       crashed or wedged surfaces as a rank-attributed
       ``resilience.errors.ExchangeTimeout`` within the exchange deadline,
       never a checkpoint torn across ranks' notions of progress);
    2. rank 0 writes through the atomic temp-dir + ``os.replace`` save
       (the multi-process convention: only rank 0 touches shared output
       directories);
    3. post-commit barrier — no rank runs ahead (and possibly fails,
       triggering a restore) while the publish is still in flight.

    ``exchange=None`` is the single-caller mode: the ``jax.process_index()
    == 0`` gate alone, no barriers — exactly the pre-existing
    ``train_distributed`` behavior (and a no-op gate single-process).
    Returns the step directory path on the writing rank, None elsewhere.
    """
    from photon_ml_tpu.telemetry import tracing

    if checkpointer is None:
        return None
    if exchange is None:
        if jax.process_index() == 0:
            with tracing.span("checkpoint/write", cat="checkpoint",
                              step=step):
                return checkpointer.save(step, arrays, meta)
        return None
    # the commit span brackets both barriers (their waits are recorded by
    # the exchange's own spans, tag checkpoint_commit/*) + the rank-0
    # write; spans observe, never gate — the barrier sequence is identical
    # with tracing off
    with tracing.span("checkpoint/commit", cat="checkpoint", step=step,
                      rank=exchange.rank):
        exchange.barrier(f"checkpoint_commit/{step}/ready")
        path = None
        if exchange.rank == 0:
            with tracing.span("checkpoint/write", cat="checkpoint",
                              step=step, rank=exchange.rank):
                path = checkpointer.save(step, arrays, meta)
        exchange.barrier(f"checkpoint_commit/{step}/published")
        return path


# -- GAME model (de)serialization to flat array dicts -------------------------


def game_model_to_arrays(model: GameModel) -> tuple[dict[str, np.ndarray], dict]:
    """Flatten a GameModel into (arrays, structure-metadata) for checkpointing."""
    arrays: dict[str, np.ndarray] = {}
    coords_meta: dict[str, dict] = {}
    for cid, sub in model.models.items():
        if isinstance(sub, FixedEffectModel):
            arrays[f"{cid}/means"] = np.asarray(sub.glm.coefficients.means)
            if sub.glm.coefficients.variances is not None:
                arrays[f"{cid}/variances"] = np.asarray(sub.glm.coefficients.variances)
            coords_meta[cid] = {
                "kind": "fixed",
                "feature_shard_id": sub.feature_shard_id,
                "task": sub.glm.task.name,
            }
        elif isinstance(sub, RandomEffectModel):
            arrays[f"{cid}/coefficients"] = np.asarray(sub.coefficients)
            arrays[f"{cid}/entity_keys"] = np.asarray(sub.entity_keys)
            if sub.variances is not None:
                arrays[f"{cid}/variances"] = np.asarray(sub.variances)
            coords_meta[cid] = {
                "kind": "random",
                "random_effect_type": sub.random_effect_type,
                "feature_shard_id": sub.feature_shard_id,
                "task": sub.task.name,
            }
        elif isinstance(sub, MatrixFactorizationModel):
            arrays[f"{cid}/row_factors"] = np.asarray(sub.row_factors)
            arrays[f"{cid}/col_factors"] = np.asarray(sub.col_factors)
            arrays[f"{cid}/row_keys"] = np.asarray(sub.row_keys)
            arrays[f"{cid}/col_keys"] = np.asarray(sub.col_keys)
            coords_meta[cid] = {
                "kind": "matrix_factorization",
                "row_effect_type": sub.row_effect_type,
                "col_effect_type": sub.col_effect_type,
                "task": sub.task.name,
            }
        else:
            raise TypeError(f"Cannot checkpoint sub-model type {type(sub)!r}")
    return arrays, {"coordinates": coords_meta, "order": list(model.models)}


def _with_prefix(arrays: Mapping[str, np.ndarray], prefix: str) -> dict[str, np.ndarray]:
    return {f"{prefix}{k}": v for k, v in arrays.items()}


def _strip_prefix(arrays: Mapping[str, np.ndarray], prefix: str) -> dict[str, np.ndarray]:
    return {k[len(prefix):]: v for k, v in arrays.items() if k.startswith(prefix)}


def pack_cd_state(
    model: GameModel,
    best_model: GameModel | None,
    best_metric: float,
    metric_history: list[dict],
) -> tuple[dict[str, np.ndarray], dict]:
    """Flatten full coordinate-descent progress (current + best model) for save."""
    arrays, model_meta = game_model_to_arrays(model)
    out = _with_prefix(arrays, "model/")
    meta: dict[str, Any] = {
        "model": model_meta,
        "best_metric": None if np.isnan(best_metric) else float(best_metric),
        "metric_history": metric_history,
    }
    if best_model is not None:
        best_arrays, best_meta = game_model_to_arrays(best_model)
        out.update(_with_prefix(best_arrays, "best/"))
        meta["best"] = best_meta
    return out, meta


def unpack_cd_state(
    ckpt: Checkpoint,
) -> tuple[GameModel, GameModel | None, float, list[dict]]:
    """Inverse of :func:`pack_cd_state`."""
    model = game_model_from_arrays(_strip_prefix(ckpt.arrays, "model/"), ckpt.meta["model"])
    best_model = None
    if "best" in ckpt.meta and ckpt.meta["best"] is not None:
        best_model = game_model_from_arrays(
            _strip_prefix(ckpt.arrays, "best/"), ckpt.meta["best"]
        )
    raw = ckpt.meta.get("best_metric")
    best_metric = float("nan") if raw is None else float(raw)
    return model, best_model, best_metric, list(ckpt.meta.get("metric_history", []))


class DivergenceError(RuntimeError):
    """Raised when training state goes non-finite (failure detection).

    The reference relies on Spark lineage recompute and has no divergence
    handling (SURVEY.md §5); here a non-finite coordinate update is caught at
    the CD level so the driver can restore the last good checkpoint instead
    of silently training on NaNs.
    """


def game_model_from_arrays(
    arrays: Mapping[str, np.ndarray], meta: Mapping[str, Any]
) -> GameModel:
    """Inverse of :func:`game_model_to_arrays`."""
    models: dict[str, DatumScoringModel] = {}
    coords_meta = meta["coordinates"]
    for cid in meta["order"]:
        info = coords_meta[cid]
        task = TaskType[info["task"]]
        variances = arrays.get(f"{cid}/variances")
        if info["kind"] == "fixed":
            glm = GeneralizedLinearModel(
                coefficients=Coefficients(
                    means=arrays[f"{cid}/means"], variances=variances
                ),
                task=task,
            )
            models[cid] = FixedEffectModel(
                glm=glm, feature_shard_id=info["feature_shard_id"]
            )
        elif info["kind"] == "random":
            models[cid] = RandomEffectModel(
                coefficients=arrays[f"{cid}/coefficients"],
                entity_keys=arrays[f"{cid}/entity_keys"],
                random_effect_type=info["random_effect_type"],
                feature_shard_id=info["feature_shard_id"],
                task=task,
                variances=variances,
            )
        elif info["kind"] == "matrix_factorization":
            models[cid] = MatrixFactorizationModel(
                row_factors=arrays[f"{cid}/row_factors"],
                col_factors=arrays[f"{cid}/col_factors"],
                row_effect_type=info["row_effect_type"],
                col_effect_type=info["col_effect_type"],
                row_keys=arrays[f"{cid}/row_keys"],
                col_keys=arrays[f"{cid}/col_keys"],
                task=task,
            )
        else:
            raise ValueError(f"Unknown checkpoint coordinate kind {info['kind']!r}")
    return GameModel(models=models)


def latest_trained_model(checkpointer: TrainingCheckpointer) -> "tuple[GameModel, int] | None":
    """(current GameModel, step) from the newest intact checkpoint under
    ``checkpointer`` — the warm-start re-entry hook for incremental
    refresh (algorithm/refresh.py): a daily-refresh driver can resume
    straight from PR 8 training checkpoints without a saved model
    directory. Handles both checkpoint layouts that carry a full model:
    CD-state checkpoints (``pack_cd_state`` — the "model/" prefix) and
    incremental-refresh checkpoints (bare ``game_model_to_arrays``
    layout). Returns None when the directory holds no loadable step;
    raises ValueError for a checkpoint kind that carries no model (e.g. a
    streaming solver-progress checkpoint) — the operator must point at the
    training run's CD checkpoints instead."""
    ckpt = checkpointer.restore()
    if ckpt is None:
        return None
    if ckpt.meta.get("kind") == "incremental_refresh":
        return (
            game_model_from_arrays(ckpt.arrays, ckpt.meta["model"]),
            ckpt.step,
        )
    if "model" in ckpt.meta and any(
        k.startswith("model/") for k in ckpt.arrays
    ):
        model = game_model_from_arrays(
            _strip_prefix(ckpt.arrays, "model/"), ckpt.meta["model"]
        )
        return model, ckpt.step
    raise ValueError(
        f"checkpoint step {ckpt.step} at {checkpointer.directory} carries "
        f"no GAME model (kind={ckpt.meta.get('kind')!r}); point the "
        "refresh at the training run's coordinate-descent checkpoint "
        "directory or pass a saved model directory"
    )


def fingerprint_mismatch(saved: dict | None, expected: dict) -> str | None:
    """None when the fingerprints agree; otherwise a human-readable
    clause NAMING the differing fields with both sides' values — the one
    formatter every fingerprint-guarded restore (SolverCheckpointer,
    train_partitioned) raises with, so the attribution format cannot
    drift between consumers."""
    saved = saved or {}
    if saved == expected:
        return None
    diff = sorted(
        k for k in set(saved) | set(expected)
        if saved.get(k) != expected.get(k)
    )
    return (
        f"differs on {diff}: checkpoint="
        f"{ {k: saved.get(k) for k in diff} }, this run="
        f"{ {k: expected.get(k) for k in diff} }"
    )


# -- streaming solver-state checkpoints ---------------------------------------


@dataclasses.dataclass
class SolverProgress:
    """One restored streaming-solve position.

    lam_index:    index into the SORTED λ grid of the in-flight solve
                  (== len(grid) when the run died after the last λ).
    iteration:    outer solver iteration the state was saved at.
    epochs_total: chunked epochs consumed by COMPLETED λs (never redone).
    epochs_lambda: epochs consumed by the in-flight λ up to the save.
    completed:    [(λ, solve-space coefficients)] for finished λs, in grid
                  order — both the models already trained and the warm
                  start for the λ after them.
    state_arrays: the in-flight solver state's field arrays (None when the
                  save landed exactly on a λ boundary).
    """

    lam_index: int
    iteration: int
    epochs_total: int
    epochs_lambda: int
    completed: list
    state_arrays: dict | None


class SolverCheckpointer:
    """Epoch-granular checkpoints for host-loop streaming solves.

    Persists, through the same atomic temp-dir + ``os.replace`` contract
    as :class:`TrainingCheckpointer` (which it wraps), everything a killed
    ``train_glm_streaming`` run needs to resume without redoing work:
    the full optimizer state struct of the in-flight solve (every field of
    ``optim``'s LBFGS/OWLQN/TRON state dataclasses — history buffers,
    trust-region radius, iteration/reason scalars), the λ-grid position,
    the epoch cursor, and the completed λs' solve-space coefficients.

    A ``fingerprint`` (λ grid, optimizer, dimensions, chunk plan) rides
    every save; a restore under a different fingerprint FAILS FAST with
    the differing fields named instead of silently resuming a
    mismatched solve — the same pin-the-agreement rule the partitioned
    checkpoint applies to its layout exchange.

    Step ids encode (λ index, iteration) monotonically, so
    ``TrainingCheckpointer``'s newest-intact-step restore (with its
    corrupt-step fallback and prune protections) applies unchanged.
    """

    #: step = lam_index * STRIDE + iteration + 1 — monotone across the
    #: run as long as a single solve stays under STRIDE iterations
    STEP_STRIDE = 1_000_000

    def __init__(self, directory: str | os.PathLike, *, max_to_keep: int = 3,
                 save_every: int = 1):
        #: iteration cadence for mid-solve snapshots: the state is
        #: model-sized (d·(2m+4) floats for LBFGS — ~0.5 GB at d=10⁷
        #: m=10), so giant-d runs widen this instead of paying a blocking
        #: np.savez every iteration; λ-boundary snapshots always save
        self.save_every = max(1, int(save_every))
        self._inner = TrainingCheckpointer(directory, max_to_keep=max_to_keep)
        self.directory = self._inner.directory

    def latest_step(self) -> int | None:
        """Duck-compatible with TrainingCheckpointer for
        resilience.recovery.run_with_recovery's has-a-checkpoint test."""
        return self._inner.latest_step()

    def newest_loadable_step(self) -> int | None:
        """Duck-compatible with TrainingCheckpointer for coordinated
        rollback's rank-0 step resolution."""
        return self._inner.newest_loadable_step()

    def save_progress(
        self,
        *,
        fingerprint: dict,
        lam_index: int,
        iteration: int,
        epochs_total: int,
        epochs_lambda: int,
        completed,
        solver_state=None,
    ) -> str:
        """Persist one epoch-boundary snapshot (see class docstring).

        Every snapshot is SELF-CONTAINED — completed λs' coefficients are
        re-written each time even though they no longer change. This is
        deliberate: restore falls back across steps on corruption and
        prune deletes old steps freely, which cross-step references would
        break (a referenced step could be pruned or damaged out from
        under a newer snapshot). The cost is bounded by the grid size and
        amortized by ``save_every`` — widen the cadence at giant d rather
        than sharing state across steps."""
        arrays: dict[str, np.ndarray] = {}
        lams = []
        for i, (lam, w) in enumerate(completed):
            lams.append(float(lam))
            arrays[f"completed/{i:04d}"] = np.asarray(w)
        state_fields: list[str] = []
        if solver_state is not None:
            for f in dataclasses.fields(solver_state):
                state_fields.append(f.name)
                arrays[f"state/{f.name}"] = np.asarray(
                    jax.device_get(getattr(solver_state, f.name))
                )
        meta = {
            "kind": "solver_progress",
            "fingerprint": fingerprint,
            "lam_index": int(lam_index),
            "iteration": int(iteration),
            "epochs_total": int(epochs_total),
            "epochs_lambda": int(epochs_lambda),
            "completed_lambdas": lams,
            "state_fields": state_fields,
        }
        step = int(lam_index) * self.STEP_STRIDE + int(iteration) + 1
        return self._inner.save(step, arrays, meta)

    def restore_progress(self, fingerprint: dict) -> SolverProgress | None:
        """Newest intact snapshot, or None. Raises ValueError (attributed:
        the differing fingerprint fields are named) when the checkpoint
        was written under a different solve configuration."""
        ckpt = self._inner.restore()
        if ckpt is None:
            return None
        if ckpt.meta.get("kind") != "solver_progress":
            raise ValueError(
                f"checkpoint at {self.directory} is not a streaming-solver "
                f"checkpoint (kind={ckpt.meta.get('kind')!r}); use a fresh "
                "checkpoint directory"
            )
        mismatch = fingerprint_mismatch(ckpt.meta.get("fingerprint"),
                                        fingerprint)
        if mismatch is not None:
            raise ValueError(
                f"streaming checkpoint at {self.directory} was written "
                f"under a different solve fingerprint ({mismatch}); resume "
                "with the original λ grid/optimizer/input, or use a fresh "
                "checkpoint directory"
            )
        completed = [
            (float(lam), ckpt.arrays[f"completed/{i:04d}"])
            for i, lam in enumerate(ckpt.meta.get("completed_lambdas", []))
        ]
        state_fields = ckpt.meta.get("state_fields") or []
        state_arrays = (
            {name: ckpt.arrays[f"state/{name}"] for name in state_fields}
            if state_fields else None
        )
        return SolverProgress(
            lam_index=int(ckpt.meta["lam_index"]),
            iteration=int(ckpt.meta["iteration"]),
            epochs_total=int(ckpt.meta.get("epochs_total", 0)),
            epochs_lambda=int(ckpt.meta.get("epochs_lambda", 0)),
            completed=completed,
            state_arrays=state_arrays,
        )
