"""Mid-training checkpoint / resume for GAME coordinate descent.

The reference has **no** mid-training checkpoints: recovery is Spark lineage
recompute plus coarse warm-start from models saved per optimization config
(SURVEY.md §5; GameTrainingDriver.scala:748-815, GameEstimator.scala:392-411).
This module goes beyond it with first-class checkpoint/resume:

- ``TrainingCheckpointer`` writes one atomic step directory per save
  (``step_<k>/`` with ``arrays.npz`` + ``meta.json`` + per-coordinate entity
  key vocabularies), prunes to ``max_to_keep``, and restores the latest
  intact step. Atomicity = write to a temp dir, ``os.replace`` into place —
  a crash mid-save never corrupts the latest good checkpoint.
- ``run_coordinate_descent(..., checkpointer=...)`` (algorithm/
  coordinate_descent.py) saves after every coordinate update and fast-
  forwards past completed updates on resume.
- ``train_distributed(..., checkpointer=...)`` (parallel/distributed.py)
  saves the mesh-sharded ``GameTrainState`` per CD sweep; arrays are pulled
  to host with ``jax.device_get`` (works for sharded arrays — all shards on
  this host are gathered) and re-sharded on restore by the caller's
  ``shard_inputs``.

Checkpoints are plain numpy + JSON: portable across backends (save on TPU,
restore on CPU), no framework version pinning, diffable metadata.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shutil
import tempfile
import zipfile
from typing import Any, Mapping

import jax
import numpy as np

logger = logging.getLogger(__name__)

from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (
    DatumScoringModel,
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.models.matrix_factorization import MatrixFactorizationModel
from photon_ml_tpu.types import TaskType

_STEP_PREFIX = "step_"
_META_FILE = "meta.json"
_ARRAYS_FILE = "arrays.npz"


@dataclasses.dataclass
class Checkpoint:
    """One restored checkpoint: step id, array pytree, JSON metadata."""

    step: int
    arrays: dict[str, np.ndarray]
    meta: dict[str, Any]


class TrainingCheckpointer:
    """Atomic, pruned, numbered checkpoints under one directory.

    Layout::

        <directory>/
          step_00000007/
            arrays.npz     flat {key: array} — numeric state
            meta.json      structure + scalars (task types, shard ids, ...)
          step_00000008/
            ...

    ``save`` never leaves a partially-written ``step_*`` dir: content goes to
    a ``tmp.*`` sibling first and is renamed into place, then older steps are
    pruned down to ``max_to_keep``.
    """

    def __init__(self, directory: str | os.PathLike, *, max_to_keep: int = 3):
        self.directory = str(directory)
        self.max_to_keep = max(1, int(max_to_keep))
        os.makedirs(self.directory, exist_ok=True)

    # -- core save/restore ---------------------------------------------------

    def save(self, step: int, arrays: Mapping[str, np.ndarray], meta: dict) -> str:
        step_dir = os.path.join(self.directory, f"{_STEP_PREFIX}{step:08d}")
        tmp_dir = tempfile.mkdtemp(prefix="tmp.", dir=self.directory)
        try:
            host_arrays = {k: np.asarray(jax.device_get(v)) for k, v in arrays.items()}
            np.savez(os.path.join(tmp_dir, _ARRAYS_FILE), **host_arrays)
            with open(os.path.join(tmp_dir, _META_FILE), "w") as f:
                json.dump({"step": step, **meta}, f, indent=2, default=str)
            if os.path.isdir(step_dir):
                shutil.rmtree(step_dir)
            os.replace(tmp_dir, step_dir)
        except BaseException:
            shutil.rmtree(tmp_dir, ignore_errors=True)
            raise
        self._prune()
        return step_dir

    def steps(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith(_STEP_PREFIX):
                path = os.path.join(self.directory, name)
                # intact = both files present (a partially-pruned or
                # partially-deleted dir must not be offered for restore)
                if os.path.isfile(os.path.join(path, _META_FILE)) and os.path.isfile(
                    os.path.join(path, _ARRAYS_FILE)
                ):
                    try:
                        out.append(int(name[len(_STEP_PREFIX):]))
                    except ValueError:
                        continue
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    #: everything a truncated/garbled step file can raise during load:
    #: zip directory damage (BadZipFile), npz entry damage (zlib via
    #: ValueError/OSError), meta damage (JSONDecodeError is a ValueError)
    _CORRUPT_ERRORS = (
        OSError,
        EOFError,
        ValueError,
        KeyError,
        zipfile.BadZipFile,
    )

    def _load(self, step: int) -> Checkpoint:
        step_dir = os.path.join(self.directory, f"{_STEP_PREFIX}{step:08d}")
        with open(os.path.join(step_dir, _META_FILE)) as f:
            meta = json.load(f)
        with np.load(os.path.join(step_dir, _ARRAYS_FILE), allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files}
        return Checkpoint(step=step, arrays=arrays, meta=meta)

    def restore(self, step: int | None = None) -> Checkpoint | None:
        """Restore ``step`` (default: NEWEST step that actually loads).

        A ``step_<k>/`` dir whose ``arrays.npz`` or ``meta.json`` is
        truncated/garbled (external damage — the atomic save never
        produces one) is skipped with a warning and the next older step is
        tried, so resume degrades to the newest INTACT step instead of
        aborting. Returns None when no step loads; raises ValueError for
        an explicitly-requested step that is missing, and the underlying
        error for one that is present but corrupt (an explicit request
        must not silently resolve to a different step).
        """
        if step is not None:
            if step not in self.steps():
                raise ValueError(
                    f"checkpoint step {step} not found (intact steps: "
                    f"{self.steps()})"
                )
            return self._load(step)
        for candidate in reversed(self.steps()):
            try:
                return self._load(candidate)
            except self._CORRUPT_ERRORS as e:
                logger.warning(
                    "checkpoint step %d at %s is corrupt (%s: %s); falling "
                    "back to the previous step",
                    candidate, self.directory, type(e).__name__, e,
                )
        return None

    def _loadable(self, step: int) -> bool:
        """Cheap integrity probe for pruning decisions: meta parses and the
        npz's zip central directory (stored at end of file — the first
        casualty of truncation) reads. Full CRC verification is restore's
        job; pruning must not re-read multi-GB arrays."""
        step_dir = os.path.join(self.directory, f"{_STEP_PREFIX}{step:08d}")
        try:
            with open(os.path.join(step_dir, _META_FILE)) as f:
                json.load(f)
            with zipfile.ZipFile(os.path.join(step_dir, _ARRAYS_FILE)) as z:
                z.namelist()
            return True
        except self._CORRUPT_ERRORS:
            return False

    def _prune(self) -> None:
        steps = self.steps()
        doomed = steps[: -self.max_to_keep]
        if not doomed:
            return
        kept = steps[-self.max_to_keep:]
        if not any(self._loadable(s) for s in kept):
            # every kept step is damaged: protect the newest loadable step
            # among the prune candidates — pruning must never delete the
            # last checkpoint a resume could actually restore
            for s in reversed(doomed):
                if self._loadable(s):
                    logger.warning(
                        "keeping checkpoint step %d beyond max_to_keep=%d: "
                        "it is the newest loadable step (%s newer steps "
                        "are corrupt)",
                        s, self.max_to_keep, len(kept),
                    )
                    doomed = [d for d in doomed if d != s]
                    break
        for s in doomed:
            shutil.rmtree(
                os.path.join(self.directory, f"{_STEP_PREFIX}{s:08d}"),
                ignore_errors=True,
            )


# -- GAME model (de)serialization to flat array dicts -------------------------


def game_model_to_arrays(model: GameModel) -> tuple[dict[str, np.ndarray], dict]:
    """Flatten a GameModel into (arrays, structure-metadata) for checkpointing."""
    arrays: dict[str, np.ndarray] = {}
    coords_meta: dict[str, dict] = {}
    for cid, sub in model.models.items():
        if isinstance(sub, FixedEffectModel):
            arrays[f"{cid}/means"] = np.asarray(sub.glm.coefficients.means)
            if sub.glm.coefficients.variances is not None:
                arrays[f"{cid}/variances"] = np.asarray(sub.glm.coefficients.variances)
            coords_meta[cid] = {
                "kind": "fixed",
                "feature_shard_id": sub.feature_shard_id,
                "task": sub.glm.task.name,
            }
        elif isinstance(sub, RandomEffectModel):
            arrays[f"{cid}/coefficients"] = np.asarray(sub.coefficients)
            arrays[f"{cid}/entity_keys"] = np.asarray(sub.entity_keys)
            if sub.variances is not None:
                arrays[f"{cid}/variances"] = np.asarray(sub.variances)
            coords_meta[cid] = {
                "kind": "random",
                "random_effect_type": sub.random_effect_type,
                "feature_shard_id": sub.feature_shard_id,
                "task": sub.task.name,
            }
        elif isinstance(sub, MatrixFactorizationModel):
            arrays[f"{cid}/row_factors"] = np.asarray(sub.row_factors)
            arrays[f"{cid}/col_factors"] = np.asarray(sub.col_factors)
            arrays[f"{cid}/row_keys"] = np.asarray(sub.row_keys)
            arrays[f"{cid}/col_keys"] = np.asarray(sub.col_keys)
            coords_meta[cid] = {
                "kind": "matrix_factorization",
                "row_effect_type": sub.row_effect_type,
                "col_effect_type": sub.col_effect_type,
                "task": sub.task.name,
            }
        else:
            raise TypeError(f"Cannot checkpoint sub-model type {type(sub)!r}")
    return arrays, {"coordinates": coords_meta, "order": list(model.models)}


def _with_prefix(arrays: Mapping[str, np.ndarray], prefix: str) -> dict[str, np.ndarray]:
    return {f"{prefix}{k}": v for k, v in arrays.items()}


def _strip_prefix(arrays: Mapping[str, np.ndarray], prefix: str) -> dict[str, np.ndarray]:
    return {k[len(prefix):]: v for k, v in arrays.items() if k.startswith(prefix)}


def pack_cd_state(
    model: GameModel,
    best_model: GameModel | None,
    best_metric: float,
    metric_history: list[dict],
) -> tuple[dict[str, np.ndarray], dict]:
    """Flatten full coordinate-descent progress (current + best model) for save."""
    arrays, model_meta = game_model_to_arrays(model)
    out = _with_prefix(arrays, "model/")
    meta: dict[str, Any] = {
        "model": model_meta,
        "best_metric": None if np.isnan(best_metric) else float(best_metric),
        "metric_history": metric_history,
    }
    if best_model is not None:
        best_arrays, best_meta = game_model_to_arrays(best_model)
        out.update(_with_prefix(best_arrays, "best/"))
        meta["best"] = best_meta
    return out, meta


def unpack_cd_state(
    ckpt: Checkpoint,
) -> tuple[GameModel, GameModel | None, float, list[dict]]:
    """Inverse of :func:`pack_cd_state`."""
    model = game_model_from_arrays(_strip_prefix(ckpt.arrays, "model/"), ckpt.meta["model"])
    best_model = None
    if "best" in ckpt.meta and ckpt.meta["best"] is not None:
        best_model = game_model_from_arrays(
            _strip_prefix(ckpt.arrays, "best/"), ckpt.meta["best"]
        )
    raw = ckpt.meta.get("best_metric")
    best_metric = float("nan") if raw is None else float(raw)
    return model, best_model, best_metric, list(ckpt.meta.get("metric_history", []))


class DivergenceError(RuntimeError):
    """Raised when training state goes non-finite (failure detection).

    The reference relies on Spark lineage recompute and has no divergence
    handling (SURVEY.md §5); here a non-finite coordinate update is caught at
    the CD level so the driver can restore the last good checkpoint instead
    of silently training on NaNs.
    """


def game_model_from_arrays(
    arrays: Mapping[str, np.ndarray], meta: Mapping[str, Any]
) -> GameModel:
    """Inverse of :func:`game_model_to_arrays`."""
    models: dict[str, DatumScoringModel] = {}
    coords_meta = meta["coordinates"]
    for cid in meta["order"]:
        info = coords_meta[cid]
        task = TaskType[info["task"]]
        variances = arrays.get(f"{cid}/variances")
        if info["kind"] == "fixed":
            glm = GeneralizedLinearModel(
                coefficients=Coefficients(
                    means=arrays[f"{cid}/means"], variances=variances
                ),
                task=task,
            )
            models[cid] = FixedEffectModel(
                glm=glm, feature_shard_id=info["feature_shard_id"]
            )
        elif info["kind"] == "random":
            models[cid] = RandomEffectModel(
                coefficients=arrays[f"{cid}/coefficients"],
                entity_keys=arrays[f"{cid}/entity_keys"],
                random_effect_type=info["random_effect_type"],
                feature_shard_id=info["feature_shard_id"],
                task=task,
                variances=variances,
            )
        elif info["kind"] == "matrix_factorization":
            models[cid] = MatrixFactorizationModel(
                row_factors=arrays[f"{cid}/row_factors"],
                col_factors=arrays[f"{cid}/col_factors"],
                row_effect_type=info["row_effect_type"],
                col_effect_type=info["col_effect_type"],
                row_keys=arrays[f"{cid}/row_keys"],
                col_keys=arrays[f"{cid}/col_keys"],
                task=task,
            )
        else:
            raise ValueError(f"Unknown checkpoint coordinate kind {info['kind']!r}")
    return GameModel(models=models)
