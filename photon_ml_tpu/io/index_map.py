"""Feature index maps: (name, term) -> dense column index.

Reference parity: photon-api index/IndexMap.scala (iface),
DefaultIndexMap(Loader) (on-heap from distinct), PalDBIndexMap (off-heap
partitioned stores), and the client's Constants (DELIMITER="\\u0001",
INTERCEPT_NAME="(INTERCEPT)", reference photon-lib Constants.scala:31-42).

TPU-native: the index map is host-side metadata — it never reaches the
device. Persistence is a sorted key file + JSON metadata; the off-heap,
memory-mapped variant (PalDB equivalent, for billion-feature maps that must
not live on the Python heap) is provided by the native runtime
(photon_ml_tpu.runtime.native_index, C++ mmap hash store) with this module
as the contract and fallback.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Iterator, Mapping

DELIMITER = ""
INTERCEPT_NAME = "(INTERCEPT)"
INTERCEPT_TERM = ""


def feature_key(name: str, term: str = "") -> str:
    """Reference Utils.getFeatureKey: name + DELIMITER + term."""
    return f"{name}{DELIMITER}{term}"


def split_feature_key(key: str) -> tuple[str, str]:
    name, _, term = key.partition(DELIMITER)
    return name, term


INTERCEPT_KEY = feature_key(INTERCEPT_NAME, INTERCEPT_TERM)


class IndexMap(Mapping[str, int]):
    """Immutable feature-key -> index map with reverse lookup.

    Reference IndexMap: getIndex / getFeatureName + the map contract.
    """

    def __init__(self, key_to_index: dict[str, int]):
        self._forward = dict(key_to_index)
        self._reverse: dict[int, str] | None = None

    # Mapping protocol -------------------------------------------------------
    def __getitem__(self, key: str) -> int:
        return self._forward[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._forward)

    def __len__(self) -> int:
        return len(self._forward)

    # Reference API ----------------------------------------------------------
    def get_index(self, key: str) -> int:
        """-1 when absent (reference IndexMap.NULL_KEY semantics)."""
        return self._forward.get(key, -1)

    def get_feature_name(self, index: int) -> str | None:
        if self._reverse is None:
            self._reverse = {v: k for k, v in self._forward.items()}
        return self._reverse.get(index)

    @property
    def size(self) -> int:
        return len(self._forward)

    @property
    def has_intercept(self) -> bool:
        return INTERCEPT_KEY in self._forward

    @property
    def intercept_index(self) -> int | None:
        idx = self._forward.get(INTERCEPT_KEY)
        return idx

    # Construction -----------------------------------------------------------
    @classmethod
    def from_keys(cls, keys: Iterable[str], *, add_intercept: bool = False) -> "IndexMap":
        """Build from distinct feature keys, sorted for determinism
        (reference DefaultIndexMapLoader sorts distinct keys)."""
        distinct = sorted(set(keys))
        mapping = {k: i for i, k in enumerate(distinct)}
        if add_intercept and INTERCEPT_KEY not in mapping:
            mapping[INTERCEPT_KEY] = len(mapping)
        return cls(mapping)

    @classmethod
    def from_name_terms(
        cls, pairs: Iterable[tuple[str, str]], *, add_intercept: bool = False
    ) -> "IndexMap":
        return cls.from_keys((feature_key(n, t) for n, t in pairs),
                             add_intercept=add_intercept)

    # Persistence ------------------------------------------------------------
    @staticmethod
    def list_directory(directory: str | os.PathLike) -> set[str]:
        """Shard names present in a stores directory, from filenames alone —
        no store is opened (cheap existence/coverage validation)."""
        from photon_ml_tpu.io.paldb import PARTITION_RE

        shards: set[str] = set()
        for fname in os.listdir(str(directory)):
            if fname.endswith(".keys"):
                shards.add(fname[: -len(".keys")])
            elif fname.endswith(".photonix.json"):
                shards.add(fname[: -len(".photonix.json")])
            elif m := PARTITION_RE.match(fname):
                shards.add(m.group("ns"))
        return shards

    @staticmethod
    def load_directory(directory: str | os.PathLike) -> dict[str, "IndexMap"]:
        """Load every index map in a directory, both formats: plain
        ``<shard>.keys`` files and partitioned native off-heap stores
        (``<shard>.photonix.json``; reference PalDB stores). Returns
        shard id -> Mapping (OffHeapIndexMap is a drop-in)."""
        from photon_ml_tpu.io.paldb import PARTITION_RE, load_paldb_index_map

        maps: dict[str, IndexMap] = {}
        directory = str(directory)
        for fname in sorted(os.listdir(directory)):
            if fname.endswith(".identity.json"):
                shard = fname[: -len(".identity.json")]
                if shard not in maps:
                    with open(os.path.join(directory, fname)) as f:
                        meta = json.load(f)
                    maps[shard] = IdentityIndexMap(
                        meta["dim"], intercept_index=meta.get("intercept_index")
                    )
            elif fname.endswith(".keys"):
                shard = fname[: -len(".keys")]
                if shard not in maps:
                    maps[shard] = IndexMap.load(directory, shard)
            elif fname.endswith(".photonix.json"):
                shard = fname[: -len(".photonix.json")]
                if shard not in maps:
                    from photon_ml_tpu.io.offheap_index_map import OffHeapIndexMap

                    maps[shard] = OffHeapIndexMap(directory, shard)
            elif m := PARTITION_RE.match(fname):
                # reference-written JVM PalDB stores: migration read path
                shard = m.group("ns")
                if shard not in maps:
                    maps[shard] = load_paldb_index_map(directory, shard)
        return maps

    def save(self, directory: str | os.PathLike, name: str = "index") -> str:
        """Write ``<name>.keys`` (one key per line, index order) +
        ``<name>.meta.json``. Keys may contain the \\u0001 delimiter; lines
        are the unit, so keys must not contain newlines."""
        os.makedirs(directory, exist_ok=True)
        ordered = sorted(self._forward.items(), key=lambda kv: kv[1])
        expected = list(range(len(ordered)))
        if [i for _, i in ordered] != expected:
            raise ValueError("index map indices must be dense 0..n-1 to save")
        keys_path = os.path.join(directory, f"{name}.keys")
        with open(keys_path, "w", encoding="utf-8") as f:
            for k, _ in ordered:
                f.write(k + "\n")
        with open(os.path.join(directory, f"{name}.meta.json"), "w") as f:
            json.dump({"size": len(ordered), "format": "photon-ml-tpu/index/v1"}, f)
        return keys_path

    @classmethod
    def load(cls, directory: str | os.PathLike, name: str = "index") -> "IndexMap":
        keys_path = os.path.join(directory, f"{name}.keys")
        with open(keys_path, encoding="utf-8") as f:
            mapping = {line.rstrip("\n"): i for i, line in enumerate(f)}
        return cls(mapping)


class IdentityIndexMap(IndexMap):
    """An O(1) virtual map for PRE-INDEXED feature spaces: key "<j>" (term
    empty, with or without the delimiter) maps to integer j for
    0 <= j < dim; nothing is materialized (reference
    IdentityIndexMapLoader, used when data carries numeric feature ids).

    This is how a literal d=10⁹ coordinate flows through the product path
    (config -> reader -> estimator): the reference sizes its feature space
    by name-term maps (off-heap PalDB at production scale), which caps any
    in-test dimension at the number of DISTINCT OBSERVED names; pre-indexed
    data (LibSVM integer columns, hashing-trick features) needs no such
    materialization. Iteration is refused above a size guard — callers that
    enumerate entries (feature-stats writers) must special-case this type.
    """

    _ITER_GUARD = 1 << 20

    def __init__(self, dim: int, *, intercept_index: int | None = None):
        # deliberately NOT calling super().__init__: no dict exists
        self._dim = int(dim)
        self._intercept = intercept_index

    def __getitem__(self, key: str) -> int:
        idx = self.get_index(key)
        if idx < 0:
            raise KeyError(key)
        return idx

    def __iter__(self):
        if self._dim > self._ITER_GUARD:
            raise RuntimeError(
                f"refusing to enumerate a {self._dim}-entry IdentityIndexMap "
                "(pre-indexed giant-d space); handle this map by index"
            )
        return (feature_key(str(i), "") for i in range(self._dim))

    def __len__(self) -> int:
        return self._dim

    def get_index(self, key: str) -> int:
        if self._intercept is not None and key == INTERCEPT_KEY:
            return self._intercept
        name, term = split_feature_key(key)
        if term:
            return -1
        try:
            j = int(name)
        except ValueError:
            return -1
        return j if 0 <= j < self._dim else -1

    def get_feature_name(self, index: int) -> str | None:
        if self._intercept is not None and index == self._intercept:
            return INTERCEPT_KEY
        if 0 <= index < self._dim:
            return feature_key(str(index), "")
        return None

    @property
    def size(self) -> int:
        return self._dim

    @property
    def has_intercept(self) -> bool:
        return self._intercept is not None

    @property
    def intercept_index(self) -> int | None:
        return self._intercept

    def save(self, directory: str | os.PathLike, name: str = "index") -> str:
        """Persist as a tiny ``<name>.identity.json`` marker (dim only) —
        no key material exists to write."""
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, f"{name}.identity.json")
        with open(path, "w") as f:
            json.dump({
                "dim": self._dim, "intercept_index": self._intercept,
                "format": "photon-ml-tpu/identity-index/v1",
            }, f)
        return path
