"""Serving-path telemetry: latency-SLO histograms + micro-batch counters.

No reference analogue as code: the reference's scoring is an offline batch
job (photon-client cli/game/scoring/GameScoringDriver.scala) whose only
evidence is Spark task metrics; an online resident scorer lives or dies by
its latency distribution, so the serving layer (photon_ml_tpu/serving/)
feeds the process-wide metrics registry with exactly the SLO evidence an
operator needs: per-request latency p50/p95 (``time.perf_counter`` spans —
lint check 11), queue depth, request/batch/row counts, and the pad
fraction the shape-bucket discipline costs.

Names are constants so producers (serving/resident.py, serving/batching.py)
and consumers (tests, journals, bench.py, cli/serve_driver.py) cannot
drift — the same contract as telemetry/stream_counters.py.
"""

from __future__ import annotations

from photon_ml_tpu.telemetry.registry import default_registry

#: prefix shared by every serving metric (reset_serving_metrics)
SERVING_METRIC_PREFIX = "serve/"
#: submit-to-result latency per request (ms): the SLO histogram — its
#: p50/p95 are what the serve driver reports and bench.py prices
LATENCY_MS = "serve/latency_ms"
#: bounded request-queue depth observed at each enqueue/dequeue
QUEUE_DEPTH = "serve/queue_depth"
#: requests accepted into the queue
REQUESTS = "serve/requests"
#: device dispatches the micro-batching loop issued (coalesced flushes)
BATCHES = "serve/batches"
#: true rows scored (request rows, pads excluded)
ROWS = "serve/rows"
#: pad rows the shape-bucket discipline added on top of ROWS
PADDED_ROWS = "serve/padded_rows"
#: cumulative padded_rows / (rows + padded_rows) — the bucket-set tax
PAD_FRACTION = "serve/pad_fraction"
#: requests that failed (poisoned input, scoring error) — each one
#: attributed to its request id, never fatal to the serving loop
REQUEST_FAILURES = "serve/request_failures"
#: distinct (shape-bucket, layout) program signatures the resident scorer
#: has scored through — bounded by the configured bucket set, which is the
#: whole point (one compile per signature, zero per-request compiles)
COMPILED_SIGNATURES = "serve/compiled_signatures"
#: over-sized requests split across micro-batches instead of compiling a
#: fresh signature (the bucket-miss rule)
BUCKET_SPLITS = "serve/bucket_splits"
#: bytes of placed model params resident in the layout-keyed cache
#: (parallel/scoring.py params_for_layouts) — the resident half of the
#: program ledger's HBM-overcommit forecast (telemetry/program_ledger.py)
RESIDENT_PARAMS_BYTES = "serve/resident_params_bytes"
#: in-place model refreshes accepted by the guarded swap API
#: (serving/resident.py swap_model — zero recompiles on a same-layout swap)
MODEL_SWAPS = "serve/model_swaps"
#: swaps REJECTED typed by the layout fingerprint guard — the serving loop
#: keeps running on the resident model after each one
SWAP_REJECTED = "serve/swap_rejected"


def reset_serving_metrics(registry=None) -> None:
    """Drop per-run serving metrics — the serve driver calls this at run
    start (next to ``reset_resilience_metrics``) and again between its
    embedded unbatched baseline and the batched replay, so the journal
    snapshot carries only the replay's own latency distribution."""
    reg = registry or default_registry()
    reg.remove_prefix(SERVING_METRIC_PREFIX)


def record_request_latency_ms(ms: float) -> None:
    default_registry().histogram(LATENCY_MS).observe(float(ms))


def set_queue_depth(depth: int) -> None:
    default_registry().gauge(QUEUE_DEPTH).set(int(depth))


def record_request(n: int = 1) -> None:
    default_registry().counter(REQUESTS).inc(int(n))


def record_request_failure(n: int = 1) -> None:
    default_registry().counter(REQUEST_FAILURES).inc(int(n))


def record_batch() -> None:
    default_registry().counter(BATCHES).inc()


def record_scored(rows: int, padded_rows: int) -> None:
    """One scored micro-batch's row accounting; refreshes the cumulative
    pad-fraction gauge."""
    reg = default_registry()
    reg.counter(ROWS).inc(int(rows))
    reg.counter(PADDED_ROWS).inc(int(padded_rows))
    total = reg.counter(ROWS).value + reg.counter(PADDED_ROWS).value
    if total:
        reg.gauge(PAD_FRACTION).set(
            reg.counter(PADDED_ROWS).value / total
        )


def set_compiled_signatures(n: int) -> None:
    default_registry().gauge(COMPILED_SIGNATURES).set(int(n))


def set_resident_params_bytes(n: int) -> None:
    default_registry().gauge(RESIDENT_PARAMS_BYTES).set(int(n))


def record_model_swap() -> None:
    default_registry().counter(MODEL_SWAPS).inc()


def record_swap_rejected() -> None:
    default_registry().counter(SWAP_REJECTED).inc()


def record_bucket_split(n: int = 1) -> None:
    default_registry().counter(BUCKET_SPLITS).inc(int(n))


def latency_summary() -> dict:
    return default_registry().histogram(LATENCY_MS).summary()


def pad_fraction() -> float:
    value = default_registry().gauge(PAD_FRACTION).value
    return float(value or 0.0)
