"""Device/runtime probes: compile counts, HBM bytes, marginal timing.

Reference parity: no reference analogue — Photon-ML leaned on the Spark UI
for executor/runtime attribution (SURVEY.md §5); on the tunneled TPU
platform the measurement discipline itself is load-bearing and lives here
as a library instead of inside ``bench.py``:

- ``MarginalTimer`` / ``scan_step_marginal``: the BASELINE.md methodology —
  K_hi-vs-K_lo differencing with host-read synchronization, because
  per-call tunnel dispatch is ~80-110 ms with tens of ms of jitter and
  ``block_until_ready`` does not synchronize on this platform (CLAUDE.md).
- ``stream_calibration``: the same-run chip-speed probe
  (``fe_hot_loop_stream_gbps``) as a callable, so ANY experiment can
  normalize its marginals against this run's chip instead of comparing
  absolute GB/s across the chip-lottery pool.
- ``install_compile_listener`` / ``CompileMonitor``: jax.monitoring hook
  counting backend compiles (recompilation storms are a classic silent
  perf pathology under vmap/jit churn).
- ``live_buffer_bytes``: live device-buffer HBM bytes (allocator stats on
  real TPUs, live-array sum on backends without ``memory_stats``).

Everything imports jax lazily so this module is safe to import before the
platform is chosen (bench.py / driver startup order).
"""

from __future__ import annotations

import dataclasses
import statistics
import time
from typing import Callable

import numpy as np

from photon_ml_tpu.telemetry.registry import default_registry

#: median-of-K reps for gate metrics (chip-lottery pool: single-shot numbers
#: swing ~2x between back-to-back reps — BASELINE.md tenancy study)
GATE_REPS = 3


def median_spread(measure_once: Callable[[], float], reps: int = GATE_REPS):
    """Run a marginal measurement ``reps`` times; return
    (median, [min, max]). The spread is the honest error bar for
    round-over-round comparisons on the shared-chip pool."""
    vals = [measure_once() for _ in range(reps)]
    return statistics.median(vals), [min(vals), max(vals)]


def read_scalar(x) -> float:
    """Host-read synchronization point: returns float(x), forcing the device
    stream to drain. The ONLY reliable sync on tunneled platforms."""
    return float(np.asarray(x).ravel()[0])


@dataclasses.dataclass
class MarginalResult:
    median: float  # marginal seconds per unit of work
    spread: list  # [min, max] across reps


@dataclasses.dataclass
class MarginalTimer:
    """K_hi-vs-K_lo marginal differencing over an arbitrary timed unit.

    ``measure(timed_k)`` calls ``timed_k(k)`` — which must run ``k`` units
    of work and return elapsed seconds, ending on a host read (use
    :func:`read_scalar`) — and returns the per-unit marginal
    ``(t(k_hi) - t(k_lo)) / (k_hi - k_lo)`` as a median-of-``reps`` with
    [min, max] spread. Differencing cancels the fixed per-call dispatch
    cost; ``k_hi - k_lo`` must be large enough that device time dwarfs the
    dispatch jitter (an 80-eval spread has produced NEGATIVE marginals —
    CLAUDE.md)."""

    k_lo: int = 1
    k_hi: int = 5
    reps: int = GATE_REPS
    floor: float = 1e-6

    def __post_init__(self):
        if self.k_hi <= self.k_lo:
            raise ValueError(f"k_hi ({self.k_hi}) must exceed k_lo ({self.k_lo})")

    def measure(self, timed_k: Callable[[int], float]) -> MarginalResult:
        def once() -> float:
            lo = timed_k(self.k_lo)
            hi = timed_k(self.k_hi)
            return max((hi - lo) / (self.k_hi - self.k_lo), self.floor)

        median, spread = median_spread(once, self.reps)
        return MarginalResult(median=median, spread=spread)


def scan_step_marginal(
    step_fn,
    operand,
    dim: int,
    *,
    k_lo: int = 16,
    k_hi: int = 256,
    reps: int = GATE_REPS,
    warmups: int = 4,
    rng=None,
) -> tuple[float, list]:
    """Marginal seconds per evaluation of ``step_fn(w, operand) -> (w', v)``.

    K evaluations run inside ONE jit via ``lax.scan`` (so the K_hi-K_lo
    delta is pure device time), every step consumes the carry (defeats
    XLA loop-invariant hoisting — CLAUDE.md), warm starts are perturbed per
    rep (some backends cache repeat executions), and timing ends on a host
    read. Returns ``(median, [min, max])`` like :func:`median_spread`."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(7) if rng is None else rng

    def timed(k: int) -> float:
        @jax.jit
        def run(w0, op):
            w, vs = jax.lax.scan(
                lambda w, _: step_fn(w, op), w0, None, length=k
            )
            return vs.sum() + w.sum()

        float(run(jnp.zeros(dim, jnp.float32), operand))  # compile + sync
        best = None
        for _ in range(warmups):
            w0 = jnp.asarray(rng.normal(size=dim).astype(np.float32)) * 0.01
            t0 = time.perf_counter()
            float(run(w0, operand))
            el = time.perf_counter() - t0
            best = el if best is None or el < best else best
        return best

    return median_spread(
        lambda: max((timed(k_hi) - timed(k_lo)) / (k_hi - k_lo), 1e-6), reps
    )


def stream_calibration(
    features,
    *,
    k_lo: int = 16,
    k_hi: int = 256,
    reps: int = GATE_REPS,
    rng=None,
) -> dict:
    """Same-run chip-speed calibration: achieved GB/s of one [n, d] matvec
    X read per step. The pool's chips vary run to run (567-747 GB/s across
    rounds of one process — BASELINE.md), so hot-loop fractions are only
    meaningful against THIS probe measured in the same process. Note the
    probe is an XLA matvec and slightly underestimates peak (the Pallas
    kernel sustains ~1.1x it), so fractions > 1.0 are real."""
    import jax.numpy as jnp

    n, d = features.shape
    xbytes = n * d * features.dtype.itemsize

    def step(w, x):
        return w + jnp.sum(x @ w) * 1e-30, jnp.float32(0)

    marginal, spread = scan_step_marginal(
        step, features, d, k_lo=k_lo, k_hi=k_hi, reps=reps, rng=rng
    )
    return {
        "gbps": xbytes / marginal / 1e9,
        "spread_gbps": [xbytes / s / 1e9 for s in spread[::-1]],
        "marginal_sec": marginal,
        "spread_sec": spread,
        "bytes_per_eval": xbytes,
        "n": int(n),
        "d": int(d),
    }


# --- compile-event monitoring (jax.monitoring) ------------------------------

#: registry names of the backend-compile counter/histogram the listener
#: feeds — public so the program ledger (telemetry/program_ledger.py) can
#: take scoped deltas against them and heartbeats can snapshot the count
COMPILE_COUNT_METRIC = "jax/backend_compile_count"
COMPILE_SECONDS_METRIC = "jax/backend_compile_seconds"
_COMPILE_COUNTER = COMPILE_COUNT_METRIC
_COMPILE_SECONDS = COMPILE_SECONDS_METRIC
#: registries that already have a listener feeding them (the listener holds
#: a strong reference, so the id() stays unique for the registry's lifetime)
_installed_registry_ids: set[int] = set()


def install_compile_listener(registry=None) -> None:
    """Idempotently (per registry) install a jax.monitoring duration
    listener that counts backend compiles into the metrics registry.
    jax.monitoring has no targeted unregister, so each listener installs
    once per (process, registry) and stays."""
    reg = registry or default_registry()
    if id(reg) in _installed_registry_ids:
        return
    import jax.monitoring

    def _on_duration(name: str, secs: float, **kw) -> None:
        if "backend_compile" in name:
            reg.counter(_COMPILE_COUNTER).inc()
            reg.histogram(_COMPILE_SECONDS).observe(secs)

    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    _installed_registry_ids.add(id(reg))


def compile_count(registry=None) -> int:
    """Backend compiles observed since :func:`install_compile_listener`."""
    reg = registry or default_registry()
    return reg.counter(_COMPILE_COUNTER).value


class CompileMonitor:
    """``with CompileMonitor() as cm: ...; cm.count`` — compiles (and compile
    seconds) attributable to the enclosed block."""

    def __init__(self, registry=None):
        self.registry = registry or default_registry()
        # snapshot at construction too, so count/seconds are well-defined
        # even when read from a finally block after __enter__ failed
        self._count0 = self.registry.counter(_COMPILE_COUNTER).value
        self._secs0 = self.registry.histogram(_COMPILE_SECONDS).total

    def __enter__(self) -> "CompileMonitor":
        install_compile_listener(self.registry)
        self._count0 = self.registry.counter(_COMPILE_COUNTER).value
        self._secs0 = self.registry.histogram(_COMPILE_SECONDS).total
        return self

    def __exit__(self, *exc) -> bool:
        return False

    @property
    def count(self) -> int:
        return self.registry.counter(_COMPILE_COUNTER).value - self._count0

    @property
    def seconds(self) -> float:
        return self.registry.histogram(_COMPILE_SECONDS).total - self._secs0


def live_buffer_bytes(device=None) -> int:
    """Live device-buffer bytes: allocator ``bytes_in_use`` where the
    backend exposes memory_stats (real TPUs), else the sum over
    ``jax.live_arrays()`` (virtual CPU meshes)."""
    import jax

    dev = device or jax.local_devices()[0]
    try:
        stats = dev.memory_stats()
    except Exception:
        stats = None
    if stats and "bytes_in_use" in stats:
        return int(stats["bytes_in_use"])
    return int(sum(a.nbytes for a in jax.live_arrays()))


def device_memory_limit_bytes(device=None) -> "int | None":
    """Allocator ``bytes_limit`` where the backend reports one (real TPUs);
    None on backends without memory_stats (virtual CPU meshes) — the
    capability-probe shape of :func:`live_buffer_bytes`, and the budget the
    program ledger's HBM-overcommit forecast is judged against."""
    import jax

    dev = device or jax.local_devices()[0]
    try:
        stats = dev.memory_stats()
    except Exception:
        stats = None
    if stats and "bytes_limit" in stats:
        return int(stats["bytes_limit"])
    return None
