"""Process-wide metrics registry: counters, gauges, histograms.

Reference parity: photon-lib util/Timed.scala:33-77 recorded named phase
durations and OptimizationStatesTracker.scala:82-101 kept per-iteration
solver state; both report through ad-hoc logging. Here the recording side is
a single typed registry every layer feeds (``util.timed.Timed`` phases,
solver telemetry, compile-event probes), replacing the bare module-level
``_TIMINGS`` dict the drivers used to print from. Snapshots are plain dicts
so the JSONL run journal (telemetry/journal.py) can persist them verbatim.

Thread-safe; no jax dependency — importable before the backend is chosen
(bench.py and the drivers configure platforms after import).
"""

from __future__ import annotations

import math
import threading
from collections import deque

#: histograms keep the most recent observations for percentile estimation;
#: count/total/min/max stay exact over the full stream
HISTOGRAM_WINDOW = 8192


class Counter:
    """Monotonically increasing count (e.g. solver invocations, compiles)."""

    def __init__(self) -> None:
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins scalar (e.g. live HBM bytes, lane count)."""

    def __init__(self) -> None:
        self._value: float | None = None

    def set(self, value: float) -> None:
        self._value = float(value)

    @property
    def value(self) -> float | None:
        return self._value


class Histogram:
    """Streaming distribution: exact count/total/min/max, windowed p50/p95."""

    def __init__(self, window: int = HISTOGRAM_WINDOW) -> None:
        self._lock = threading.Lock()
        self._count = 0
        self._total = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._values: deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._count += 1
            self._total += v
            self._min = min(self._min, v)
            self._max = max(self._max, v)
            self._values.append(v)

    def observe_many(self, values) -> None:
        """Bulk observation under ONE lock acquisition — per-lane feeders
        (thousands of iteration counts per sweep) must not pay a lock
        round-trip per value."""
        vs = [float(v) for v in values]
        if not vs:
            return
        with self._lock:
            self._count += len(vs)
            self._total += sum(vs)
            self._min = min(self._min, min(vs))
            self._max = max(self._max, max(vs))
            self._values.extend(vs)

    @property
    def count(self) -> int:
        return self._count

    @property
    def total(self) -> float:
        return self._total

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile over the retained window; NaN when empty."""
        with self._lock:
            values = sorted(self._values)
        if not values:
            return math.nan
        rank = max(0, min(len(values) - 1, math.ceil(p / 100.0 * len(values)) - 1))
        return values[rank]

    def summary(self) -> dict[str, float]:
        """count/total/mean/min/max/p50/p95 — the shape ``timing_summary``
        reports and the run journal persists."""
        with self._lock:
            count, total = self._count, self._total
            mn, mx = self._min, self._max
        if count == 0:
            return {"count": 0, "total": 0.0, "mean": math.nan,
                    "min": math.nan, "max": math.nan,
                    "p50": math.nan, "p95": math.nan}
        return {
            "count": count,
            "total": total,
            "mean": total / count,
            "min": mn,
            "max": mx,
            "p50": self.percentile(50.0),
            "p95": self.percentile(95.0),
        }


class MetricsRegistry:
    """Named metric store with get-or-create accessors.

    Names are free-form but by convention slash-namespaced
    (``timing/<phase>``, ``solver/<coordinate>/iterations``,
    ``jax/backend_compile_count``) so consumers can select by prefix.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}

    def _get_or_create(self, name: str, cls):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = cls()
                self._metrics[name] = metric
            elif not isinstance(metric, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(metric).__name__}, not {cls.__name__}"
                )
            return metric

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get_or_create(name, Histogram)

    def histograms(self, prefix: str = "") -> dict[str, Histogram]:
        with self._lock:
            return {
                name: m for name, m in self._metrics.items()
                if isinstance(m, Histogram) and name.startswith(prefix)
            }

    def remove_prefix(self, prefix: str) -> None:
        """Drop every metric under ``prefix`` (e.g. per-run phase timings)."""
        with self._lock:
            for name in [n for n in self._metrics if n.startswith(prefix)]:
                del self._metrics[name]

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()

    def snapshot(self) -> dict[str, dict]:
        """JSON-ready view: {"counters": {...}, "gauges": {...},
        "histograms": {name: summary-dict}}."""
        with self._lock:
            items = list(self._metrics.items())
        out: dict[str, dict] = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in items:
            if isinstance(m, Counter):
                out["counters"][name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.value
            else:
                out["histograms"][name] = m.summary()
        return out


#: the process-wide registry ``Timed``, the drivers, and the probes feed
_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _DEFAULT
