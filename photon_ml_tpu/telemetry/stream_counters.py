"""Out-of-core streaming-epoch telemetry: the observable overlap.

Reference parity: the reference's beyond-memory ingestion rode Spark's
per-task input metrics (AvroDataReader.scala work shows up in the task UI
as input bytes/records); here the equivalent evidence for the chunked
streaming pipeline (io/stream_reader.py + algorithm/streaming.py) lives in
the process-wide metrics registry so run journals can prove — on success
AND failure paths — that host decode was actually hidden behind device
compute instead of serialized with it.

Names are constants so the producers (the chunk prefetcher / epoch runner)
and consumers (tests, journals, bench.py) cannot drift.
"""

from __future__ import annotations

from photon_ml_tpu.telemetry.registry import default_registry

#: per-chunk host decode+assembly duration (ms) — fed by the prefetcher
#: for every chunk it produces, prefetch on or off
CHUNK_DECODE_MS = "io/chunk_decode_ms"
#: prefix shared by the epoch-level gauges (reset_stream_metrics)
STREAM_METRIC_PREFIX = "stream/"
#: fraction of total host decode time hidden behind device compute in the
#: most recent epoch: 1 - (consumer wait / total decode), clamped to
#: [0, 1]; 0.0 when prefetch is off (nothing can hide)
OVERLAP_FRACTION = "stream/overlap_fraction"
#: chunk count of the most recent epoch
CHUNKS_PER_EPOCH = "stream/chunks_per_epoch"
#: streamed-GAME run evidence (algorithm/streaming_game.py): total chunk
#: LOADS (source decodes — DuHL working-set cache hits don't count; the
#: cache is exactly what the schedule saves) and chunk VISITS (schedule
#: entries processed by random-effect solves, loads or hits)
GAME_CHUNK_LOADS = "stream/game_chunk_loads"
GAME_CHUNK_VISITS = "stream/game_chunk_visits"
#: sweeps the most recent streamed-GAME train ran (epochs-to-tolerance
#: evidence for the DuHL-vs-uniform comparison)
GAME_SWEEPS = "stream/game_sweeps"


def reset_stream_metrics(registry=None) -> None:
    """Drop per-run streaming metrics — drivers call this at run start next
    to ``reset_solver_metrics``/``reset_layout_metrics`` so each run's
    journal snapshot (taken on success AND failure paths) carries only its
    own epochs' decode histogram and overlap evidence."""
    reg = registry or default_registry()
    reg.remove_prefix(STREAM_METRIC_PREFIX)
    reg.remove_prefix(CHUNK_DECODE_MS)


def record_chunk_decode_ms(ms: float) -> None:
    default_registry().histogram(CHUNK_DECODE_MS).observe(float(ms))


def set_overlap_fraction(fraction: float) -> None:
    default_registry().gauge(OVERLAP_FRACTION).set(float(fraction))


def set_chunks_per_epoch(n: int) -> None:
    default_registry().gauge(CHUNKS_PER_EPOCH).set(int(n))


def overlap_fraction() -> float:
    value = default_registry().gauge(OVERLAP_FRACTION).value
    return float(value or 0.0)


def chunks_per_epoch() -> int:
    value = default_registry().gauge(CHUNKS_PER_EPOCH).value
    return int(value or 0)


def set_game_stream_evidence(
    *, chunk_loads: int, chunk_visits: int, sweeps: int
) -> None:
    default_registry().gauge(GAME_CHUNK_LOADS).set(int(chunk_loads))
    default_registry().gauge(GAME_CHUNK_VISITS).set(int(chunk_visits))
    default_registry().gauge(GAME_SWEEPS).set(int(sweeps))


def game_stream_evidence() -> dict:
    reg = default_registry()
    return {
        "chunk_loads": int(reg.gauge(GAME_CHUNK_LOADS).value or 0),
        "chunk_visits": int(reg.gauge(GAME_CHUNK_VISITS).value or 0),
        "sweeps": int(reg.gauge(GAME_SWEEPS).value or 0),
    }


def chunk_decode_summary() -> dict:
    return default_registry().histogram(CHUNK_DECODE_MS).summary()
