"""Solver telemetry adapter: SolverResult / LaneTrace -> events + journal.

Reference parity: photon-client event/PhotonOptimizationLogEvent (per-
coordinate-update optimization telemetry emitted from Driver.scala:120-393)
+ photon-lib OptimizationStatesTracker.scala:82-101 (the per-iteration state
table reported across coordinates). This module closes that parity gap for
every solve shape in the stack:

- a single un-vmapped solve (the fixed-effect coordinate, sequential
  ``train_glm`` λ steps) → one ``convergence`` row with iteration count,
  convergence reason, value and gradient norm, plus the trimmed
  per-iteration value history;
- vmapped lanes (λ-grid lanes, random-effect entity buckets) → per-lane
  rows (capped) and a ``convergence_lanes`` tally of reasons across lanes,
  so pathologies like "every lane pays max_iter" (CLAUDE.md) show up as
  ``reasons: {"MAX_ITERATIONS": <all lanes>}`` instead of staying silent.

The adapter fans out to any of: a RunJournal (JSONL rows), an EventEmitter
(OptimizationLogEvent per update), and a MetricsRegistry (iteration
histograms / convergence counters). All sinks are optional.
"""

from __future__ import annotations

import numpy as np

from photon_ml_tpu.optim.common import (
    ConvergenceReason,
    LaneTrace,
    LaneTraces,
    SolverResult,
)
from photon_ml_tpu.util.events import EventEmitter, OptimizationLogEvent

#: per-lane rows written to the journal before falling back to tally-only
MAX_LANE_ROWS = 128

#: registry namespace for solver convergence metrics
SOLVER_METRIC_PREFIX = "solver/"

#: cross-coordinate per-lane iteration-count histogram: the lane-iteration
#: DISTRIBUTION the lane scheduler (algorithm/lane_scheduler.py) exists to
#: exploit — fed here for vmapped traces and by the scheduler itself
LANE_ITERS_METRIC = "solver/lane_iters"


def reset_solver_metrics(registry=None) -> None:
    """Drop per-run solver/* AND scheduler/* counters and histograms —
    drivers call this at run start (next to ``reset_timings``) so a sweep
    invoking ``run()`` repeatedly journals per-run tallies, not cross-run
    accumulations."""
    from photon_ml_tpu.telemetry.registry import default_registry

    reg = registry or default_registry()
    reg.remove_prefix(SOLVER_METRIC_PREFIX)
    # literal, not imported: lane_scheduler pulls jax in, and this helper
    # must stay importable/callable before the backend is chosen
    reg.remove_prefix("scheduler/")


def _reason_name(code) -> str:
    try:
        return ConvergenceReason(int(code)).name
    except ValueError:
        return f"UNKNOWN_{int(code)}"


def solver_result_row(
    result: SolverResult,
    *,
    max_history: int = 64,
) -> dict:
    """One journal-ready dict from a scalar (un-vmapped) SolverResult."""
    iterations = int(result.iterations)
    values = np.asarray(result.value_history)
    history = [
        float(v) for v in values[: min(iterations + 1, max_history, len(values))]
        if np.isfinite(v)
    ]
    return {
        "iterations": iterations,
        "reason": _reason_name(result.reason),
        "converged": bool(result.converged),
        "value": float(result.value),
        "gradient_norm": float(result.gradient_norm),
        "value_history": history,
    }


def _as_host_trace(trace: LaneTrace | LaneTraces | SolverResult) -> LaneTrace:
    """Normalize to one LaneTrace whose fields are host numpy arrays — ONE
    device-to-host transfer per field (per-bucket LaneTraces merge here, in
    numpy), so the summary/rows consumers below never trigger repeated
    ~100 ms tunnel dispatches (CLAUDE.md)."""
    if isinstance(trace, SolverResult):
        from photon_ml_tpu.optim.common import lane_trace_of

        trace = lane_trace_of(trace)
    if isinstance(trace, LaneTraces):
        parts = trace.buckets
        return LaneTrace(
            iterations=np.concatenate([np.asarray(t.iterations) for t in parts]),
            reason=np.concatenate([np.asarray(t.reason) for t in parts]),
            value=np.concatenate([np.asarray(t.value) for t in parts]),
            gradient_norm=np.concatenate(
                [np.asarray(t.gradient_norm) for t in parts]
            ),
            valid=np.concatenate([np.asarray(t.valid) for t in parts]),
            scheduled=any(t.scheduled for t in parts),
        )
    if isinstance(trace.iterations, np.ndarray):
        return trace
    return LaneTrace(
        iterations=np.asarray(trace.iterations),
        reason=np.asarray(trace.reason),
        value=np.asarray(trace.value),
        gradient_norm=np.asarray(trace.gradient_norm),
        valid=np.asarray(trace.valid),
        scheduled=trace.scheduled,
    )


def lane_summary(trace: LaneTrace | SolverResult) -> dict:
    """Convergence-reason tallies + iteration stats across vmapped lanes.

    Accepts either a LaneTrace (the RE-bucket shape) or a vmapped
    SolverResult with a leading lane axis (the λ-grid shape).
    """
    trace = _as_host_trace(trace)
    valid = np.asarray(trace.valid).astype(bool)
    iterations = np.asarray(trace.iterations)[valid]
    reasons = np.asarray(trace.reason)[valid]
    values = np.asarray(trace.value)[valid]
    n = int(valid.sum())
    if n == 0:
        return {"num_lanes": 0, "reasons": {}, "lanes_at_max_iterations": 0}
    codes, counts = np.unique(reasons, return_counts=True)
    tallies = {_reason_name(c): int(k) for c, k in zip(codes, counts)}
    return {
        "num_lanes": n,
        "iterations_min": int(iterations.min()),
        "iterations_mean": float(iterations.mean()),
        "iterations_max": int(iterations.max()),
        "iterations_total": int(iterations.sum()),
        "reasons": tallies,
        "lanes_at_max_iterations": int(
            (reasons == int(ConvergenceReason.MAX_ITERATIONS)).sum()
        ),
        "lanes_not_converged": int(
            (reasons == int(ConvergenceReason.NOT_CONVERGED)).sum()
        ),
        "value_mean": float(values.mean()),
        "value_max": float(values.max()),
    }


def lane_rows(trace: LaneTrace | SolverResult, keys=None, limit: int = MAX_LANE_ROWS):
    """Per-lane convergence dicts (valid lanes only), ``keys[i]`` merged in
    when given (e.g. ``{"lambda": 0.1}`` per λ-grid lane)."""
    trace = _as_host_trace(trace)
    valid = np.asarray(trace.valid).astype(bool)
    iterations = np.asarray(trace.iterations)
    reasons = np.asarray(trace.reason)
    values = np.asarray(trace.value)
    grads = np.asarray(trace.gradient_norm)
    rows = []
    for i in np.flatnonzero(valid)[:limit]:
        row = {
            "lane": int(i),
            "iterations": int(iterations[i]),
            "reason": _reason_name(reasons[i]),
            "value": float(values[i]),
            "gradient_norm": float(grads[i]),
        }
        if keys is not None and i < len(keys):
            key = keys[i]
            row.update(key if isinstance(key, dict) else {"key": key})
        rows.append(row)
    return rows


class SolverTelemetry:
    """Fan-out sink for solver/coordinate convergence telemetry.

    ``journal``/``emitter``/``registry`` are each optional; drivers build one
    of these and thread it through estimators into the coordinate-descent
    loop and the GLM training paths.
    """

    def __init__(
        self,
        journal=None,
        emitter: EventEmitter | None = None,
        registry=None,
        max_lane_rows: int = MAX_LANE_ROWS,
    ):
        self.journal = journal
        self.emitter = emitter
        self.registry = registry
        self.max_lane_rows = max_lane_rows

    def _has_sink(self) -> bool:
        """False when no sink would consume a record — building rows costs
        real device-to-host reads (~100 ms dispatch each on the tunneled
        TPU, CLAUDE.md), so producers skip the work entirely when the
        journal is absent/inert (worker ranks drop every record), the
        registry is absent, and no event listener is registered."""
        if self.journal is not None and getattr(self.journal, "active", True):
            return True
        if self.registry is not None:
            return True
        return self.emitter is not None and self.emitter.has_listeners

    def _journal(self, kind: str, row: dict) -> None:
        if self.journal is not None:
            self.journal.record(kind, **row)

    def heartbeat(self, stage: str, **cursor) -> None:
        """Periodic liveness row (ISSUE 12): training loops call this at
        sweep/epoch/λ boundaries so ``dev/doctor.py --live`` can read a
        wedged run's progress cursor + registry counter deltas out of the
        crash-durable journal stage. Observe-only and inert without an
        active journal (worker ranks, journal-less runs)."""
        if self.journal is None or not getattr(self.journal, "active", False):
            return
        self.journal.heartbeat(registry=self.registry, stage=stage, **cursor)

    def _emit(self, coordinate_id: str, iteration: int, metrics: dict) -> None:
        if self.emitter is not None:
            self.emitter.send(OptimizationLogEvent(
                coordinate_id=coordinate_id,
                iteration=iteration,
                metrics=metrics,
            ))

    def _count(self, coordinate_id: str, iterations: int, converged: bool) -> None:
        if self.registry is None:
            return
        self.registry.histogram(
            f"{SOLVER_METRIC_PREFIX}{coordinate_id}/iterations"
        ).observe(iterations)
        self.registry.counter(f"{SOLVER_METRIC_PREFIX}{coordinate_id}/solves").inc()
        if not converged:
            self.registry.counter(f"{SOLVER_METRIC_PREFIX}{coordinate_id}/not_converged").inc()

    def record_solve(
        self,
        coordinate_id: str,
        result: SolverResult,
        *,
        outer_iteration: int = 0,
        extra: dict | None = None,
    ) -> dict:
        """One un-vmapped solve (FE coordinate, sequential λ step)."""
        if not self._has_sink():
            return {}
        row = solver_result_row(result)
        row.update(extra or {})
        row.update(coordinate=coordinate_id, outer_iteration=outer_iteration)
        self._journal("convergence", row)
        self._emit(coordinate_id, outer_iteration, row)
        self._count(coordinate_id, row["iterations"], row["converged"])
        return row

    def record_lanes(
        self,
        coordinate_id: str,
        trace: LaneTrace | SolverResult,
        *,
        outer_iteration: int = 0,
        keys=None,
        extra: dict | None = None,
    ) -> dict:
        """Vmapped lanes (λ grid, RE buckets): per-lane rows + reason tally."""
        if not self._has_sink():
            return {}
        trace = _as_host_trace(trace)  # one transfer feeds summary AND rows
        summary = lane_summary(trace)
        summary.update(extra or {})
        summary.update(coordinate=coordinate_id, outer_iteration=outer_iteration)
        for row in lane_rows(trace, keys=keys, limit=self.max_lane_rows):
            row.update(coordinate=coordinate_id, outer_iteration=outer_iteration)
            self._journal("convergence", row)
        self._journal("convergence_lanes", summary)
        self._emit(coordinate_id, outer_iteration, summary)
        if self.registry is not None and summary.get("num_lanes", 0) > 0:
            self.registry.histogram(
                f"{SOLVER_METRIC_PREFIX}{coordinate_id}/iterations"
            ).observe(summary["iterations_mean"])
            # per-lane iteration DISTRIBUTION across coordinates — p50/p95
            # vs max is the headroom the lane scheduler compacts away.
            # Scheduler-produced traces are skipped: the scheduler already
            # observed them (counting twice would double count/total)
            if not trace.scheduled:
                valid = np.asarray(trace.valid).astype(bool)
                self.registry.histogram(LANE_ITERS_METRIC).observe_many(
                    np.asarray(trace.iterations)[valid].tolist()
                )
            self.registry.counter(f"{SOLVER_METRIC_PREFIX}{coordinate_id}/solves").inc(
                summary["num_lanes"]
            )
            self.registry.counter(
                f"{SOLVER_METRIC_PREFIX}{coordinate_id}/lanes_at_max_iterations"
            ).inc(summary["lanes_at_max_iterations"])
        return summary

    def record_coordinate(
        self,
        coordinate_id: str,
        outer_iteration: int,
        info,
        *,
        metrics: dict | None = None,
    ) -> None:
        """Per-coordinate, per-outer-iteration hook for the GAME block-
        coordinate-descent loop: dispatches on what the coordinate's
        ``update_model`` returned (SolverResult for the fixed effect,
        LaneTrace(s) for vmapped random-effect buckets, None for locked/MF)."""
        if not self._has_sink():
            return
        extra = {"evaluation": metrics} if metrics else None
        if isinstance(info, SolverResult):
            self.record_solve(
                coordinate_id, info, outer_iteration=outer_iteration, extra=extra
            )
        elif isinstance(info, (LaneTrace, LaneTraces)):
            self.record_lanes(
                coordinate_id, info, outer_iteration=outer_iteration, extra=extra
            )
        elif metrics:
            row = dict(coordinate=coordinate_id, outer_iteration=outer_iteration,
                       evaluation=metrics)
            self._journal("coordinate_update", row)
            self._emit(coordinate_id, outer_iteration, row)
