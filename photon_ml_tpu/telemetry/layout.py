"""Layout-decision observability for the sparse batch builders.

Reference parity: no reference analogue — the Spark reference never chooses
a device layout (its sparse vectors stay Breeze CSR end to end); this is
TPU-first observability for the hybrid dense-head / sparse-tail builder
(data/sparse_batch.py, ISSUE 5). The hot-coverage fraction, head width
k_hot, residual tail width L, and hybrid-vs-ELL byte estimate are exactly
the quantities that decide whether the layout wins (the expected win is
index-op removal proportional to hot coverage, BASELINE.md r6), so they are
recorded as registry gauges the run journal persists on success AND failure
paths (both drivers snapshot the registry in their ``finally`` blocks).

Per-run lifecycle mirrors ``solver/*``: drivers call
:func:`reset_layout_metrics` at run start (next to ``reset_solver_metrics``)
so repeated ``run()`` calls journal per-run decisions, not stale ones.

No jax dependency — importable before the backend is chosen.
"""

from __future__ import annotations

#: registry namespace for layout-decision metrics
LAYOUT_METRIC_PREFIX = "layout/"


def reset_layout_metrics(registry=None) -> None:
    """Drop per-run layout/* gauges and counters — drivers call this at run
    start so each run's journal carries its own layout decisions."""
    from photon_ml_tpu.telemetry.registry import default_registry

    reg = registry or default_registry()
    reg.remove_prefix(LAYOUT_METRIC_PREFIX)


def record_hybrid_layout(
    label: str,
    *,
    k_hot: int,
    k_hot_padded: int,
    hot_coverage: float,
    hot_nnz: int,
    tail_nnz: int,
    tail_width: int,
    hybrid_bytes: int,
    ell_bytes: int,
    registry=None,
) -> None:
    """One hybrid build's layout decision, as gauges under
    ``layout/<label>/*`` plus a ``layout/<label>/builds`` counter.

    ``hybrid_bytes``/``ell_bytes`` are the builder's device-footprint
    estimates for the chosen hybrid layout vs the counterfactual plain-ELL
    layout of the same entries (auto width for both).
    """
    from photon_ml_tpu.telemetry.registry import default_registry

    reg = registry or default_registry()
    base = f"{LAYOUT_METRIC_PREFIX}{label}"
    reg.counter(f"{base}/builds").inc()
    _set_gauges(reg, base, (
        ("k_hot", k_hot),
        ("k_hot_padded", k_hot_padded),
        ("hot_coverage", hot_coverage),
        ("hot_nnz", hot_nnz),
        ("tail_nnz", tail_nnz),
        ("tail_width", tail_width),
        ("hybrid_bytes", hybrid_bytes),
        ("ell_bytes", ell_bytes),
    ))


def record_global_hot_ranking(
    label: str,
    *,
    k_hot: int,
    global_nnz: int,
    num_ranks: int,
    registry=None,
) -> None:
    """One partitioned-ingest GLOBAL hot-column resolution
    (io/partitioned_reader.py): the head was elected from the summed
    per-rank nnz histograms, not this rank's local block — the gauge trio
    is the journal evidence that a composed hybrid x --partitioned-io run
    ranked globally (every rank records identical values)."""
    from photon_ml_tpu.telemetry.registry import default_registry

    reg = registry or default_registry()
    base = f"{LAYOUT_METRIC_PREFIX}{label}"
    reg.counter(f"{base}/global_hot_rankings").inc()
    _set_gauges(reg, base, (
        ("global_hot_k", k_hot),
        ("global_hot_nnz", global_nnz),
        ("global_hot_ranks", num_ranks),
    ))


def record_block_head(
    label: str,
    *,
    width: int,
    num_blocks: int,
    k_hot_padded: int,
    registry=None,
) -> None:
    """The column-sharded builder's per-block head shape: every block pads
    to the WIDEST block's hot count, so hot ids clustered into few
    contiguous column blocks inflate ``width·num_blocks`` well past the
    global head size — ``block_head_replication`` is that blow-up factor
    (1.0 = perfectly spread head; ~num_blocks = fully clustered, the
    degenerate regime the builder also warns about)."""
    from photon_ml_tpu.telemetry.registry import default_registry

    reg = registry or default_registry()
    base = f"{LAYOUT_METRIC_PREFIX}{label}"
    _set_gauges(reg, base, (
        ("block_head_width", width),
        ("block_head_replication",
         width * num_blocks / k_hot_padded if k_hot_padded else 0.0),
    ))


def _set_gauges(reg, base: str, pairs) -> None:
    for name, value in pairs:
        reg.gauge(f"{base}/{name}").set(value)
