"""Bench-artifact history: one loader + unit-string parser for the driver's
``BENCH_r*.json`` / ``MULTICHIP_r*.json`` records.

No reference analogue: the reference publishes no benchmark artifacts at
all (BASELINE.md "Why the reference itself is not measured here"); this
module exists for the TPU rebuild's own evidence chain. The driver captures
each round's ``bench.py`` stdout as a 2,000-byte tail plus a best-effort
``parsed`` JSON object, and every row's win criterion references values
EMBEDDED in its compact unit string (the same-run calibration discipline:
the chip pool varies run to run, so absolute ms/GB/s across rounds are
meaningless — only fractions of the same run's probe compare). BENCH_r04
and r05 shipped with ``parsed: null`` because the unit prose overran the
tail; nobody noticed for two rounds because decoding the units was a human
job. Here the whole chain becomes machine-readable:

- :func:`load_bench_artifact` reads one ``BENCH_rNN.json``; when ``parsed``
  is null it SALVAGES the intact trailing row objects out of the truncated
  tail (the head of the line is what truncation eats, so extra_metrics
  survive) and flags the artifact.
- :func:`parse_unit` decodes the compact unit grammar (``ELLsr 644``,
  ``OFF710 ovl0.03``, ``v62/128 sw8/8``, ``1/dsp sr 3400``, ``0.57xcal``)
  plus the legacy verbose prose of the r01-r05 records into typed fields.
- :func:`calibration_fraction` normalizes a bandwidth row against the SAME
  artifact's ``fe_hot_loop_stream_gbps`` probe, per the CLAUDE.md rule.
- :func:`load_history` collects every round in a directory, sorted, so
  cross-round trend analysis (telemetry/verdicts.py, dev/doctor.py) reads
  one structure.

Everything here is stdlib-only (json/re) — importable by bench.py before
the jax platform is chosen, and by dev/doctor.py offline.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import re

#: driver artifact filename patterns (repo root / run directory)
BENCH_GLOB = "BENCH_r*.json"
MULTICHIP_GLOB = "MULTICHIP_r*.json"
#: the full unslimmed report bench.py sidecars under PHOTON_TELEMETRY_DIR
SIDECAR_FILENAME = "bench-report.json"

_NUM = r"(\d+(?:\.\d+)?)"

#: field -> (regex, cast); the compact r6+ unit grammar first, then the
#: legacy verbose prose the r01-r05 artifacts carry. Each row's unit embeds
#: its OWN same-run baseline (the calibration discipline), so these fields
#: are what the verdict rules judge against.
_UNIT_PATTERNS: tuple[tuple[str, str, type], ...] = (
    # embedded same-run baselines
    ("ell_ms", rf"ELLsr {_NUM}", float),
    ("ell_unscheduled_ms", rf"ELLunsr {_NUM}", float),
    ("off_ms", rf"OFF{_NUM}", float),
    ("overlap", rf"ovl{_NUM}", float),
    ("unbatched_rate", rf"1/dsp sr {_NUM}", float),
    ("seq_rate", rf"seq{_NUM}", float),
    ("full_ms", rf"fullsr {_NUM}", float),
    ("one_rank_ms", rf"1rk{_NUM}", float),
    ("p95_ms", rf"p95 {_NUM}ms", float),
    ("cal_fraction", rf"{_NUM}xcal", float),
    # descriptive fields
    ("coverage", rf"cov{_NUM}", float),
    ("hot_cols", r"hot(\d+)", int),
    ("roofline_gbps", rf"roof{_NUM}", float),
    ("chunks", r"ON (\d+)ch", int),
    ("chunks", r"\b(\d+)ch\b", int),  # r20 trims the "ON " (line budget)
    # legacy verbose grammar (r01-r05): the same facts in prose
    ("cal_fraction", rf"stream rate: {_NUM}", float),
    ("ms_per_iter", rf"{_NUM} ?ms/it(?:er)?\b", float),
    ("ms_per_eval", rf"{_NUM} ms/eval", float),
)


def parse_unit(metric: str, unit: str) -> dict:
    """Structured fields out of one row's compact unit string.

    Tolerant by design: returns whatever the grammar yields (possibly
    empty) — a verdict rule that needs a missing field reports
    ``no-evidence`` instead of crashing on an old artifact.
    """
    out: dict = {}
    for field, pattern, cast in _UNIT_PATTERNS:
        if field in out:
            continue  # first grammar wins (compact beats legacy prose)
        m = re.search(pattern, unit)
        if m:
            out[field] = cast(m.group(1))
    # DuHL evidence pairs: v<ordered>/<uniform> visits, sw<o>/<u> sweeps
    m = re.search(r"\bv(\d+)/(\d+)", unit)
    if m:
        out["visits_ordered"] = int(m.group(1))
        out["visits_uniform"] = int(m.group(2))
    m = re.search(r"\bsw(\d+)/(\d+)", unit)
    if m:
        out["sweeps_ordered"] = int(m.group(1))
        out["sweeps_uniform"] = int(m.group(2))
    # refresh evidence pair: ln<solved>/<total> RE lane-solves
    m = re.search(r"\bln(\d+)/(\d+)", unit)
    if m:
        out["lanes_solved"] = int(m.group(1))
        out["lanes_total"] = int(m.group(2))
    # partitioned-read evidence pair: rb<max-per-rank>/<input>MB decoded
    m = re.search(r"\brb(\d+(?:\.\d+)?)/(\d+(?:\.\d+)?)MB", unit)
    if m:
        out["rank_payload_mb"] = float(m.group(1))
        out["input_mb"] = float(m.group(2))
    return out


@dataclasses.dataclass
class BenchRow:
    """One report row (primary or extra_metrics entry) + its parsed unit."""

    metric: str
    value: float | None
    spread: list
    unit: str
    parsed_unit: dict
    salvaged: bool = False

    @classmethod
    def from_report_row(cls, row: dict, *, salvaged: bool = False) -> "BenchRow":
        unit = str(row.get("unit", ""))
        value = row.get("value")
        return cls(
            metric=str(row.get("metric", "")),
            value=None if value is None else float(value),
            spread=list(row.get("spread") or []),
            unit=unit,
            parsed_unit=parse_unit(str(row.get("metric", "")), unit),
            salvaged=salvaged,
        )


@dataclasses.dataclass
class BenchArtifact:
    """One round's bench evidence: rows + capture health."""

    path: str
    round: int | None
    rc: int | None
    parsed_ok: bool        #: the driver's tail parse round-tripped
    rows: list             #: list[BenchRow] — extra_metrics (+ salvage)
    primary: "BenchRow | None" = None
    vs_baseline: float | None = None
    source: str = "parsed"  #: "parsed" | "tail-salvage" | "sidecar"
    tail_bytes: int = 0

    def row(self, metric: str) -> "BenchRow | None":
        if self.primary is not None and self.primary.metric == metric:
            return self.primary
        for r in self.rows:
            if r.metric == metric:
                return r
        return None

    @property
    def all_rows(self) -> list:
        rows = list(self.rows)
        if self.primary is not None:
            rows.insert(0, self.primary)
        return rows


def _round_of(path: str, data: dict) -> int | None:
    if isinstance(data.get("n"), int):
        return int(data["n"])
    m = re.search(r"r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else None


def salvage_rows(tail: str) -> list:
    """Recover intact row objects from a TRUNCATED tail capture.

    The tail keeps the LAST 2,000 bytes, so over-budget lines lose their
    head (the primary metric) while trailing ``{"metric": ...}`` objects
    survive whole — exactly the r04/r05 ``parsed: null`` shape. Balanced
    objects are decoded with ``json.JSONDecoder.raw_decode``; a decoded
    object that is itself a full report expands into its rows.
    """
    decoder = json.JSONDecoder()
    rows: list = []
    i = 0
    while True:
        j = tail.find('{"metric"', i)
        if j < 0:
            break
        try:
            obj, end = decoder.raw_decode(tail, j)
        except ValueError:
            i = j + 1
            continue
        i = end
        if isinstance(obj, dict) and "extra_metrics" in obj:
            # a complete report object: expand primary + rows
            rows.append(obj)
            rows.extend(obj["extra_metrics"])
        elif isinstance(obj, dict) and "metric" in obj:
            rows.append(obj)
    return rows


def load_bench_artifact(path: str) -> BenchArtifact:
    """One ``BENCH_rNN.json`` -> :class:`BenchArtifact` (salvaging the tail
    when the driver recorded ``parsed: null``)."""
    with open(path) as f:
        data = json.load(f)
    tail = str(data.get("tail", ""))
    parsed = data.get("parsed")
    art = BenchArtifact(
        path=path,
        round=_round_of(path, data),
        rc=data.get("rc"),
        parsed_ok=parsed is not None,
        rows=[],
        tail_bytes=len(tail.encode()),
    )
    if parsed is not None:
        art.primary = BenchRow.from_report_row(parsed)
        art.vs_baseline = parsed.get("vs_baseline")
        art.rows = [
            BenchRow.from_report_row(r)
            for r in parsed.get("extra_metrics") or []
        ]
        art.source = "parsed"
        return art
    art.source = "tail-salvage"
    seen: set[str] = set()
    for obj in salvage_rows(tail):
        if "extra_metrics" in obj:
            art.primary = BenchRow.from_report_row(obj, salvaged=True)
            art.vs_baseline = obj.get("vs_baseline")
            continue
        row = BenchRow.from_report_row(obj, salvaged=True)
        if row.metric and row.metric not in seen:
            seen.add(row.metric)
            art.rows.append(row)
    return art


def load_sidecar(path: str) -> BenchArtifact:
    """The full unslimmed ``bench-report.json`` sidecar bench.py writes
    under ``PHOTON_TELEMETRY_DIR`` — never tail-truncated, so the doctor
    prefers it over the captured line when both describe the same run."""
    with open(path) as f:
        data = json.load(f)
    report = data.get("report", data)
    art = BenchArtifact(
        path=path,
        round=data.get("round"),
        rc=0,
        parsed_ok=True,
        rows=[
            BenchRow.from_report_row(r)
            for r in report.get("extra_metrics") or []
        ],
        primary=BenchRow.from_report_row(report),
        vs_baseline=report.get("vs_baseline"),
        source="sidecar",
    )
    return art


@dataclasses.dataclass
class MultichipArtifact:
    path: str
    round: int | None
    n_devices: int | None
    rc: int | None
    ok: bool
    skipped: bool


def load_multichip_artifact(path: str) -> MultichipArtifact:
    with open(path) as f:
        data = json.load(f)
    return MultichipArtifact(
        path=path,
        round=_round_of(path, data),
        n_devices=data.get("n_devices"),
        rc=data.get("rc"),
        ok=bool(data.get("ok", data.get("rc") == 0)),
        skipped=bool(data.get("skipped", False)),
    )


@dataclasses.dataclass
class BenchHistory:
    """Every round's artifacts in one directory, sorted by round."""

    artifacts: list
    multichip: list
    sidecar: "BenchArtifact | None" = None

    @property
    def latest(self) -> "BenchArtifact | None":
        """The artifact current-run verdicts judge: the sidecar when one is
        present (always complete), else the highest round."""
        if self.sidecar is not None:
            return self.sidecar
        return self.artifacts[-1] if self.artifacts else None

    def series(self, metric: str) -> list:
        """[(round, BenchRow)] for one metric across rounds (rows missing
        from a round — including truncated-away primaries — are skipped)."""
        out = []
        for art in self.artifacts:
            row = art.row(metric)
            if row is not None and row.value is not None:
                out.append((art.round, row))
        return out


def load_history(directory: str) -> BenchHistory:
    """All bench evidence in ``directory``: BENCH_r*/MULTICHIP_r* rounds
    plus the sidecar, each loaded tolerantly (a malformed artifact becomes
    an empty round, never an exception — the doctor must read sick runs)."""
    arts = []
    for path in sorted(glob.glob(os.path.join(directory, BENCH_GLOB))):
        try:
            arts.append(load_bench_artifact(path))
        except (OSError, ValueError) as e:
            arts.append(BenchArtifact(
                path=path, round=None, rc=None, parsed_ok=False, rows=[],
                source=f"unreadable: {e}",
            ))
    arts.sort(key=lambda a: (a.round is None, a.round))
    multi = []
    for path in sorted(glob.glob(os.path.join(directory, MULTICHIP_GLOB))):
        try:
            multi.append(load_multichip_artifact(path))
        except (OSError, ValueError):
            multi.append(MultichipArtifact(
                path=path, round=None, n_devices=None, rc=None, ok=False,
                skipped=False,
            ))
    multi.sort(key=lambda a: (a.round is None, a.round))
    sidecar = None
    sidecar_path = os.path.join(directory, SIDECAR_FILENAME)
    if os.path.exists(sidecar_path):
        try:
            sidecar = load_sidecar(sidecar_path)
        except (OSError, ValueError):
            sidecar = None
    return BenchHistory(artifacts=arts, multichip=multi, sidecar=sidecar)


def calibration_fraction(artifact: BenchArtifact, row: BenchRow) -> float | None:
    """A bandwidth row as a fraction of the SAME artifact's stream probe.

    Prefers the fraction the unit already embeds (``0.57xcal`` — computed
    in-process by bench.py, immune to rounding); falls back to
    value / same-run ``fe_hot_loop_stream_gbps``. None when the artifact
    carries neither (e.g. the r02 record predates the probe row) — never a
    cross-round number (chips vary run to run; CLAUDE.md).
    """
    frac = row.parsed_unit.get("cal_fraction")
    if frac is not None:
        return float(frac)
    cal = artifact.row("fe_hot_loop_stream_gbps")
    if cal is None or not cal.value or row.value is None:
        return None
    return float(row.value) / float(cal.value)
