"""Resilience telemetry: retry/giveup/quarantine/restore counters + events.

No reference analogue as code: the reference gets fault tolerance from
Spark lineage recompute and surfaces it only as task-retry counts in the
Spark UI — owned by the Spark substrate, not any photon-ml source file
(SURVEY.md §5). Here every explicit recovery
action the resilience layer takes (photon_ml_tpu/resilience/) lands on a
named counter in the process-wide metrics registry, so the run journal —
which both GAME drivers persist on success AND failure — records how many
transient errors were retried, how many exhausted their budget, how many
corrupt Avro blocks were quarantined, and how many checkpoint restores a
run needed.

Quarantined block SPANS additionally ride a small bounded event ring
(``drain_quarantine_events``) that the drivers journal as one
``quarantined_block`` row per span — the registry keeps the count, the
journal keeps the forensics (path, block index, byte range, reason).

Names are constants so producers (io/avro.py, resilience/policy.py,
algorithm/coordinate_descent.py) and consumers (tests, journals) cannot
drift — the same contract as telemetry/io_counters.py.
"""

from __future__ import annotations

import threading
from collections import deque

from photon_ml_tpu.telemetry.registry import default_registry

#: transient failures retried by a RetryPolicy or a driver-level restart
RETRIES = "resilience/retries"
#: retry/restart budgets exhausted (the error then propagated)
GIVEUPS = "resilience/giveups"
#: corrupt Avro container blocks skipped under on_corrupt="quarantine"
QUARANTINED_BLOCKS = "resilience/quarantined_blocks"
#: coordinate-descent / sweep restores from a checkpoint
CHECKPOINT_RESTORES = "resilience/checkpoint_restores"
#: restarts whose failure shape was a device loss / pool preemption
#: (resilience.errors.is_preemption) — distinct from flaky-I/O retries
PREEMPTIONS = "resilience/preemptions"
#: epochs / sweeps of completed work a checkpoint resume did NOT redo
#: (streaming λ-grid epochs, partitioned/distributed sweeps) — the
#: counter that prices what the checkpoint cadence actually saved
EPOCHS_RESUMED = "resilience/epochs_resumed"
#: typed PeerAbort failures observed (a peer's abort marker ended this
#: rank's exchange wait early, attributed) — ISSUE 15
PEER_ABORTS = "resilience/peer_aborts"
#: all-rank coordinated rollback restarts this rank participated in
#: (one per restart generation; the SHARED budget consumes these) —
#: ISSUE 15
COORDINATED_RESTARTS = "resilience/coordinated_restarts"

#: bounded forensic ring: quarantine spans awaiting journaling (a corrupt
#: input could hold thousands of bad blocks; the counter stays exact while
#: the ring keeps only the most recent spans)
QUARANTINE_EVENT_WINDOW = 256

_events_lock = threading.Lock()
_quarantine_events: deque[dict] = deque(maxlen=QUARANTINE_EVENT_WINDOW)


def record_retry(n: int = 1) -> None:
    default_registry().counter(RETRIES).inc(int(n))


def record_giveup(n: int = 1) -> None:
    default_registry().counter(GIVEUPS).inc(int(n))


def record_checkpoint_restore(n: int = 1) -> None:
    default_registry().counter(CHECKPOINT_RESTORES).inc(int(n))


def record_preemption(n: int = 1) -> None:
    default_registry().counter(PREEMPTIONS).inc(int(n))


def record_epochs_resumed(n: int) -> None:
    default_registry().counter(EPOCHS_RESUMED).inc(int(n))


def record_peer_abort(n: int = 1) -> None:
    default_registry().counter(PEER_ABORTS).inc(int(n))


def record_coordinated_restart(n: int = 1) -> None:
    default_registry().counter(COORDINATED_RESTARTS).inc(int(n))


def reset_resilience_metrics(registry=None) -> None:
    """Drop the PER-RUN recovery counters (preemptions, epochs_resumed,
    peer_aborts, coordinated_restarts) — drivers call this at run start
    next to ``reset_solver_metrics`` so a sweep invoking ``run()``
    repeatedly journals per-run tallies. The ISSUE-3 counters
    (retries/giveups/quarantined_blocks/checkpoint_restores) keep their
    original process-lifetime semantics: existing consumers assert
    cumulative values across runs."""
    reg = registry or default_registry()
    reg.remove_prefix(PREEMPTIONS)
    reg.remove_prefix(EPOCHS_RESUMED)
    reg.remove_prefix(PEER_ABORTS)
    reg.remove_prefix(COORDINATED_RESTARTS)


def record_quarantined_block(
    path: str, block_index: int, start: int, end: int, reason: str
) -> None:
    """One corrupt block skipped: count it and ring-buffer its span."""
    default_registry().counter(QUARANTINED_BLOCKS).inc(1)
    with _events_lock:
        _quarantine_events.append(
            {
                "path": str(path),
                "block_index": int(block_index),
                "byte_start": int(start),
                "byte_end": int(end),
                "reason": str(reason),
            }
        )


def drain_quarantine_events() -> list[dict]:
    """Pop every pending quarantine span (drivers journal these as
    ``quarantined_block`` rows; tests assert on them)."""
    with _events_lock:
        out = list(_quarantine_events)
        _quarantine_events.clear()
    return out


def retries() -> int:
    return int(default_registry().counter(RETRIES).value)


def giveups() -> int:
    return int(default_registry().counter(GIVEUPS).value)


def quarantined_blocks() -> int:
    return int(default_registry().counter(QUARANTINED_BLOCKS).value)


def checkpoint_restores() -> int:
    return int(default_registry().counter(CHECKPOINT_RESTORES).value)


def preemptions() -> int:
    return int(default_registry().counter(PREEMPTIONS).value)


def peer_aborts() -> int:
    return int(default_registry().counter(PEER_ABORTS).value)


def coordinated_restarts() -> int:
    return int(default_registry().counter(COORDINATED_RESTARTS).value)


def epochs_resumed() -> int:
    return int(default_registry().counter(EPOCHS_RESUMED).value)
