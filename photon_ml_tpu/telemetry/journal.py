"""Rank-0-only JSONL run journal, atomically finalized into the output dir.

Reference parity: photon-lib util/PhotonLogger.scala:34-90 (spool locally,
publish to the final destination on close) crossed with
PhotonOptimizationLogEvent / OptimizationStatesTracker.scala:82-101 (the
structured per-coordinate optimization telemetry the reference emitted to
external listeners). Here both become one machine-parseable artifact: every
driver/estimator/bench phase appends typed records (phase timings,
convergence rows, calibration probes, config summaries) to a local spool
file, and ``close()`` moves it atomically to ``<dir>/run-journal.jsonl``.

Multi-process discipline (CLAUDE.md): only rank 0 touches shared output
directories, while collectives must still run on EVERY rank — so a journal
constructed on rank > 0 is inert (all methods are no-ops) and callers never
need to branch on rank themselves (which would tempt them to skip
collectives inside ``if journal:`` blocks).

Crash durability (ISSUE 12): with ``durable=True`` (the default) the spool
IS the staged file ``<dir>/<filename>.partial`` and every row is
append-fsync'd, so a SIGKILL'd run leaves a readable journal for
``dev/doctor.py --live`` to tail; ``close()`` still publishes atomically
(``os.replace`` of the stage onto the final name — readers of the final
path never see a torn file). Flushing is observe-only: durable on/off
changes nothing about what callers compute (pinned bitwise on an
instrumented streaming solve, tests/test_doctor.py). Heartbeat rows
(:meth:`RunJournal.heartbeat`) carry a training cursor plus registry
counter DELTAS since the previous heartbeat — the live progress signal a
wedged production run is diagnosed by.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import math
import os
import tempfile
import threading
import time

JOURNAL_FILENAME = "run-journal.jsonl"
#: suffix of the crash-durable stage file a live/killed run is readable at
JOURNAL_PARTIAL_SUFFIX = ".partial"


def _process_index() -> int:
    """Current rank; 0 when jax is absent or uninitialized (single host)."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


def json_safe(obj):
    """Recursively coerce to strict-JSON values: numpy/jax scalars and
    arrays, enums, dataclasses; NaN/Inf become None (the driver summary
    convention, cli/game_training_driver.py)."""
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, enum.Enum):
        return obj.name
    if isinstance(obj, dict):
        return {str(k): json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [json_safe(v) for v in obj]
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return json_safe(dataclasses.asdict(obj))
    # numpy / jax scalars and arrays without importing either eagerly
    item = getattr(obj, "item", None)
    shape = getattr(obj, "shape", None)
    if item is not None and shape == ():
        return json_safe(item())
    tolist = getattr(obj, "tolist", None)
    if tolist is not None:
        return json_safe(tolist())
    return str(obj)


#: fields the journal stamps onto every heartbeat row itself — everything
#: ELSE in the row is the caller's progress cursor (dev/doctor.py and
#: telemetry/verdicts.py both print "where was the run" from this split).
#: ``hbm_bytes`` and ``compiles`` are the ISSUE 13 drift snapshots: live
#: device-buffer bytes and the backend compile count, so ``doctor --live``
#: can show device-memory drift and mid-run compile storms on a wedged run.
_HEARTBEAT_BOOKKEEPING = frozenset(
    {"kind", "seq", "ts", "elapsed_ms", "counter_deltas", "gauges",
     "hbm_bytes", "compiles"}
)


def _live_hbm_bytes() -> "int | None":
    """Live device-buffer bytes for heartbeat rows; None unless a jax
    backend is ALREADY initialized — a heartbeat must never force one
    (journal-only processes exist, e.g. the SIGKILL chaos subprocess, and
    on the tunneled platform a FIRST device call can block on the relay;
    merely having jax imported is not enough). Observe-only: the probe
    must never gate (or fail) a heartbeat. Training/scoring loops always
    have a live backend by their first heartbeat, so the field is only
    absent where probing would have been wrong anyway."""
    import sys

    xb = sys.modules.get("jax._src.xla_bridge")
    if xb is None or not getattr(xb, "_backends", None):
        return None
    try:
        from photon_ml_tpu.telemetry.probes import live_buffer_bytes

        return int(live_buffer_bytes())
    except (ImportError, RuntimeError):
        return None


def heartbeat_cursor(row: dict) -> dict:
    """The caller-supplied progress cursor of one ``heartbeat`` journal row
    (stage, sweep/epoch/λ indices, ...) with the journal's own bookkeeping
    fields stripped."""
    return {k: v for k, v in row.items() if k not in _HEARTBEAT_BOOKKEEPING}


class RunJournal:
    """``with RunJournal(out_dir) as j: j.record("phase_timing", ...)``.

    Records are dicts with a ``kind`` plus caller fields; ``seq``, ``ts``
    (absolute wall clock) and ``elapsed_ms`` (monotonic since journal
    open — robust to host clock steps, correlates with trace spans) are
    stamped automatically. Inactive (rank > 0, or ``directory=None``)
    journals accept every call and write nothing.
    """

    def __init__(
        self,
        directory: str | os.PathLike | None,
        *,
        filename: str = JOURNAL_FILENAME,
        rank: int | None = None,
        durable: bool = True,
    ):
        self.directory = None if directory is None else str(directory)
        self.filename = filename
        self.rank = _process_index() if rank is None else int(rank)
        self.durable = bool(durable)
        self._seq = 0
        self._spool = None
        self._closed = False
        #: journals now have legitimate second writer threads (the serve
        #: driver's swap poller, the micro-batch consumer's ledger rows):
        #: the seq stamp + buffered write/flush/fsync must be one atomic
        #: unit or concurrent rows tear mid-file (read_journal only
        #: forgives a torn FINAL line)
        self._lock = threading.Lock()
        self._hb_counters: dict[str, int] = {}
        # monotonic anchor: rows carry elapsed_ms since journal open so
        # they order correctly across host clock steps and correlate with
        # trace spans (telemetry/tracing.py durations are perf_counter too)
        self._t0 = time.perf_counter()
        if self.active:
            if self.durable:
                # the spool IS the stage file, in the destination directory
                # (os.replace is atomic only within one filesystem): every
                # row is append-fsync'd below, so a killed run's journal is
                # readable at <dir>/<filename>.partial before publish
                os.makedirs(self.directory, exist_ok=True)
                self._spool = open(self.partial_path, "w")
            else:
                self._spool = tempfile.NamedTemporaryFile(
                    mode="w", suffix=".jsonl", prefix="photon-journal-",
                    delete=False,
                )
            self.record("journal_open", pid=os.getpid(), rank=self.rank)

    @property
    def active(self) -> bool:
        return self.directory is not None and self.rank == 0 and not self._closed

    @property
    def path(self) -> str | None:
        """Final journal path (exists only after ``close()``)."""
        if self.directory is None:
            return None
        return os.path.join(self.directory, self.filename)

    @property
    def partial_path(self) -> str | None:
        """The crash-durable stage file a live (or killed) durable run is
        readable at — what ``dev/doctor.py --live`` tails."""
        if self.directory is None:
            return None
        return os.path.join(
            self.directory, self.filename + JOURNAL_PARTIAL_SUFFIX
        )

    def record(self, kind: str, **fields) -> None:
        if not self.active:
            return
        payload = json_safe(fields)
        with self._lock:
            if not self.active:  # closed while we serialized
                return
            row = {
                "kind": kind,
                "seq": self._seq,
                # ts is the ONE sanctioned absolute wall-clock stamp (lint
                # check 11 allowlist); durations/ordering ride elapsed_ms
                "ts": time.time(),
                "elapsed_ms": round(
                    (time.perf_counter() - self._t0) * 1e3, 3
                ),
            }
            row.update(payload)
            self._seq += 1
            self._spool.write(json.dumps(row, allow_nan=False) + "\n")
            self._spool.flush()
            if self.durable:
                # append-fsync per row: a SIGKILL between rows loses at
                # most the row being written, never the file (journals are
                # low-rate — tens of rows plus heartbeats per run)
                os.fsync(self._spool.fileno())

    def record_timings(self, timings: dict[str, dict[str, float]]) -> None:
        """One ``phase_timing`` row per named phase — the shape
        ``util.timed.timing_summary()`` returns."""
        for name, summary in timings.items():
            self.record("phase_timing", name=name, **summary)

    def record_metrics(self, snapshot: dict) -> None:
        """Persist a full ``MetricsRegistry.snapshot()``."""
        self.record("metrics", snapshot=snapshot)

    def record_gauge(self, name: str, value) -> None:
        self.record("gauge", name=name, value=value)

    def heartbeat(self, *, registry=None, **cursor) -> None:
        """One periodic liveness row: the caller's progress cursor (sweep/
        epoch/λ index, dataset id, ...) plus the registry's counter DELTAS
        since the previous heartbeat (what moved, not the whole snapshot)
        and its current gauges. ``dev/doctor.py --live`` reads the last of
        these to say where a wedged run actually is. Observe-only: emitted
        from observers/loop tails, never gating any training work."""
        if not self.active:
            return
        fields = dict(cursor)
        hbm = _live_hbm_bytes()
        if hbm is not None:
            fields["hbm_bytes"] = hbm
        if registry is not None:
            snap = registry.snapshot()
            counters = {
                str(k): int(v) for k, v in (snap.get("counters") or {}).items()
            }
            # absolute compile-count snapshot (the delta alone cannot show
            # a storm's trajectory across heartbeats)
            from photon_ml_tpu.telemetry.probes import COMPILE_COUNT_METRIC

            if COMPILE_COUNT_METRIC in counters:
                fields["compiles"] = counters[COMPILE_COUNT_METRIC]
            deltas = {
                k: v - self._hb_counters.get(k, 0)
                for k, v in counters.items()
                if v != self._hb_counters.get(k, 0)
            }
            self._hb_counters = counters
            if deltas:
                fields["counter_deltas"] = deltas
            gauges = {
                k: v for k, v in (snap.get("gauges") or {}).items()
                if v is not None
            }
            if gauges:
                fields["gauges"] = gauges
        self.record("heartbeat", **fields)

    def close(self) -> None:
        """Atomically publish the spool as ``<directory>/<filename>``."""
        if self._closed or self._spool is None:
            self._closed = True
            return
        self.record("journal_close", records=self._seq)
        with self._lock:
            # a concurrent writer thread (swap poller) blocked on the lock
            # re-checks `active` after acquiring it, so nothing writes to
            # the spool once it is closed here
            self._closed = True
            self._spool.flush()
            os.fsync(self._spool.fileno())
            self._spool.close()
        if self.durable:
            # the spool IS the stage file in the destination directory:
            # publish is one atomic rename
            os.replace(self._spool.name, self.path)
            return
        os.makedirs(self.directory, exist_ok=True)
        # stage into the destination directory first: os.replace is atomic
        # only within one filesystem, and the spool lives in the system tmp
        fd, staged = tempfile.mkstemp(
            dir=self.directory, prefix=".journal-", suffix=".tmp"
        )
        try:
            with os.fdopen(fd, "wb") as dst, open(self._spool.name, "rb") as src:
                dst.write(src.read())
            os.replace(staged, self.path)
        except BaseException:
            if os.path.exists(staged):
                os.unlink(staged)
            raise
        finally:
            os.unlink(self._spool.name)

    def __enter__(self) -> "RunJournal":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    @staticmethod
    def read(path: str | os.PathLike) -> list[dict]:
        """Parse a finalized journal back into a list of record dicts."""
        return read_journal(path, tolerant=False)


def read_journal(path: str | os.PathLike, *, tolerant: bool = False) -> list[dict]:
    """Parse a JSONL journal. ``tolerant=True`` skips unparseable lines —
    the shape of a crash-durable ``.partial`` stage whose final row was cut
    mid-write by a SIGKILL (every earlier row is fsync'd whole)."""
    records: list[dict] = []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                if not tolerant:
                    raise
    return records
