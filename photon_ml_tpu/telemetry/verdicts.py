"""Per-bench-row win criteria + known-pathology diagnostics: the rules a
run is judged by, as code instead of BASELINE.md prose.

No reference analogue: the reference ships no benchmark governance at all;
this registry encodes the TPU rebuild's own measured-facts discipline
(CLAUDE.md / BASELINE.md): every bench row carries its SAME-RUN baseline
embedded in its unit (chips vary run to run — absolute numbers never
compare across rounds), so each row is self-judging once the unit is
parsed (telemetry/bench_history.py). Snap ML (arXiv:1803.06333) treats
measured hierarchy-level throughput as a control signal; here the measured
rows are the control signal for the repo's own perf claims.

Three layers:

- :func:`rule` registers one win criterion per row key. dev/lint_parity.py
  check 12 statically cross-checks this registry against
  ``bench.sample_report()`` — a new bench row without a registered verdict
  rule fails the lint, so "what does winning mean" can never again live
  only in prose.
- :func:`judge_row` / :func:`judge_artifact` produce :class:`Verdict`
  records (win / regression / flat / info / pathology / no-evidence), with
  the two measured pathology signatures named with their known causes: a
  NEGATIVE MARGINAL (K-spread too small against the ~100 ms dispatch
  jitter — the BENCH_r03 signature) and a ~40x SAME-RUN BLOWOUT (a Pallas
  call vmap-batched into a serial per-lane loop, or host contention from a
  concurrent CPU job — both measured, CLAUDE.md).
- :func:`journal_findings` cross-checks a run journal's registry snapshot
  (overlap_fraction ~ 0 with prefetch on, high serve pad_fraction,
  quarantined blocks, preemption restarts, stragglers, and the program
  ledger's compile pathologies — recompile storms with their attributed
  cause, signature churn, compile-dominated runs, HBM overcommit
  forecasts; ISSUE 13) and :func:`history_findings` reads cross-round
  trends (improvements, plateaus) in the direction each rule declares.

Statuses: only ``regression`` (a row losing its win criterion) fails a
doctor run by default — pathologies and warnings are findings the operator
reads, because historical artifacts legitimately carry them (r04/r05
``parsed: null``).
"""

from __future__ import annotations

import dataclasses
import fnmatch
from typing import Callable

from photon_ml_tpu.telemetry.bench_history import (
    BenchArtifact,
    BenchHistory,
    BenchRow,
    MultichipArtifact,
    calibration_fraction,
)
from photon_ml_tpu.telemetry.journal import heartbeat_cursor

# verdict statuses
WIN = "win"
REGRESSION = "regression"   # lost its win criterion -> nonzero doctor exit
FLAT = "flat"
INFO = "info"
PATHOLOGY = "pathology"     # known bad-measurement signature, named cause
WARNING = "warning"
NO_EVIDENCE = "no-evidence"

#: same-run ratio beyond which a loss is reported as the measured
#: contention/vmapped-Pallas blowout instead of a plain regression
BLOWOUT_RATIO = 10.0

#: tolerance band for same-run ms comparisons (spread jitter)
FLAT_BAND = 0.02

NEGATIVE_MARGINAL_CAUSE = (
    "negative marginal — K_hi-K_lo differencing spread too small against "
    "the ~100 ms dispatch jitter (the BENCH_r03 signature); widen the K "
    "spread so device time dwarfs the jitter"
)
BLOWOUT_CAUSE = (
    "same-run blowout >= 10x — known causes: a Pallas kernel vmap-batched "
    "into a serial per-lane loop (measured 40x; lint check 6) or host "
    "contention from a concurrent CPU job corrupting the marginal "
    "(measured 40x on an r4 λ-grid trial; CLAUDE.md)"
)


@dataclasses.dataclass
class Verdict:
    """One finding: a row/artifact/journal fact plus the rule that judged it."""

    metric: str
    rule: str
    status: str
    detail: str
    round: int | None = None

    def line(self) -> str:
        tag = f"r{self.round}" if self.round is not None else "-"
        return f"{self.status.upper():10s} {tag:>4s}  {self.metric}: {self.detail}"


@dataclasses.dataclass
class Rule:
    pattern: str                #: exact metric key, or a ``prefix*`` glob
    name: str                   #: short rule id printed in reports
    judge: Callable             #: (row, artifact) -> Verdict
    higher_better: bool | None  #: cross-round trend direction (None = n/a)
    doc: str


_RULES: list[Rule] = []


def rule(pattern: str, *, name: str, higher_better: bool | None = None,
         doc: str = ""):
    """Register one win criterion. ``pattern`` is the bench row key (or a
    ``prefix*`` glob for row families); string literals only — lint check
    12 reads them statically against ``bench.sample_report()``."""

    def deco(fn: Callable) -> Callable:
        _RULES.append(Rule(pattern=pattern, name=name, judge=fn,
                           higher_better=higher_better, doc=doc or fn.__doc__ or ""))
        return fn

    return deco


def rule_for(metric: str) -> Rule | None:
    """Exact key first, then glob families."""
    for r in _RULES:
        if r.pattern == metric:
            return r
    for r in _RULES:
        if r.pattern.endswith("*") and fnmatch.fnmatch(metric, r.pattern):
            return r
    return None


def registered_rules() -> list[Rule]:
    return list(_RULES)


def _negative_marginal(row: BenchRow) -> bool:
    values = [row.value] + [s for s in row.spread if isinstance(s, (int, float))]
    return any(v is not None and v <= 0 for v in values)


def _verdict(row, rule_name, status, detail, art=None):
    return Verdict(metric=row.metric, rule=rule_name, status=status,
                   detail=detail, round=None if art is None else art.round)


def _same_run_lower(row, art, baseline_ms, *, rule_name, baseline_label):
    """Shared same-run 'ON must beat its embedded OFF' comparison for
    ms-valued rows; names the blowout pathology when the loss is ~40x."""
    if baseline_ms is None:
        return _verdict(
            row, rule_name, NO_EVIDENCE,
            f"unit embeds no {baseline_label} (value {row.value})", art,
        )
    if row.value is None:
        return _verdict(row, rule_name, NO_EVIDENCE, "row has no value", art)
    ratio = row.value / baseline_ms if baseline_ms else float("inf")
    detail = (
        f"{row.value:g} ms vs same-run {baseline_label} {baseline_ms:g} ms "
        f"({ratio:.2f}x)"
    )
    if ratio >= BLOWOUT_RATIO:
        return _verdict(row, rule_name, REGRESSION,
                        f"{detail} — {BLOWOUT_CAUSE}", art)
    if ratio < 1.0 - FLAT_BAND:
        return _verdict(row, rule_name, WIN, detail, art)
    if ratio <= 1.0 + FLAT_BAND:
        return _verdict(row, rule_name, FLAT, detail, art)
    return _verdict(row, rule_name, REGRESSION, detail, art)


# -- per-row rules (BASELINE.md same-run criteria, as code) ------------------


@rule("glm_lambda_grid_example_iters_per_sec", name="primary-positive",
      higher_better=True,
      doc="primary λ-grid throughput; judged across rounds by history, "
          "within a round only for presence + vs_baseline > 1")
def _judge_primary(row: BenchRow, art: BenchArtifact) -> Verdict:
    vs = art.vs_baseline
    detail = f"{row.value:g} ex*it/s" + (
        f", {vs:g}x scipy grid" if vs is not None else ""
    )
    if vs is not None and vs <= 1.0:
        return _verdict(row, "primary-positive", REGRESSION,
                        detail + " — TPU grid no faster than host scipy", art)
    return _verdict(row, "primary-positive", INFO, detail, art)


@rule("fe_hot_loop_stream_gbps", name="calibration-probe", higher_better=None,
      doc="the same-run normalizer every bandwidth fraction divides by; "
          "never compared across rounds (chip lottery)")
def _judge_stream(row: BenchRow, art: BenchArtifact) -> Verdict:
    return _verdict(row, "calibration-probe", INFO,
                    f"stream probe {row.value:g} GB/s (this run's chip)", art)


@rule("fe_hot_loop_hbm_gbps_*", name="hot-loop-cal-fraction",
      higher_better=None,  # absolute GB/s never compare across rounds
      doc="single-pass kernel rows must hold ~1x the same-run stream "
          "probe (the r4 study); the 2-pass autodiff row is informational; "
          "no cross-round trend — the chip pool swings absolutes")
def _judge_hot_loop(row: BenchRow, art: BenchArtifact) -> Verdict:
    frac = calibration_fraction(art, row)
    if frac is None:
        return _verdict(row, "hot-loop-cal-fraction", NO_EVIDENCE,
                        f"{row.value:g} GB/s, no same-run stream probe", art)
    detail = f"{row.value:g} GB/s = {frac:.2f}x same-run stream probe"
    if row.metric.endswith("autodiff_xla"):
        # 2 X passes by construction: ~0.5x is the expected shape
        return _verdict(row, "hot-loop-cal-fraction", INFO, detail, art)
    if frac >= 1.0:
        return _verdict(row, "hot-loop-cal-fraction", WIN, detail, art)
    if frac >= 0.8:
        return _verdict(row, "hot-loop-cal-fraction", FLAT, detail, art)
    return _verdict(
        row, "hot-loop-cal-fraction", REGRESSION,
        detail + " — the single-pass kernel should sustain ~1x the probe "
                 "(1.10x measured r4/r5)", art,
    )


@rule("fused_game_sweep_ms", name="sweep-baseline", higher_better=False,
      doc="the unscheduled-LBFGS sweep: the same-run baseline the newton/"
          "scheduled rows are judged against")
def _judge_sweep(row: BenchRow, art: BenchArtifact) -> Verdict:
    return _verdict(row, "sweep-baseline", INFO,
                    f"{row.value:g} ms/sweep (same-run baseline row)", art)


@rule("fused_game_sweep_newton_ms", name="newton-beats-lbfgs",
      higher_better=False,
      doc="Newton REs must beat the same-run LBFGS sweep (r5: 18 vs 48 ms)")
def _judge_newton(row: BenchRow, art: BenchArtifact) -> Verdict:
    base = art.row("fused_game_sweep_ms")
    return _same_run_lower(
        row, art, None if base is None else base.value,
        rule_name="newton-beats-lbfgs",
        baseline_label="fused_game_sweep_ms",
    )


@rule("fused_game_sweep_scheduled_ms", name="scheduled-beats-unscheduled",
      higher_better=False,
      doc="probe/rescue scheduling must beat the same-run unscheduled "
          "sweep on this warm-started bench (expected to lose only cold)")
def _judge_scheduled(row: BenchRow, art: BenchArtifact) -> Verdict:
    base = art.row("fused_game_sweep_ms")
    return _same_run_lower(
        row, art, None if base is None else base.value,
        rule_name="scheduled-beats-unscheduled",
        baseline_label="fused_game_sweep_ms",
    )


@rule("sparse_giant_fe_entry_iters_per_sec", name="ell-throughput",
      higher_better=True,
      doc="the d=1e7 ELL row; bounded by the ~7-12 ns/element per-index "
          "rate, so cross-round plateau is the expected shape (history "
          "names it); hybrid is the lever, not reordering")
def _judge_ell(row: BenchRow, art: BenchArtifact) -> Verdict:
    return _verdict(row, "ell-throughput", INFO,
                    f"{row.value:g} entry-iters/s (ELL layout)", art)


@rule("sparse_giant_fe_hybrid", name="hybrid-beats-ell", higher_better=False,
      doc="hybrid ms/iter must beat the ELL ms/iter embedded in its unit "
          "(same Zipfian data, same process — the r6 criterion)")
def _judge_hybrid(row: BenchRow, art: BenchArtifact) -> Verdict:
    return _same_run_lower(
        row, art, row.parsed_unit.get("ell_ms"),
        rule_name="hybrid-beats-ell", baseline_label="embedded ELL",
    )


@rule("sparse_giant_fe_composed", name="composed-beats-ell-unscheduled",
      higher_better=False,
      doc="the hybrid+scheduled sweep must beat the embedded same-run "
          "ELL+unscheduled sweep (the ISSUE 6 criterion)")
def _judge_composed(row: BenchRow, art: BenchArtifact) -> Verdict:
    return _same_run_lower(
        row, art, row.parsed_unit.get("ell_unscheduled_ms"),
        rule_name="composed-beats-ell-unscheduled",
        baseline_label="embedded ELL-unscheduled",
    )


@rule("sparse_1e8_fe_tron_ms_per_iter", name="tron-1e8", higher_better=False,
      doc="d=1e8 TRON row; r6 redefined it onto Zipf+hybrid, so r5-and-"
          "earlier values are not comparable (BASELINE.md)")
def _judge_tron(row: BenchRow, art: BenchArtifact) -> Verdict:
    return _verdict(row, "tron-1e8", INFO,
                    f"{row.value:g} ms/TRON-iter (Zipf+hybrid since r6; "
                    "earlier rounds not comparable)", art)


@rule("stream_fe_chunked", name="prefetch-on-beats-off", higher_better=False,
      doc="prefetch-ON ms/epoch must beat the same-run OFF embedded in the "
          "unit; overlap ~0 with a win absent is the hid-nothing pathology")
def _judge_stream_chunked(row: BenchRow, art: BenchArtifact) -> Verdict:
    v = _same_run_lower(
        row, art, row.parsed_unit.get("off_ms"),
        rule_name="prefetch-on-beats-off", baseline_label="prefetch-OFF",
    )
    overlap = row.parsed_unit.get("overlap")
    if overlap is not None and overlap < 0.01 and v.status != WIN:
        v = dataclasses.replace(
            v, status=PATHOLOGY,
            detail=v.detail + (
                " — overlap_fraction ~ 0: prefetch hid nothing; expected "
                "only when compute is host-bound (1-core CPU mesh), never "
                "on the tunnel where the ~100 ms blocking dispatch should "
                "hide the decode"
            ),
        )
    return v


@rule("stream_game_duhl", name="duhl-fewer-visits", higher_better=False,
      doc="DuHL must reach tolerance in strictly fewer RE chunk visits "
          "than the same-run uniform sweep (v-pair in the unit; CPU "
          "anchor v62/128)")
def _judge_duhl(row: BenchRow, art: BenchArtifact) -> Verdict:
    u = row.parsed_unit
    vo, vu = u.get("visits_ordered"), u.get("visits_uniform")
    if vo is None or vu is None:
        return _verdict(row, "duhl-fewer-visits", NO_EVIDENCE,
                        "unit embeds no v<ordered>/<uniform> pair", art)
    detail = f"v{vo}/{vu} chunk visits to tolerance"
    so, su = u.get("sweeps_ordered"), u.get("sweeps_uniform")
    if so is not None and su is not None:
        detail += f", sw{so}/{su}"
        if so > su:
            return _verdict(
                row, "duhl-fewer-visits", REGRESSION,
                detail + " — DuHL took MORE sweeps than uniform: the "
                "importance ranking pinned the wrong chunks (rank on "
                "movement+gradient after warmup_sweeps, never on "
                "first-solve movement — the measured 12-vs-8 failure)", art,
            )
    if vo < vu:
        return _verdict(row, "duhl-fewer-visits", WIN, detail, art)
    return _verdict(
        row, "duhl-fewer-visits", REGRESSION,
        detail + " — the working set saved nothing over uniform", art,
    )


@rule("stream_game_ranks", name="rank-reads-strict-subset",
      higher_better=False,
      doc="multi-rank partitioned streamed GAME (ISSUE 17): max per-rank "
          "decoded payload bytes must be STRICTLY smaller than the global "
          "input bytes (rb<rank>/<input>MB pair) — the I/O the partition "
          "exists to save. Wall ms/sweep on virtual ranks is "
          "thread-serialized on one host and is informational only; the "
          "same-run single-rank sweep ms (1rk) gives its scale")
def _judge_stream_ranks(row: BenchRow, art: BenchArtifact) -> Verdict:
    u = row.parsed_unit
    rank_mb, input_mb = u.get("rank_payload_mb"), u.get("input_mb")
    if rank_mb is None or input_mb is None:
        return _verdict(row, "rank-reads-strict-subset", NO_EVIDENCE,
                        "unit embeds no rb<rank>/<input>MB pair", art)
    detail = f"max per-rank payload {rank_mb:g} MB of {input_mb:g} MB input"
    one_rank = u.get("one_rank_ms")
    if one_rank is not None and row.value is not None:
        detail += (f"; {row.value:g} ms/sweep vs same-run single-rank "
                   f"{one_rank:g} (informational — virtual ranks "
                   f"serialize)")
    if 0 < rank_mb < input_mb:
        return _verdict(row, "rank-reads-strict-subset", WIN, detail, art)
    return _verdict(
        row, "rank-reads-strict-subset", REGRESSION,
        detail + " — a rank decoded the whole input: the partitioned "
        "plan assigned it every covering block (ISSUE 17's point is that "
        "it must not)", art,
    )


@rule("serve_microbatch", name="batched-beats-unbatched", higher_better=True,
      doc="micro-batched scores/sec must beat the same-run one-request-"
          "per-dispatch rate embedded in the unit (~14x on the CPU mesh)")
def _judge_serve(row: BenchRow, art: BenchArtifact) -> Verdict:
    base = row.parsed_unit.get("unbatched_rate")
    if base is None:
        return _verdict(row, "batched-beats-unbatched", NO_EVIDENCE,
                        "unit embeds no same-run unbatched rate", art)
    if row.value is None:
        return _verdict(row, "batched-beats-unbatched", NO_EVIDENCE,
                        "row has no value", art)
    ratio = row.value / base if base else float("inf")
    detail = f"{row.value:g} sc/s vs unbatched {base:g} ({ratio:.1f}x)"
    if ratio > 1.0:
        return _verdict(row, "batched-beats-unbatched", WIN, detail, art)
    return _verdict(
        row, "batched-beats-unbatched", REGRESSION,
        detail + " — the micro-batch loop must beat one-request-per-"
        "dispatch or serving has no reason to exist", art,
    )


@rule("refresh_incremental", name="refresh-beats-full-retrain",
      higher_better=False,
      doc="incremental refresh ms must beat the same-run full retrain "
          "embedded in the unit, with STRICTLY fewer RE lane-solves "
          "(ln<solved>/<total> pair) — a refresh that re-solves every "
          "lane saved nothing (ISSUE 14)")
def _judge_refresh(row: BenchRow, art: BenchArtifact) -> Verdict:
    u = row.parsed_unit
    v = _same_run_lower(
        row, art, u.get("full_ms"),
        rule_name="refresh-beats-full-retrain",
        baseline_label="full retrain",
    )
    solved, total = u.get("lanes_solved"), u.get("lanes_total")
    if solved is not None and total is not None:
        v = dataclasses.replace(v, detail=v.detail + f", ln{solved}/{total}")
        if solved >= total and v.status in (WIN, FLAT):
            return dataclasses.replace(
                v, status=REGRESSION,
                detail=v.detail + " — the refresh re-solved every RE lane: "
                "the selection policy saved nothing (check "
                "gradient_tolerance / the declared changed-entity set)",
            )
    return v


@rule("search_throughput", name="tournament-beats-sequential",
      higher_better=True,
      doc="GP tournament configs/sec must beat the same-run one-config-"
          "per-solve sequential rate embedded in the unit (seq token) — "
          "vmapped lanes are the ONLY reason the search driver exists "
          "(ISSUE 20); wall rates never compare across rounds")
def _judge_search(row: BenchRow, art: BenchArtifact) -> Verdict:
    base = row.parsed_unit.get("seq_rate")
    if base is None:
        return _verdict(row, "tournament-beats-sequential", NO_EVIDENCE,
                        "unit embeds no same-run sequential rate", art)
    if row.value is None:
        return _verdict(row, "tournament-beats-sequential", NO_EVIDENCE,
                        "row has no value", art)
    ratio = row.value / base if base else float("inf")
    detail = f"{row.value:g} cfg/s vs sequential {base:g} ({ratio:.1f}x)"
    if ratio > 1.0:
        return _verdict(row, "tournament-beats-sequential", WIN, detail, art)
    return _verdict(
        row, "tournament-beats-sequential", REGRESSION,
        detail + " — the vmapped tournament must beat one-config-per-"
        "solve or the search driver has no reason to exist", art,
    )


# -- judging entry points ----------------------------------------------------


def judge_row(row: BenchRow, artifact: BenchArtifact) -> Verdict:
    """One row -> one verdict: negative-marginal pathology first, then the
    registered win criterion (rows without a rule report as such — lint
    check 12 keeps that set empty for sample_report rows)."""
    if _negative_marginal(row):
        return _verdict(row, "negative-marginal", PATHOLOGY,
                        NEGATIVE_MARGINAL_CAUSE, artifact)
    r = rule_for(row.metric)
    if row.value is None and r is not None:
        # a null-valued row reaches no criterion (and the per-rule detail
        # formatters assume a number) — the doctor must read sick runs
        return _verdict(row, r.name, NO_EVIDENCE,
                        "row carries no value", artifact)
    if r is None:
        return _verdict(
            row, "unregistered", WARNING,
            "no verdict rule registered for this row — add one in "
            "telemetry/verdicts.py (lint check 12)", artifact,
        )
    return r.judge(row, artifact)


def judge_artifact(artifact: BenchArtifact) -> list:
    """Row verdicts + artifact-level capture health for one round."""
    verdicts: list[Verdict] = []
    if artifact.rc not in (0, None):
        verdicts.append(Verdict(
            metric="artifact", rule="bench-exit-code", status=REGRESSION,
            detail=f"bench.py exited rc={artifact.rc}", round=artifact.round,
        ))
    if not artifact.parsed_ok:
        verdicts.append(Verdict(
            metric="artifact", rule="parsed-non-null", status=PATHOLOGY,
            detail=(
                "driver captured parsed:null — the JSON line overran the "
                "2,000-byte tail (the BENCH_r04/r05 regression; "
                f"test_bench_line.py pins <=1999 B); {len(artifact.rows)} "
                "row(s) salvaged from the truncated tail, primary metric "
                "lost" if artifact.primary is None else
                "driver captured parsed:null but the full report was "
                "salvaged from the tail"
            ),
            round=artifact.round,
        ))
    for row in artifact.all_rows:
        verdicts.append(judge_row(row, artifact))
    return verdicts


def judge_multichip(artifact: MultichipArtifact) -> Verdict:
    if artifact.skipped:
        return Verdict("multichip", "multichip-ok", INFO,
                       "dryrun skipped this round", round=artifact.round)
    if artifact.ok and artifact.rc == 0:
        return Verdict("multichip", "multichip-ok", WIN,
                       f"dryrun_multichip ok on {artifact.n_devices} devices",
                       round=artifact.round)
    return Verdict("multichip", "multichip-ok", REGRESSION,
                   f"dryrun_multichip failed (rc={artifact.rc})",
                   round=artifact.round)


# -- cross-round history -----------------------------------------------------

#: a first->last ratio past this (in the rule's better direction) is an
#: improvement finding; within FLAT of 1.0 over the trailing window is a
#: plateau finding
IMPROVEMENT_RATIO = 1.25
PLATEAU_BAND = 0.05
PLATEAU_WINDOW = 3


def history_findings(history: BenchHistory) -> list:
    """Cross-round trends per metric, in each rule's declared direction.

    Values still only compare across rounds LOOSELY (chip lottery swings
    absolutes ~25%+); the thresholds are set so only trend-scale moves
    (the r1->r3 λ-grid 3x) and genuine plateaus report.
    """
    findings: list[Verdict] = []
    metrics: list[str] = []
    for art in history.artifacts:
        for row in art.all_rows:
            if row.metric not in metrics:
                metrics.append(row.metric)
    for metric in metrics:
        series = history.series(metric)
        if len(series) < 2:
            continue
        r = rule_for(metric)
        higher_better = r.higher_better if r is not None else None
        (r0, first), (r1, last) = series[0], series[-1]
        if higher_better is not None and first.value:
            ratio = last.value / first.value
            improved = (
                ratio >= IMPROVEMENT_RATIO if higher_better
                else ratio <= 1.0 / IMPROVEMENT_RATIO
            )
            if improved:
                findings.append(Verdict(
                    metric=metric, rule="history-improvement", status=INFO,
                    detail=(
                        f"improved {first.value:g} (r{r0}) -> "
                        f"{last.value:g} (r{r1}), "
                        f"{max(ratio, 1 / ratio):.2f}x"
                    ),
                ))
        if len(series) >= PLATEAU_WINDOW:
            tail = [row.value for _, row in series[-PLATEAU_WINDOW:]]
            lo, hi = min(tail), max(tail)
            if lo > 0 and hi / lo <= 1.0 + PLATEAU_BAND:
                since = series[-PLATEAU_WINDOW][0]
                findings.append(Verdict(
                    metric=metric, rule="history-plateau", status=INFO,
                    detail=(
                        f"plateau at ~{tail[-1]:g} since r{since} "
                        f"(last {PLATEAU_WINDOW} rounds within "
                        f"{PLATEAU_BAND:.0%})"
                    ),
                ))
    return findings


# -- run-journal cross-checks ------------------------------------------------

#: serve/pad_fraction above this wastes most of every micro-batch on pads
PAD_FRACTION_HIGH = 0.5

#: program-ledger pathology thresholds (ISSUE 13; telemetry/program_ledger):
#: a storm is REDUNDANT compiles — compiles beyond the label's distinct
#: signature count, i.e. the same program compiled again (a program
#: instance rebuilt per step, or executable-cache eviction). Healthy
#: bounded ladders can never trip this no matter how many coordinates
#: share a label (serving's 3 shape buckets, the 5 RE entity caps, one
#: ladder per coordinate): every warm-up compile mints a NEW signature,
#: so compiles == signatures and the redundancy is zero.
RECOMPILE_STORM_REDUNDANT_MIN = 3
#: distinct signatures under one label at/past this is churn — each one is
#: a resident executable and a paid compile. A WARNING, not a pathology:
#: a label shared across coordinates/buckets legitimately carries one
#: signature per (coordinate, bucket) pair — compare the count against
#: your configured ladder before acting
SIGNATURE_CHURN_MIN = 8
#: fraction of run wall-clock spent in backend compiles past which the run
#: is compile-dominated (the tunnel's remote compiles make this fatal to
#: iteration speed); only judged on runs longer than the floor, so tiny
#: fixture runs don't all report it
COMPILE_DOMINATED_FRACTION = 0.5
COMPILE_DOMINATED_MIN_ELAPSED_S = 30.0


def _last_row(records: list, kind: str) -> dict | None:
    for row in reversed(records):
        if row.get("kind") == kind:
            return row
    return None


def journal_findings(records: list) -> list:
    """Registry-counter cross-checks over parsed run-journal rows (the
    doctor's journal half): every check is a named signature from the
    measured-facts list, with the counter value in the detail."""
    findings: list[Verdict] = []
    if not records:
        return findings
    config = _last_row(records, "config") or {}
    metrics = _last_row(records, "metrics") or {}
    snapshot = metrics.get("snapshot") or {}
    counters = snapshot.get("counters") or {}
    gauges = snapshot.get("gauges") or {}

    closed = _last_row(records, "journal_close") is not None
    hb = _last_row(records, "heartbeat")
    if not closed:
        detail = "journal never finalized — the run crashed or is in flight"
        if hb is not None:
            detail += f"; last heartbeat cursor {heartbeat_cursor(hb)}"
        findings.append(Verdict("journal", "journal-finalized", WARNING,
                                detail))
    failure = _last_row(records, "run_failure")
    if failure is not None:
        findings.append(Verdict(
            "journal", "run-failure", WARNING,
            f"run failed: {failure.get('error')} "
            f"(transient={failure.get('transient')}, "
            f"preemption={failure.get('preemption')}, "
            f"restarts_used={failure.get('restarts_used')})",
        ))

    overlap = gauges.get("stream/overlap_fraction")
    chunks = gauges.get("stream/chunks_per_epoch")
    prefetch_on = config.get("streaming_prefetch", True)
    if (
        overlap is not None and overlap < 0.01
        and prefetch_on and (chunks or 0) > 1
    ):
        findings.append(Verdict(
            "stream/overlap_fraction", "overlap-with-prefetch-on", PATHOLOGY,
            f"overlap_fraction={overlap:g} with prefetch on over "
            f"{int(chunks)} chunks/epoch — decode hid nothing; expected "
            "only when compute contends for the same host core (1-core "
            "CPU mesh), never on the tunnel",
        ))
    pad = gauges.get("serve/pad_fraction")
    if pad is not None and pad > PAD_FRACTION_HIGH:
        findings.append(Verdict(
            "serve/pad_fraction", "pad-fraction-high", WARNING,
            f"pad_fraction={pad:g}: most scored rows are padding — shrink "
            "the micro-batch shape buckets toward the real request sizes",
        ))
    quarantined = counters.get("resilience/quarantined_blocks", 0)
    if quarantined:
        findings.append(Verdict(
            "resilience/quarantined_blocks", "quarantine-nonzero", WARNING,
            f"{quarantined} corrupt block(s) quarantined (skip-and-count; "
            "spans in the quarantined_block journal rows)",
        ))
    preemptions = counters.get("resilience/preemptions", 0)
    restores = counters.get("resilience/checkpoint_restores", 0)
    if preemptions or restores:
        findings.append(Verdict(
            "resilience/preemptions", "preemption-restarts", INFO,
            f"{preemptions} preemption(s), {restores} checkpoint "
            f"restore(s), {counters.get('resilience/epochs_resumed', 0)} "
            "epochs/sweeps not redone",
        ))
    giveups = counters.get("resilience/giveups", 0)
    if giveups:
        findings.append(Verdict(
            "resilience/giveups", "restart-budget-exhausted", WARNING,
            f"{giveups} giveup(s): the restart budget ran out — the run "
            "ended on an error recovery could not absorb",
        ))
    findings.extend(_ledger_findings(records, counters, gauges, snapshot))
    straggler = _last_row(records, "straggler_report")
    if straggler is not None:
        # the PR 9 shape: {"num_ranks": N, "tags": [{tag, wait_s, count,
        # missing_ranks, straggler_rank, reason}, ...]} sorted worst-first
        tags = straggler.get("tags") or []
        named = [
            f"{t.get('tag')}: rank {t.get('straggler_rank')} "
            f"({t.get('reason')})"
            for t in tags
            if t.get("straggler_rank") is not None
        ][:5]
        findings.append(Verdict(
            "straggler_report", "straggler-attribution",
            WARNING if any(
                t.get("reason") == "never_arrived" for t in tags
            ) else INFO,
            f"straggler table over {len(tags)} exchange tag(s): "
            + ("; ".join(named) if named else "no stragglers named"),
        ))
    return findings


def _ledger_findings(records: list, counters: dict, gauges: dict,
                     snapshot: dict) -> list:
    """Program-ledger pathologies (ISSUE 13) over the journal's metrics
    snapshot + program_* rows: recompile storms (with the last attributed
    cause), signature churn, compile-seconds-dominated runs, and HBM
    overcommit forecasts."""
    findings: list[Verdict] = []
    last_attribution: dict[str, str] = {}
    for row in records:
        if row.get("kind") == "program_recompile" and row.get("label"):
            last_attribution[row["label"]] = str(row.get("summary"))
    for key, value in sorted(counters.items()):
        # NB "/recompiles" also endswith "/compiles" — exclude it first
        if (
            not key.startswith("xla/")
            or not key.endswith("/compiles")
            or key.endswith("/recompiles")
        ):
            continue
        label = key[len("xla/"):-len("/compiles")]
        sigs = gauges.get(f"xla/{label}/signatures")
        if sigs is None:
            continue
        redundant = value - int(sigs)
        if redundant >= RECOMPILE_STORM_REDUNDANT_MIN:
            cause = last_attribution.get(label)
            findings.append(Verdict(
                key, "recompile-storm", PATHOLOGY,
                f"{value} compiles for only {int(sigs)} distinct "
                f"signature(s) under '{label}' — the same program "
                f"recompiled {redundant} time(s): a program instance is "
                "being rebuilt per step, or the executable cache is "
                "thrashing"
                + (f"; last attribution: {cause}" if cause else ""),
            ))
    for key, value in sorted(gauges.items()):
        if not (key.startswith("xla/") and key.endswith("/signatures")):
            continue
        label = key[len("xla/"):-len("/signatures")]
        if value is not None and value >= SIGNATURE_CHURN_MIN:
            findings.append(Verdict(
                key, "signature-churn", WARNING,
                f"{int(value)} distinct signatures under '{label}' — each "
                "is a paid compile and a resident executable; bound the "
                "input shapes (power-of-two buckets)",
            ))
    compile_s = (
        (snapshot.get("histograms") or {})
        .get("jax/backend_compile_seconds") or {}
    ).get("total")
    elapsed_ms = records[-1].get("elapsed_ms") if records else None
    if (
        compile_s is not None and elapsed_ms
        and elapsed_ms / 1e3 >= COMPILE_DOMINATED_MIN_ELAPSED_S
        and compile_s >= COMPILE_DOMINATED_FRACTION * elapsed_ms / 1e3
    ):
        findings.append(Verdict(
            "jax/backend_compile_seconds", "compile-dominated", WARNING,
            f"{compile_s:.1f}s of backend compiles in a "
            f"{elapsed_ms / 1e3:.1f}s run "
            f"(>= {COMPILE_DOMINATED_FRACTION:.0%}) — the run is paying "
            "compiles, not compute; check the recompile attributions "
            "above / warm the signatures up front",
        ))
    overcommitted: set[str] = set()
    for row in records:
        if row.get("kind") != "program_compile":
            continue
        forecast = row.get("hbm_forecast_bytes")
        limit = row.get("device_bytes_limit")
        label = row.get("label")
        if (
            forecast is not None and limit is not None
            and forecast > limit and label not in overcommitted
        ):
            overcommitted.add(label)
            findings.append(Verdict(
                f"xla/{label}/hbm_forecast_bytes", "hbm-overcommit-forecast",
                WARNING,
                f"'{label}' forecasts {forecast / 1e9:.2f} GB resident+temp "
                f"against a {limit / 1e9:.2f} GB device limit — the next "
                "dispatch risks an OOM; shrink the batch/bucket or shard "
                "the params",
            ))
    return findings


def coordination_findings(records: list) -> list:
    """Cross-rank coordinated-recovery findings (ISSUE 15) over the
    MERGED journal rows of every rank's journal in a run directory: the
    per-rank restart table (restarts / aborts observed / aborts written /
    generations, from ``coordinated_restart`` / ``peer_abort`` /
    ``abort_written`` rows) and the RESTART-STORM pathology — the job's
    shared budget exhausted with the SAME culprit rank attributed every
    time, which names the rank to drain/replace instead of a generic
    "budget ran out"."""
    findings: list[Verdict] = []
    per_rank: dict[int, dict] = {}

    def ent(rank) -> dict | None:
        if rank is None:
            return None
        return per_rank.setdefault(int(rank), {
            "restarts": 0, "aborts_observed": 0, "aborts_written": 0,
            "blamed": 0, "max_generation": 0,
        })

    origins: list = []
    origin_generations: set = set()
    exhausted_rows: list[dict] = []
    for row in records:
        kind = row.get("kind")
        if kind == "coordinated_restart":
            e = ent(row.get("rank"))
            if e is not None:
                e["restarts"] += 1
                e["max_generation"] = max(
                    e["max_generation"], int(row.get("generation") or 0)
                )
            if row.get("origin_rank") is not None:
                origins.append(int(row["origin_rank"]))
                # every rank journals the SAME restart: distinct
                # generations count actual restarts, not rank-rows
                origin_generations.add(int(row.get("generation") or 0))
                blamed = ent(row["origin_rank"])
                blamed["blamed"] += 1
            if row.get("exhausted"):
                exhausted_rows.append(row)
        elif kind == "peer_abort":
            e = ent(row.get("rank"))
            if e is not None:
                e["aborts_observed"] += 1
        elif kind == "abort_written":
            e = ent(row.get("rank"))
            if e is not None:
                e["aborts_written"] += 1
        elif kind == "run_failure" and row.get("origin_rank") is not None:
            if row.get("restarts_used") is not None and row.get(
                "max_restarts"
            ) is not None and int(row["restarts_used"]) >= int(
                row["max_restarts"]
            ):
                exhausted_rows.append(row)
    if not per_rank:
        return findings
    table = "; ".join(
        f"rank {r}: restarts={e['restarts']} "
        f"aborts_observed={e['aborts_observed']} "
        f"aborts_written={e['aborts_written']} blamed={e['blamed']} "
        f"max_gen={e['max_generation']}"
        for r, e in sorted(per_rank.items())
    )
    findings.append(Verdict(
        "coordination", "cross-rank-restart-table", INFO,
        f"coordinated recovery over {len(per_rank)} rank(s): {table}",
    ))
    if exhausted_rows and origins and len(set(origins)) == 1:
        culprit = origins[0]
        findings.append(Verdict(
            "coordination", "restart-storm", PATHOLOGY,
            f"restart budget exhausted with rank {culprit} attributed as "
            f"the origin of every coordinated restart "
            f"({len(origin_generations)} restart generation(s)) — one "
            "flapping rank is burning the JOB's shared budget; "
            "drain/replace that worker before re-running",
        ))
    return findings


def last_abort_marker(records: list) -> dict | None:
    """The newest abort attribution seen in the merged journal rows — a
    ``peer_abort`` (observer side) or ``abort_written`` (culprit side)
    row. Newest by (generation, wall clock), NOT by file-concatenation
    order: the merge walks per-rank journals one at a time, so the last
    row read can be a stale rank's. What ``doctor --live`` prints while a
    run is wedged mid-restart."""
    last = None
    last_key = None
    for row in records:
        if row.get("kind") not in ("peer_abort", "abort_written"):
            continue
        key = (
            int(row.get("generation") or -1),
            float(row.get("ts") or 0.0),
        )
        if last_key is None or key >= last_key:
            last, last_key = row, key
    return last


def regressions(verdicts: list) -> list:
    return [v for v in verdicts if v.status == REGRESSION]
