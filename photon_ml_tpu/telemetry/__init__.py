"""Structured run telemetry: metrics registry, JSONL run journal, solver
convergence tracing, device/runtime probes.

Reference parity: the PhotonLogger / OptimizationStatesTracker /
PhotonOptimizationLogEvent triple (photon-lib util/PhotonLogger.scala:34-90,
OptimizationStatesTracker.scala:82-101, photon-client event/ emitted from
Driver.scala:120-393) rebuilt as one subsystem the whole stack emits
through — see each submodule's docstring for its slice of the map.
"""

from photon_ml_tpu.telemetry.journal import (
    JOURNAL_FILENAME,
    JOURNAL_PARTIAL_SUFFIX,
    RunJournal,
    json_safe,
    read_journal,
)
from photon_ml_tpu.telemetry.layout import (
    LAYOUT_METRIC_PREFIX,
    record_hybrid_layout,
    reset_layout_metrics,
)
from photon_ml_tpu.telemetry.probes import (
    GATE_REPS,
    CompileMonitor,
    MarginalResult,
    MarginalTimer,
    compile_count,
    install_compile_listener,
    live_buffer_bytes,
    median_spread,
    read_scalar,
    scan_step_marginal,
    stream_calibration,
)
from photon_ml_tpu.telemetry.program_ledger import (
    ProgramLedger,
    current_ledger,
    install_ledger,
    ledger_active,
    ledger_jit,
    uninstall_ledger,
)
from photon_ml_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)
from photon_ml_tpu.telemetry.tracing import (
    Tracer,
    current_tracer,
    exchange_wait_tables,
    finalize_trace,
    flush_trace_best_effort,
    gather_straggler_report,
    install_tracer,
    publish_trace,
    span,
    straggler_report,
    tracing_active,
    uninstall_tracer,
)
# solver_trace pulls jax/flax (via optim.common); load it lazily so that
# importing the registry/journal/probes side of telemetry — which util.timed
# does on every import — stays jax-free (the drivers/conftest configure the
# platform before jax ever loads).
_LAZY = {
    "SolverTelemetry": "photon_ml_tpu.telemetry.solver_trace",
    "lane_rows": "photon_ml_tpu.telemetry.solver_trace",
    "lane_summary": "photon_ml_tpu.telemetry.solver_trace",
    "solver_result_row": "photon_ml_tpu.telemetry.solver_trace",
}


def __getattr__(name: str):
    module = _LAZY.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)


__all__ = [
    "JOURNAL_FILENAME",
    "JOURNAL_PARTIAL_SUFFIX",
    "RunJournal",
    "json_safe",
    "read_journal",
    "LAYOUT_METRIC_PREFIX",
    "record_hybrid_layout",
    "reset_layout_metrics",
    "GATE_REPS",
    "CompileMonitor",
    "MarginalResult",
    "MarginalTimer",
    "compile_count",
    "install_compile_listener",
    "live_buffer_bytes",
    "median_spread",
    "read_scalar",
    "scan_step_marginal",
    "stream_calibration",
    "ProgramLedger",
    "current_ledger",
    "install_ledger",
    "ledger_active",
    "ledger_jit",
    "uninstall_ledger",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "default_registry",
    "SolverTelemetry",
    "lane_rows",
    "lane_summary",
    "solver_result_row",
    "Tracer",
    "current_tracer",
    "exchange_wait_tables",
    "finalize_trace",
    "flush_trace_best_effort",
    "gather_straggler_report",
    "install_tracer",
    "publish_trace",
    "span",
    "straggler_report",
    "tracing_active",
    "uninstall_tracer",
]
