"""Compiled-program ledger: per-program compile, cost, and HBM accounting
with recompile attribution.

No reference analogue: Photon-ML's unit of execution observability is the
Spark task (SURVEY.md §5); here the whole perf story rides a bounded set of
module-level jitted programs (streaming accumulators, vmapped bucket
solvers, serving shape buckets), so the compiled PROGRAM becomes the
first-class observed object — the DrJAX framing (arXiv:2403.07128: the
single traced program is the unit of system reasoning) crossed with
Snap ML's memory-hierarchy budgeting (arXiv:1803.06333).

Design (ISSUE 13):

- ``ledger_jit(fn, label=...)`` wraps ``jax.jit`` with a STABLE LABEL.
  Inert null-object by default (the tracing discipline — telemetry/
  tracing.py): with no ledger installed the wrapper is one global read +
  a passthrough call; installing a :class:`ProgramLedger` turns every
  labeled call into an observation. Observes, NEVER gates: the wrapped
  program dispatches exactly as the raw jit would — same arguments, same
  donation, same order (ledger on/off is pinned bitwise by
  tests/test_program_ledger.py).
- **Compile detection is a scoped compile-counter delta** around each
  dispatch (probes.install_compile_listener feeds the counter; the repo's
  dispatch model is single-consumer, so the delta attributes cleanly).
  This catches every real compile — new shapes, fresh program instances,
  evicted executables — without guessing from the signature cache.
- **Signatures** record every argument leaf's aval (shape, dtype,
  sharding), weak-typed python scalars (whose VALUE changes never
  recompile — they are deliberately not part of the signature), and
  static args (described by value for simple types, by type+hash
  otherwise — matching jit's own static-arg cache semantics, where a
  fresh instance with identity hash IS a new cache entry).
- **Recompile attribution is the headline**: a compile under a label that
  already compiled diffs the new signature against the previous compiled
  one and journals the exact differing leaves — turning "compile count
  went up" into "arg3.features: shape (16384, 8) -> (16000, 8) at
  streaming/accumulate_value_grad".
- **Cost analysis is free; memory analysis is not.** ``Lowered.
  cost_analysis()`` is an HLO-level analysis with NO backend compile
  (measured on this stack), so it runs for every new signature.
  ``Compiled.memory_analysis()`` requires an AOT ``lowered.compile()``,
  which this JAX does NOT share with the dispatch cache — a real second
  backend compile (measured; ~an extra remote compile per signature on
  the tunnel) — so it is opt-in (``analyze_memory=True``). Both degrade
  gracefully to None fields where the backend doesn't implement them
  (the CPU mesh), never raising into the dispatch path.
- **HBM forecast**: with memory analysis on, each compile row carries
  ``hbm_forecast_bytes`` = resident placed params (the layout-keyed
  cache's ``serve/resident_params_bytes`` gauge when fed, else the live
  device-buffer bytes probe) + the program's temp bytes, against the
  device's ``bytes_limit`` where the backend reports one —
  telemetry/verdicts.py turns forecast > limit into a finding.

Calls made while a jax trace is in flight bypass the ledger entirely: an
inner jitted step invoked during an outer trace inlines into the outer
program — it is not a separately dispatched program, and observing it
would double-count.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import threading

logger = logging.getLogger(__name__)

#: registry namespace for every per-label metric the ledger emits
LEDGER_METRIC_PREFIX = "xla/"

#: journal row kinds (dev/doctor.py's ledger table reads all three)
COMPILE_ROW = "program_compile"
RECOMPILE_ROW = "program_recompile"
SIGNATURE_ROW = "program_signature"

#: signatures retained per label for diffing; the oldest fall off (the
#: bounded-signature discipline is the point — a label that outgrows this
#: is itself the signature-churn pathology)
MAX_SIGNATURES_PER_LABEL = 64

#: cost_analysis keys worth journaling (the per-opcode utilization{...}
#: expansions are dropped — rows must stay small)
_COST_KEYS = ("flops", "bytes accessed", "transcendentals", "optimal_seconds")

#: CompiledMemoryStats attributes journaled when memory analysis runs
_MEMORY_ATTRS = (
    "generated_code_size_in_bytes",
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "alias_size_in_bytes",
    "temp_size_in_bytes",
    "peak_memory_in_bytes",
)


# ---------------------------------------------------------------------------
# Signatures
# ---------------------------------------------------------------------------

#: leaf kinds
ARRAY = "array"
WEAK = "weak"
STATIC = "static"


def _describe_static(v) -> str:
    """Stable description of a static argument, matching jit's cache
    semantics: simple values by repr (value-equal -> same entry), rich
    objects by type + hash (a default identity hash means a fresh instance
    IS a new jit cache entry, and the ledger must say so)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return repr(v)
    if isinstance(v, tuple):
        return "(" + ", ".join(_describe_static(x) for x in v) + ")"
    try:
        h = hash(v)
    except TypeError:
        return f"{type(v).__qualname__}@{id(v):#x}"
    return f"{type(v).__qualname__}#{h}"


def _describe_leaf(v) -> tuple:
    shape = getattr(v, "shape", None)
    dtype = getattr(v, "dtype", None)
    if shape is not None and dtype is not None:
        sharding = getattr(v, "sharding", None)
        return (
            ARRAY,
            tuple(int(s) for s in shape),
            str(dtype),
            None if sharding is None else str(sharding),
        )
    if isinstance(v, (bool, int, float, complex)):
        # traced weak-typed scalar: its VALUE never keys the jit cache
        return (WEAK, type(v).__name__)
    return (STATIC, _describe_static(v))


def _path_str(path) -> str:
    """['arg0'].features-style keys, compactly joined with dots."""
    parts = []
    for entry in path:
        key = getattr(entry, "key", None)
        if key is None:
            key = getattr(entry, "name", None)
        if key is None:
            key = getattr(entry, "idx", None)
        parts.append(str(key) if key is not None else str(entry))
    return ".".join(parts)


@dataclasses.dataclass(frozen=True)
class ProgramSignature:
    """One call's argument signature: dynamic leaves (path -> aval
    description) + static args (name -> description)."""

    leaves: tuple  # ((path, desc-tuple), ...)
    static: tuple  # ((name, description), ...)

    @property
    def key(self):
        return (self.leaves, self.static)

    def to_json(self) -> dict:
        return {
            "leaves": [
                {"path": p, "kind": d[0],
                 **({"shape": list(d[1]), "dtype": d[2], "sharding": d[3]}
                    if d[0] == ARRAY else {"value": d[1]})}
                for p, d in self.leaves
            ],
            "static": [{"name": n, "value": s} for n, s in self.static],
        }


def build_signature(args, kwargs, static_argnums=(), static_argnames=()) -> ProgramSignature:
    import jax

    dyn: dict = {}
    statics: list = []
    nums = set(static_argnums or ())
    names = set(static_argnames or ())
    for i, a in enumerate(args):
        if i in nums:
            statics.append((f"arg{i}", _describe_static(a)))
        else:
            dyn[f"arg{i}"] = a
    for k, v in kwargs.items():
        if k in names:
            statics.append((k, _describe_static(v)))
        else:
            dyn[k] = v
    leaves = tuple(
        (_path_str(path), _describe_leaf(leaf))
        for path, leaf in jax.tree_util.tree_flatten_with_path(dyn)[0]
    )
    return ProgramSignature(leaves=leaves, static=tuple(sorted(statics)))


_ARRAY_FIELDS = (("shape", 1), ("dtype", 2), ("sharding", 3))


def diff_signatures(old: ProgramSignature, new: ProgramSignature) -> list[dict]:
    """The differing leaves between two signatures — the attribution a
    recompile row carries. Each change names the leaf path, the field
    (shape/dtype/sharding/kind/presence/static) and old -> new values."""
    changes: list[dict] = []
    o, n = dict(old.leaves), dict(new.leaves)
    for path in sorted(o.keys() | n.keys()):
        a, b = o.get(path), n.get(path)
        if a == b:
            continue
        if a is None or b is None:
            changes.append({"leaf": path, "field": "presence",
                            "old": None if a is None else list(a),
                            "new": None if b is None else list(b)})
            continue
        if a[0] != b[0]:
            changes.append({"leaf": path, "field": "kind",
                            "old": a[0], "new": b[0]})
            continue
        if a[0] == ARRAY:
            for field, idx in _ARRAY_FIELDS:
                if a[idx] != b[idx]:
                    changes.append({
                        "leaf": path, "field": field,
                        "old": list(a[idx]) if field == "shape" else a[idx],
                        "new": list(b[idx]) if field == "shape" else b[idx],
                    })
        else:
            changes.append({"leaf": path, "field": a[0],
                            "old": a[1], "new": b[1]})
    os_, ns_ = dict(old.static), dict(new.static)
    for name in sorted(os_.keys() | ns_.keys()):
        if os_.get(name) != ns_.get(name):
            changes.append({"leaf": name, "field": "static",
                            "old": os_.get(name), "new": ns_.get(name)})
    return changes


def diff_summary(changes: list[dict], limit: int = 4) -> str:
    """One human line per recompile row: 'leaf: field old -> new; ...'."""
    if not changes:
        return ("signature identical to the previous compile — a fresh "
                "program instance or an evicted executable recompiled the "
                "same shapes")
    parts = [
        f"{c['leaf']}: {c['field']} {c['old']} -> {c['new']}"
        for c in changes[:limit]
    ]
    if len(changes) > limit:
        parts.append(f"(+{len(changes) - limit} more)")
    return "; ".join(parts)


# ---------------------------------------------------------------------------
# The ledger
# ---------------------------------------------------------------------------


class _LabelRecord:
    __slots__ = ("signatures", "order", "last_compiled", "calls", "compiles",
                 "recompiles", "distinct")

    def __init__(self):
        self.signatures: dict = {}  # key -> ProgramSignature
        self.order: list = []  # keys, oldest first (bounded eviction)
        self.last_compiled: ProgramSignature | None = None
        self.calls = 0
        self.compiles = 0
        self.recompiles = 0
        #: MONOTONE distinct-signature count: eviction bounds the diff
        #: cache above, never this — the signatures gauge and the doctor's
        #: redundancy math (compiles - signatures) must stay exact past
        #: max_signatures, or an unbounded-shape churn run would read as
        #: executable thrash
        self.distinct = 0


class ProgramLedger:
    """Per-label compile/cost/HBM accounting over ledger_jit call sites.

    registry: metrics sink (default: the process registry) —
    ``xla/<label>/{calls,compiles,recompiles}`` counters,
    ``xla/<label>/compile_seconds`` histogram, ``xla/<label>/{signatures,
    flops,bytes_accessed,temp_bytes,peak_bytes,hbm_forecast_bytes}``
    gauges. journal: optional RunJournal — compile/recompile/signature
    rows land there (inert on worker ranks, the journal's own rule).
    analyze_cost: ``Lowered.cost_analysis()`` per NEW signature (default
    on) — no backend compile, but the AOT ``lower()`` it needs re-traces
    the program once per signature on the host (AOT lowering does not
    share the dispatch path's trace); turn it off to make the ledger pure
    bookkeeping on runs where tracing the biggest programs twice matters.
    analyze_memory: opt-in ``Compiled.memory_analysis()`` — costs one
    EXTRA backend compile per new signature on this JAX (the AOT cache is
    not shared with dispatch; measured), so it must never default on.
    """

    def __init__(self, *, registry=None, journal=None,
                 analyze_cost: bool = True,
                 analyze_memory: bool = False,
                 max_signatures: int = MAX_SIGNATURES_PER_LABEL):
        from photon_ml_tpu.telemetry.registry import default_registry

        self.registry = registry or default_registry()
        self.journal = journal
        self.analyze_cost = bool(analyze_cost)
        self.analyze_memory = bool(analyze_memory)
        self.max_signatures = int(max_signatures)
        #: free-form run phase ("warm"/"replay"/...) stamped on rows —
        #: serve_driver sets it so a replay compile is attributed to the
        #: replay, not just to the label
        self.phase: str | None = None
        self._labels: dict[str, _LabelRecord] = {}
        self._lock = threading.Lock()

    # -- introspection -------------------------------------------------------

    def set_phase(self, phase: str | None) -> None:
        self.phase = phase

    def labels(self) -> list[str]:
        with self._lock:
            return sorted(self._labels)

    def signature_count(self, label: str) -> int:
        """Distinct signatures observed under ``label`` — monotone (the
        diff cache's eviction never shrinks it)."""
        with self._lock:
            rec = self._labels.get(label)
            return 0 if rec is None else rec.distinct

    def snapshot(self) -> dict:
        """{label: {calls, compiles, recompiles, signatures}} — what
        serve_driver folds into its summary."""
        with self._lock:
            return {
                label: {
                    "calls": rec.calls,
                    "compiles": rec.compiles,
                    "recompiles": rec.recompiles,
                    "signatures": rec.distinct,
                }
                for label, rec in sorted(self._labels.items())
            }

    # -- observation ---------------------------------------------------------

    def _metric(self, label: str, name: str) -> str:
        return f"{LEDGER_METRIC_PREFIX}{label}/{name}"

    def observed_call(self, jitted, label: str, args, kwargs,
                      static_argnums=(), static_argnames=()):
        """Dispatch ``jitted(*args, **kwargs)`` under observation. The
        dispatch itself is untouched; everything else is bookkeeping on
        the host, recorded on success AND failure paths."""
        from photon_ml_tpu.telemetry import probes

        probes.install_compile_listener(self.registry)
        sig = build_signature(args, kwargs, static_argnums, static_argnames)
        with self._lock:
            rec = self._labels.setdefault(label, _LabelRecord())
            is_new = sig.key not in rec.signatures
        analysis = None
        if is_new:
            # args are still alive here (before any donation) — lowering
            # needs only their avals, but never touch them post-dispatch
            analysis = self._analyze(jitted, args, kwargs)
        counter = self.registry.counter(probes.COMPILE_COUNT_METRIC)
        seconds = self.registry.histogram(probes.COMPILE_SECONDS_METRIC)
        c0, s0 = counter.value, seconds.total
        error = None
        try:
            return jitted(*args, **kwargs)
        except Exception as e:
            error = type(e).__name__
            raise
        finally:
            self._record(
                label, sig, is_new, analysis,
                compiles=counter.value - c0,
                compile_seconds=seconds.total - s0,
                error=error,
            )

    def _analyze(self, jitted, args, kwargs) -> dict:
        """Lower the call for cost analysis (no backend compile) and, when
        opted in, AOT-compile for memory analysis. A capability probe:
        every failure IS the answer (None fields), logged at debug and
        never raised into the dispatch path (reviewed broad except —
        dev/lint_parity.py check 5 allowlist)."""
        from photon_ml_tpu.telemetry import probes

        out: dict = {"cost": None, "memory": None, "hbm_forecast_bytes": None,
                     "device_bytes_limit": None}
        if not (self.analyze_cost or self.analyze_memory):
            return out
        try:
            lowered = jitted.lower(*args, **kwargs)
        except Exception:
            logger.debug("program ledger: lower() unavailable", exc_info=True)
            return out
        try:
            cost = lowered.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else None
            if cost:
                out["cost"] = {
                    k: float(cost[k]) for k in _COST_KEYS if k in cost
                }
        except Exception:
            logger.debug("program ledger: cost_analysis unavailable",
                         exc_info=True)
        if not self.analyze_memory:
            return out
        try:
            mem = lowered.compile().memory_analysis()
            memory = {
                a: int(getattr(mem, a))
                for a in _MEMORY_ATTRS
                if getattr(mem, a, None) is not None
            }
            out["memory"] = memory or None
        except Exception:
            logger.debug("program ledger: memory_analysis unavailable",
                         exc_info=True)
            return out
        temp = (out["memory"] or {}).get("temp_size_in_bytes")
        peak = (out["memory"] or {}).get("peak_memory_in_bytes", temp)
        if peak is not None:
            resident = self._resident_bytes()
            if resident is not None:
                out["hbm_forecast_bytes"] = int(resident) + int(peak)
        out["device_bytes_limit"] = probes.device_memory_limit_bytes()
        return out

    def refeed_resident_forecast(self, label: str) -> int | None:
        """Recompute ``xla/<label>/hbm_forecast_bytes`` from the CURRENT
        resident placed-params bytes plus the label's recorded peak — the
        hot-swap hook (serving/resident.py): a same-layout model swap
        triggers no compile, so without this the forecast gauge would keep
        pricing the STALE model's resident bytes. Returns the new forecast,
        or None when either input is unknown (no memory analysis ran, or
        nothing feeds the resident gauge); journals a
        ``program_forecast_refeed`` row when it changes."""
        peak = self.registry.gauge(self._metric(label, "peak_bytes")).value
        if peak is None:
            peak = self.registry.gauge(self._metric(label, "temp_bytes")).value
        resident = self._resident_bytes()
        if peak is None or resident is None:
            return None
        forecast = int(resident) + int(peak)
        self.registry.gauge(
            self._metric(label, "hbm_forecast_bytes")
        ).set(forecast)
        if self.journal is not None:
            self.journal.record(
                "program_forecast_refeed", label=label, phase=self.phase,
                resident_bytes=int(resident), peak_bytes=int(peak),
                hbm_forecast_bytes=forecast,
            )
        return forecast

    def _resident_bytes(self) -> int | None:
        """Resident placed-params bytes: the layout-keyed cache's gauge
        when someone feeds it (parallel/scoring.py), else the live
        device-buffer probe."""
        from photon_ml_tpu.telemetry import serving_counters

        gauge = self.registry.gauge(
            serving_counters.RESIDENT_PARAMS_BYTES
        ).value
        if gauge is not None:
            return int(gauge)
        try:
            from photon_ml_tpu.telemetry.probes import live_buffer_bytes

            return int(live_buffer_bytes())
        except (ImportError, RuntimeError):
            return None

    def _record(self, label: str, sig: ProgramSignature, is_new: bool,
                analysis: dict | None, *, compiles: int,
                compile_seconds: float, error: str | None) -> None:
        reg = self.registry
        with self._lock:
            rec = self._labels[label]
            rec.calls += 1
            prior = rec.last_compiled
            if prior is None:
                # the program may have compiled before this ledger was
                # installed — attribute against the most recent OTHER
                # cached signature rather than dropping the diff
                for key in reversed(rec.order):
                    if key != sig.key:
                        prior = rec.signatures[key]
                        break
            if is_new and sig.key not in rec.signatures:
                rec.distinct += 1
                rec.signatures[sig.key] = sig
                rec.order.append(sig.key)
                while len(rec.order) > self.max_signatures:
                    del rec.signatures[rec.order.pop(0)]
            if compiles > 0:
                rec.compiles += compiles
                rec.last_compiled = sig
                if prior is not None:
                    rec.recompiles += 1
            num_signatures = rec.distinct
            recompiled = compiles > 0 and prior is not None
        reg.counter(self._metric(label, "calls")).inc()
        reg.gauge(self._metric(label, "signatures")).set(num_signatures)
        if compiles <= 0:
            if is_new and self.journal is not None:
                # observed without a compile: the program was already
                # cached (ledger installed mid-run) — still worth a row so
                # the doctor table covers it
                self.journal.record(
                    SIGNATURE_ROW, label=label, phase=self.phase,
                    signature=sig.to_json(),
                    cost=None if analysis is None else analysis["cost"],
                )
            return
        reg.counter(self._metric(label, "compiles")).inc(compiles)
        reg.histogram(self._metric(label, "compile_seconds")).observe(
            compile_seconds
        )
        if recompiled:
            reg.counter(self._metric(label, "recompiles")).inc()
        cost = memory = forecast = limit = None
        if analysis is not None:
            cost = analysis["cost"]
            memory = analysis["memory"]
            forecast = analysis["hbm_forecast_bytes"]
            limit = analysis["device_bytes_limit"]
            if cost is not None:
                for key, name in (("flops", "flops"),
                                  ("bytes accessed", "bytes_accessed")):
                    if key in cost:
                        reg.gauge(self._metric(label, name)).set(cost[key])
            if memory is not None:
                for attr, name in (("temp_size_in_bytes", "temp_bytes"),
                                   ("peak_memory_in_bytes", "peak_bytes"),
                                   ("argument_size_in_bytes",
                                    "argument_bytes"),
                                   ("output_size_in_bytes", "output_bytes")):
                    if attr in memory:
                        reg.gauge(self._metric(label, name)).set(memory[attr])
            if forecast is not None:
                reg.gauge(
                    self._metric(label, "hbm_forecast_bytes")
                ).set(forecast)
        if self.journal is None:
            return
        if recompiled:
            changes = diff_signatures(prior, sig)
            self.journal.record(
                RECOMPILE_ROW, label=label, phase=self.phase,
                changed=changes, summary=diff_summary(changes),
                compiles=compiles,
                compile_seconds=round(compile_seconds, 6), error=error,
            )
        self.journal.record(
            COMPILE_ROW, label=label, phase=self.phase,
            new_signature=is_new, signature=sig.to_json(),
            compiles=compiles, compile_seconds=round(compile_seconds, 6),
            cost=cost, memory=memory, hbm_forecast_bytes=forecast,
            device_bytes_limit=limit, error=error,
        )


# ---------------------------------------------------------------------------
# The module-level hook (inert by default) + the registration wrapper
# ---------------------------------------------------------------------------

_LEDGER: ProgramLedger | None = None


def ledger_active() -> bool:
    return _LEDGER is not None


def current_ledger() -> ProgramLedger | None:
    return _LEDGER


def install_ledger(ledger: ProgramLedger) -> ProgramLedger:
    """Make ``ledger`` the process-wide sink for ledger_jit sites."""
    global _LEDGER
    _LEDGER = ledger
    return ledger


def uninstall_ledger() -> ProgramLedger | None:
    """Remove (and return) the installed ledger — drivers pair this with
    install in a try/finally so a failed run never leaks observation into
    the next one."""
    global _LEDGER
    ledger, _LEDGER = _LEDGER, None
    return ledger


def _as_tuple(v) -> tuple:
    if v is None:
        return ()
    if isinstance(v, (tuple, list)):
        return tuple(v)
    return (v,)


def ledger_jit(fn=None, *, label: str, **jit_kwargs):
    """``jax.jit`` with a stable program label the ledger observes by.

    Drop-in at every hot jit site (dev/lint_parity.py check 13 makes the
    labeling structural in algorithm/, serving/ and parallel/): identical
    dispatch semantics — all ``jit_kwargs`` (static_argnums/names,
    donate_argnums, ...) pass straight through — plus, when a ledger is
    installed, per-call compile/cost/signature observation. Usable bare
    or through ``partial`` as a decorator. Calls made while a jax trace
    is in flight bypass observation (an inlined inner step is not a
    dispatched program).
    """
    if fn is None:
        return functools.partial(ledger_jit, label=label, **jit_kwargs)
    import jax

    jitted = jax.jit(fn, **jit_kwargs)
    static_argnums = _as_tuple(jit_kwargs.get("static_argnums"))
    static_argnames = _as_tuple(jit_kwargs.get("static_argnames"))

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        ledger = _LEDGER
        if ledger is None or not jax.core.trace_state_clean():
            return jitted(*args, **kwargs)
        return ledger.observed_call(
            jitted, label, args, kwargs, static_argnums, static_argnames
        )

    wrapper.label = label
    wrapper.jitted = jitted
    # preserve the jit AOT surface: callers inspect programs via
    # .lower(...).compile().as_text() (HLO pins in tests) and the ledger
    # must not take that away
    wrapper.lower = jitted.lower
    for name in ("trace", "eval_shape", "clear_cache"):
        attr = getattr(jitted, name, None)
        if attr is not None:
            setattr(wrapper, name, attr)
    return wrapper
