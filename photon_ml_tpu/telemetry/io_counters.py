"""Per-rank partitioned host-I/O counters: the observable 1/P.

Reference parity: Spark surfaced per-executor input/output byte metrics in
its task UI for AvroDataReader.scala / ScoreProcessingUtils.scala work;
here the equivalent per-RANK evidence lives in the process-wide metrics
registry, so the two-process e2e (and any run journal) can prove each of P
ranks touched ~1/P of the input and output bytes instead of a silent
full-read multiply.

Names are constants so producers (io/partitioned_reader.py,
io/score_writer.py) and consumers (tests, journals) cannot drift.
"""

from __future__ import annotations

from photon_ml_tpu.telemetry.registry import default_registry

#: bytes of input this RANK decoded (container file bytes in file mode,
#: selected block payload bytes in block mode)
BYTES_DECODED = "io/partitioned/bytes_decoded"
#: total bytes of the input across all ranks (gauge — same on every rank)
INPUT_BYTES_TOTAL = "io/partitioned/input_bytes_total"
#: bytes of score output this RANK wrote (its own part files only)
SCORE_BYTES_WRITTEN = "io/partitioned/score_bytes_written"


def record_bytes_decoded(n: int) -> None:
    default_registry().counter(BYTES_DECODED).inc(int(n))


def set_input_bytes_total(n: int) -> None:
    default_registry().gauge(INPUT_BYTES_TOTAL).set(int(n))


def record_score_bytes_written(n: int) -> None:
    default_registry().counter(SCORE_BYTES_WRITTEN).inc(int(n))


def bytes_decoded() -> int:
    return int(default_registry().counter(BYTES_DECODED).value)


def input_bytes_total() -> int:
    value = default_registry().gauge(INPUT_BYTES_TOTAL).value
    return int(value or 0)


def score_bytes_written() -> int:
    return int(default_registry().counter(SCORE_BYTES_WRITTEN).value)
