"""Incremental-refresh telemetry: lane-selection counters + swap evidence.

No reference analogue as code: the reference's partial retraining
(CoordinateDescent.scala:44-49) locks whole coordinates and leaves no
evidence of what it saved; the refresh policy (algorithm/refresh.py) selects
at ENTITY granularity, so the acceptance criterion — strictly fewer RE
lane-solves than the full fit — must be COUNTED, not asserted in prose.
These metrics are that count: how many lanes each refresh selected (and
why), how many it carried over untouched, per coordinate and per run.

Names are constants so producers (algorithm/refresh.py) and consumers
(tests, journals, bench.py, cli/game_training_driver.py) cannot drift —
the same contract as telemetry/serving_counters.py.
"""

from __future__ import annotations

from photon_ml_tpu.telemetry.registry import default_registry

#: prefix shared by every refresh metric (reset_refresh_metrics)
REFRESH_METRIC_PREFIX = "refresh/"
#: valid RE lanes the refresh could have re-solved (the full fit's count)
LANES_TOTAL = "refresh/lanes_total"
#: lanes the policy actually re-solved — the acceptance criterion is
#: lanes_solved < lanes_total, strictly
LANES_SOLVED = "refresh/lanes_solved"
#: lanes selected because their entity was DECLARED changed (new data)
LANES_CHANGED = "refresh/lanes_changed"
#: lanes selected because their resident-solution gradient exceeded the
#: policy tolerance (catches undeclared drift)
LANES_GRADIENT = "refresh/lanes_gradient"
#: coordinates whose entities were (partially) re-solved
COORDINATES_REFRESHED = "refresh/coordinates_refreshed"
#: coordinates carried over untouched (fixed effects, MF, no selection)
COORDINATES_CARRIED = "refresh/coordinates_carried"


def reset_refresh_metrics(registry=None) -> None:
    """Drop per-run refresh metrics — the training driver calls this at
    run start next to ``reset_resilience_metrics``, so a journal snapshot
    carries only this run's selection evidence."""
    reg = registry or default_registry()
    reg.remove_prefix(REFRESH_METRIC_PREFIX)


def record_selection(*, lanes_total: int, lanes_solved: int,
                     lanes_changed: int, lanes_gradient: int) -> None:
    """One refreshed coordinate's selection outcome."""
    reg = default_registry()
    reg.counter(LANES_TOTAL).inc(int(lanes_total))
    reg.counter(LANES_SOLVED).inc(int(lanes_solved))
    reg.counter(LANES_CHANGED).inc(int(lanes_changed))
    reg.counter(LANES_GRADIENT).inc(int(lanes_gradient))
    reg.counter(COORDINATES_REFRESHED).inc()


def record_carried_coordinate(n: int = 1) -> None:
    default_registry().counter(COORDINATES_CARRIED).inc(int(n))


def selection_evidence() -> dict:
    """The counters as a summary dict (driver summaries, bench rows)."""
    reg = default_registry()
    return {
        "lanes_total": int(reg.counter(LANES_TOTAL).value),
        "lanes_solved": int(reg.counter(LANES_SOLVED).value),
        "lanes_changed": int(reg.counter(LANES_CHANGED).value),
        "lanes_gradient": int(reg.counter(LANES_GRADIENT).value),
        "coordinates_refreshed": int(
            reg.counter(COORDINATES_REFRESHED).value
        ),
        "coordinates_carried": int(reg.counter(COORDINATES_CARRIED).value),
    }
