"""Cross-rank run tracing: host-side spans, Chrome-trace export, straggler
attribution.

Reference parity: photon-lib util/Timed.scala:21-34 (wall-clock phase
blocks) crossed with util/PhotonLogger.scala:34-90 (spool locally, publish
atomically) — extended past the reference: the reference's timings are
driver-local aggregates, while a composed multi-rank run here needs to know
*where the wall-clock went* (decode vs exchange wait vs device dispatch vs
checkpoint barrier) and *which rank* is the straggler. This module provides:

- ``span(name, **attrs)`` — a context manager over ``time.perf_counter``
  recording (name, category, start, duration, attrs) into a per-thread
  ring buffer. Inert by default: with no tracer installed it returns a
  shared null object (one dict build + one attribute read — no locks, no
  allocation on the buffer side), the ``EventEmitter.has_listeners``
  discipline. Spans OBSERVE, never gate: instrumentation wraps existing
  calls with a timer and must never add, skip, reorder, or retry a
  collective (the PR 3 rule — one rank retrying an exchange desyncs SPMD).
- Chrome-trace/Perfetto export: ``publish_trace`` writes
  ``trace-{rank:05d}.json`` (catapult event format: complete ``"X"``
  events, ``pid`` = rank, ``tid`` = thread) atomically into the trace dir
  under the multi-process rules — rank 0 mkdir, barrier, per-rank write
  (the ``io/score_writer.py`` carve-out). On the FAILURE path the barrier
  is deadline-bounded and a timeout falls back to an unbarriered write so
  a crash still leaves a readable timeline.
- Straggler attribution: every exchange op (``parallel/multihost.py``)
  records its blocking wait as a span carrying ``tag`` + ``rank``;
  ``exchange_wait_tables`` aggregates per-rank per-tag wait totals and
  ``straggler_report`` names, for every tag, the rank that arrived LAST
  (least wait — everyone else's wait is caused by it) or never arrived at
  all (a wedged/crashed rank: the other ranks' bounded deadlines fire, and
  the report names the missing rank from their recorded waits alone).
  ``gather_straggler_report`` merges the per-rank tables on every rank
  through the existing ``MetadataExchange`` at run end.

Span durations are host wall-clock only — device time stays with
``MarginalTimer`` (BASELINE.md "Trace methodology r12"): never compare
absolute span times across runs; compare fractions within one trace.
"""

from __future__ import annotations

import json
import logging
import os
import re
import tempfile
import threading
import time
from typing import Iterator, Mapping, NamedTuple

logger = logging.getLogger(__name__)

TRACE_FILE_FORMAT = "trace-{rank:05d}.json"

#: category carried by top-level exchange wait spans (allgather/barrier) —
#: the ONLY spans the straggler wait tables aggregate
EXCHANGE_CAT = "exchange"
#: category for point-to-point KV transport sub-operations (kv_get/kv_set):
#: visible in the timeline, excluded from the wait tables (their parent
#: allgather span already carries the full wait)
EXCHANGE_IO_CAT = "exchange_io"

#: span names aggregated into the per-tag exchange wait tables
_WAIT_SPAN_NAMES = frozenset({"exchange/allgather", "exchange/barrier"})

#: per-thread ring capacity (events); oldest events are overwritten —
#: bounded memory no matter how long a run traces
DEFAULT_CAPACITY = 65536


def _process_index() -> int:
    """Current rank; 0 when jax is absent or uninitialized (single host) —
    the journal's rank rule (telemetry/journal.py)."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


class TraceEvent(NamedTuple):
    name: str
    cat: str
    start: float  # seconds since tracer start (perf_counter delta)
    dur: float  # seconds
    thread_id: int
    thread_name: str
    attrs: dict | None


class _Ring:
    """Fixed-capacity single-writer ring: the owning thread appends with no
    lock (plain list-slot assignment under the GIL); readers snapshot after
    the traced work quiesces."""

    __slots__ = ("items", "n", "cap")

    def __init__(self, capacity: int):
        self.items: list = [None] * capacity
        self.n = 0
        self.cap = capacity

    def append(self, item) -> None:
        self.items[self.n % self.cap] = item
        self.n += 1

    def snapshot(self) -> list:
        if self.n <= self.cap:
            return [e for e in self.items[: self.n]]
        k = self.n % self.cap
        return self.items[k:] + self.items[:k]

    @property
    def dropped(self) -> int:
        return max(0, self.n - self.cap)


class Tracer:
    """Collects spans from every thread of this process into per-thread
    ring buffers. One tracer per process (rank); install it with
    :func:`install_tracer` so the module-level :func:`span` hook feeds it.
    """

    def __init__(self, rank: int | None = None, *,
                 capacity: int = DEFAULT_CAPACITY):
        self.rank = _process_index() if rank is None else int(rank)
        self.capacity = max(16, int(capacity))
        self._t0_perf = time.perf_counter()
        # absolute wall anchor for cross-rank correlation with journal
        # ``ts`` rows (the ONE sanctioned absolute-timestamp read here —
        # dev/lint_parity.py check 11 allowlist; every duration in this
        # module is a perf_counter difference)
        self.wall_t0 = time.time()
        self._local = threading.local()
        self._threads: list[tuple[int, str, _Ring]] = []
        self._lock = threading.Lock()  # buffer registration + export only

    # -- recording (hot path: no locks) --------------------------------------

    def _buffer(self) -> _Ring:
        buf = getattr(self._local, "buf", None)
        if buf is None:
            buf = _Ring(self.capacity)
            self._local.buf = buf
            t = threading.current_thread()
            with self._lock:
                # key by registration index, not thread ident: the OS
                # reuses idents, and two short-lived threads must not
                # merge into one timeline lane
                self._threads.append((len(self._threads), t.name, buf))
        return buf

    def record(self, name: str, cat: str, t_start: float, dur: float,
               attrs: dict | None) -> None:
        """t_start: absolute ``perf_counter`` reading at span entry."""
        self._buffer().append(
            (name, cat, t_start - self._t0_perf, dur, attrs)
        )

    # -- reading --------------------------------------------------------------

    def events(self) -> Iterator[TraceEvent]:
        with self._lock:
            threads = list(self._threads)
        for tid, tname, ring in threads:
            for name, cat, start, dur, attrs in ring.snapshot():
                yield TraceEvent(name, cat, start, dur, tid, tname, attrs)

    def dropped_events(self) -> int:
        with self._lock:
            return sum(ring.dropped for _, _, ring in self._threads)

    # -- Chrome-trace export ---------------------------------------------------

    def chrome_trace(self) -> dict:
        """Catapult/Perfetto JSON object: complete ``"X"`` events with µs
        timestamps, ``pid`` = rank (a span's explicit ``rank=`` attr wins —
        virtual-rank tests separate lanes that way), ``tid`` = a small
        stable per-thread index with ``thread_name`` metadata."""
        from photon_ml_tpu.telemetry.journal import json_safe

        events: list[dict] = []
        pids: set[int] = {self.rank}
        with self._lock:
            threads = list(self._threads)
        for tid, tname, _ in threads:
            events.append({
                "ph": "M", "name": "thread_name", "pid": self.rank,
                "tid": tid, "args": {"name": tname},
            })
        for ev in self.events():
            pid = self.rank
            if ev.attrs and "rank" in ev.attrs:
                pid = int(ev.attrs["rank"])
                pids.add(pid)
            events.append({
                "ph": "X",
                "name": ev.name,
                "cat": ev.cat,
                "ts": ev.start * 1e6,
                "dur": ev.dur * 1e6,
                "pid": pid,
                "tid": ev.thread_id,
                "args": json_safe(ev.attrs or {}),
            })
        meta = [
            {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
             "args": {"name": f"rank {pid}"}}
            for pid in sorted(pids)
        ]
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {
                "rank": self.rank,
                "wall_t0": self.wall_t0,
                "dropped_events": self.dropped_events(),
            },
        }


# ---------------------------------------------------------------------------
# The module-level span hook (inert by default)
# ---------------------------------------------------------------------------


_TRACER: Tracer | None = None


class _NullSpan:
    """Shared do-nothing span: the off path allocates nothing per call
    beyond the keyword dict Python builds for the ``span(...)`` call."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "_name", "_cat", "_attrs", "_t0")

    def __init__(self, tracer: Tracer, name: str, cat: str, attrs: dict):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._attrs = attrs

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        attrs = self._attrs
        if exc_type is not None:
            # the span records even when the traced call raises — an
            # ExchangeTimeout's wait leading up to the deadline is exactly
            # the straggler evidence
            attrs = dict(attrs) if attrs else {}
            attrs["error"] = exc_type.__name__
        self._tracer.record(self._name, self._cat, self._t0, dur,
                            attrs or None)
        return False


def span(name: str, *, cat: str = "span", **attrs):
    """``with span("io/decode_chunk", chunk=3): ...`` — records a complete
    event into the installed tracer; a shared null object when tracing is
    off (the default)."""
    tracer = _TRACER
    if tracer is None:
        return _NULL_SPAN
    return _Span(tracer, name, cat, attrs)


def tracing_active() -> bool:
    return _TRACER is not None


def current_tracer() -> Tracer | None:
    return _TRACER


def install_tracer(tracer: Tracer) -> Tracer:
    """Make ``tracer`` the process-wide span sink. Returns it."""
    global _TRACER
    _TRACER = tracer
    return tracer


def uninstall_tracer() -> Tracer | None:
    """Remove (and return) the installed tracer — callers pair this with
    install in a try/finally so a failed run never leaks tracing into the
    next one."""
    global _TRACER
    tracer, _TRACER = _TRACER, None
    return tracer


# ---------------------------------------------------------------------------
# Straggler attribution
# ---------------------------------------------------------------------------

_DIGITS_RE = re.compile(r"\d+")


def normalize_tag(tag: str) -> str:
    """Aggregation key for exchange tags: digit runs collapse to ``*`` so
    per-step/per-seq tags (``checkpoint_commit/7/ready``) pool into one
    row instead of one row per step."""
    return _DIGITS_RE.sub("*", tag)


def exchange_wait_tables(tracer: Tracer) -> dict[int, dict[str, dict]]:
    """Per-rank per-tag exchange wait totals from this tracer's spans:
    ``{rank: {tag: {"count", "wait_s", "max_s"}}}``. Rank comes from each
    span's ``rank`` attr (the exchange objects stamp it), so one shared
    tracer over virtual in-process ranks separates correctly; a real
    multi-process tracer simply holds its own rank only."""
    tables: dict[int, dict[str, dict]] = {}
    for ev in tracer.events():
        if ev.name not in _WAIT_SPAN_NAMES:
            continue
        attrs = ev.attrs or {}
        rank = int(attrs.get("rank", tracer.rank))
        tag = normalize_tag(str(attrs.get("tag", "")))
        row = tables.setdefault(rank, {}).setdefault(
            tag, {"count": 0, "wait_s": 0.0, "max_s": 0.0}
        )
        row["count"] += 1
        row["wait_s"] += ev.dur
        row["max_s"] = max(row["max_s"], ev.dur)
    return tables


def straggler_report(
    tables: Mapping[int, Mapping[str, dict]],
    *,
    num_ranks: int | None = None,
) -> dict:
    """Merge per-rank wait tables into the diagnostic: for every exchange
    tag, who arrived last?

    The rank with the LEAST total wait arrived last (everyone else's wait
    on that tag is time spent waiting for it); a rank with NO entry for a
    tag the others waited on never arrived at all (crashed/wedged — the
    WithholdingExchange chaos shape), and is named ahead of any wait
    comparison. Single-rank tags are reported with no straggler.
    """
    if num_ranks is None:
        num_ranks = (max(tables) + 1) if tables else 1
    tags: set[str] = set()
    for table in tables.values():
        tags.update(table)
    rows = []
    for tag in sorted(tags):
        waits = []
        counts = []
        for r in range(num_ranks):
            entry = tables.get(r, {}).get(tag)
            waits.append(None if entry is None else entry["wait_s"])
            counts.append(0 if entry is None else entry["count"])
        present = [r for r in range(num_ranks) if waits[r] is not None]
        missing = [r for r in range(num_ranks) if waits[r] is None]
        if missing and present:
            straggler, reason = missing[0], "never_arrived"
        elif len(present) > 1:
            straggler = min(present, key=lambda r: waits[r])
            reason = "least_wait"
        else:
            straggler, reason = None, "single_rank"
        rows.append({
            "tag": tag,
            "wait_s": waits,
            "count": counts,
            "missing_ranks": missing if present else [],
            "straggler_rank": straggler,
            "reason": reason,
        })
    # the tags costing the run the most wait first — the line a human
    # pastes into a slow-run report
    rows.sort(key=lambda r: -sum(w or 0.0 for w in r["wait_s"]))
    return {"num_ranks": num_ranks, "tags": rows}


def gather_straggler_report(tracer: Tracer, exchange) -> dict:
    """Run-end merge through the existing ``MetadataExchange``: every rank
    sends ITS per-tag wait table + ring-drop count (one model-free small
    payload), every rank computes the same merged report (SPMD discipline
    — every rank must call; rank 0 is the one that journals it). The
    per-rank ``dropped_events`` list makes ring-buffer truncation visible
    in the report itself: a rank whose early exchange spans were evicted
    undercounts its waits, and the reader must know."""
    local = exchange_wait_tables(tracer).get(exchange.rank, {})
    gathered = exchange.allgather(
        "trace/straggler_table",
        {"table": local, "dropped": tracer.dropped_events()},
    )
    tables = {r: g["table"] for r, g in enumerate(gathered)}
    report = straggler_report(tables, num_ranks=exchange.num_ranks)
    report["dropped_events"] = [int(g["dropped"]) for g in gathered]
    return report


# ---------------------------------------------------------------------------
# Publication (score-writer directory discipline, journal atomicity)
# ---------------------------------------------------------------------------


def trace_path(directory: str | os.PathLike, rank: int) -> str:
    return os.path.join(str(directory), TRACE_FILE_FORMAT.format(rank=rank))


def publish_trace(tracer: Tracer, directory: str | os.PathLike, *,
                  exchange=None) -> str:
    """Atomically write this rank's ``trace-{rank:05d}.json``.

    Multi-rank (an exchange with num_ranks > 1): rank 0 creates the
    directory, a barrier, then EVERY rank writes its own part file —
    the ``io/score_writer.py`` carve-out to the rank-0-only rule; ranks
    never write each other's files. The barrier rides the exchange's
    bounded deadline: on the failure path (some rank already dead) the
    ``ExchangeTimeout`` is logged and the write proceeds unbarriered
    (``makedirs(exist_ok=True)``) so a crash still publishes a readable
    timeline — trace parts are per-rank files, so the fallback cannot
    collide.
    """
    from photon_ml_tpu.resilience.errors import ExchangeTimeout

    directory = str(directory)
    if exchange is not None and exchange.num_ranks > 1:
        if exchange.rank == 0:
            os.makedirs(directory, exist_ok=True)
        try:
            exchange.barrier("trace/output_dir")
        except ExchangeTimeout as e:
            logger.warning(
                "trace publish barrier timed out (%s); publishing "
                "unbarriered — some rank likely died, its trace part may "
                "be missing", e,
            )
    os.makedirs(directory, exist_ok=True)
    path = trace_path(directory, tracer.rank)
    payload = json.dumps(tracer.chrome_trace())
    fd, staged = tempfile.mkstemp(
        dir=directory, prefix=f".trace-{tracer.rank:05d}-", suffix=".tmp"
    )
    try:
        with os.fdopen(fd, "w") as f:
            f.write(payload)
        os.replace(staged, path)
    except BaseException:
        if os.path.exists(staged):
            os.unlink(staged)
        raise
    return path


def finalize_trace(tracer: Tracer, directory: str | os.PathLike, *,
                   exchange=None, gather: bool = True) -> dict:
    """The drivers' one flush call: publish this rank's trace file, then
    build the straggler report — merged across ranks through the exchange
    on the success path (``gather=True`` with a multi-rank exchange), from
    this tracer's local tables otherwise (single process, or the failure
    path where another collective could hang on the dead rank). On a
    MIXED-outcome run (this rank succeeded, another died before its
    run-end trace collectives) the merge allgather's bounded
    ``ExchangeTimeout`` degrades to the local report — it must never mask
    a successful result. Callers journal the returned report BEFORE
    closing the journal, so spans are flushed to disk first and a crash
    leaves a readable timeline."""
    from photon_ml_tpu.resilience.errors import ExchangeTimeout

    publish_trace(tracer, directory,
                  exchange=exchange if gather else None)
    if gather and exchange is not None and exchange.num_ranks > 1:
        try:
            return gather_straggler_report(tracer, exchange)
        except ExchangeTimeout as e:
            logger.warning(
                "straggler merge timed out (%s); reporting this rank's "
                "local wait tables only", e,
            )
    # local fallback: report over the ranks this tracer actually OBSERVED
    # (all of them for a shared virtual-rank tracer; just this rank on a
    # real multi-process run — never blame unobserved peers as
    # "never_arrived" when their tables simply did not merge). A PARTIAL
    # report is flagged so the reader knows to merge the per-rank trace
    # FILES offline (dev/trace_summary.py) for the full picture.
    tables = exchange_wait_tables(tracer)
    report = straggler_report(tables)
    report["dropped_events"] = [tracer.dropped_events()]
    if exchange is not None and exchange.num_ranks > len(tables):
        # keep report["num_ranks"] == the universe its wait_s lists are
        # indexed by (the observed ranks); the true rank count rides a
        # separate field
        report["partial"] = True
        report["observed_ranks"] = sorted(tables)
        report["expected_num_ranks"] = exchange.num_ranks
    return report


def flush_trace_best_effort(tracer: Tracer, directory: str | os.PathLike, *,
                            exchange=None, gather: bool = True,
                            journal=None) -> dict | None:
    """Driver-teardown wrapper around :func:`finalize_trace` that NEVER
    raises: tracing is observability — a publication error (unwritable
    trace dir, a dead KV coordinator) in a ``finally`` would otherwise
    replace the run's own outcome and skip the journal rows that follow
    (the failure-path journal is the artifact that most needs to
    survive). The swallow is reviewed: every error is logged with its
    traceback (dev/lint_parity.py check 5 allowlist)."""
    try:
        report = finalize_trace(tracer, directory, exchange=exchange,
                                gather=gather)
        if journal is not None:
            journal.record("straggler_report", **report)
        return report
    except Exception:
        logger.exception(
            "trace publication failed; continuing teardown (the run's own "
            "outcome and journal take precedence)"
        )
        return None
