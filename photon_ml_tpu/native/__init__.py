"""Native (C++) runtime components, loaded via ctypes."""

from photon_ml_tpu.native.build import load_offheap_library, native_available

__all__ = ["load_offheap_library", "native_available"]
