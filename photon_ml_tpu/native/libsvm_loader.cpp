// Native LibSVM text parser -> CSR arrays.
//
// TPU-native replacement for the reference's JVM-side LibSVM ingestion
// (photon-client io/deprecated/LibSVMInputDataFormat.scala and the
// dev-scripts/libsvm_text_to_trainingexample_avro.py flow): a single-pass
// C++ tokenizer that turns "label idx:val idx:val ..." lines into
// (labels, row_offsets, col_idx, values) CSR buffers, exported to numpy via
// ctypes (see photon_ml_tpu/io/libsvm_native.py). Label-convention mapping
// (±1 -> {0,1}) stays in Python where the task semantics live.
//
// C API (all exported with C linkage):
//   lsvm_parse(path, zero_based, err, err_cap) -> handle or NULL
//   lsvm_num_rows / lsvm_nnz / lsvm_max_index  (handle) -> int64
//   lsvm_export(handle, labels*, row_offsets*, cols*, vals*)
//   lsvm_free(handle)

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct ParsedFile {
  std::vector<double> labels;
  std::vector<uint64_t> row_offsets;  // size rows+1
  std::vector<uint32_t> cols;
  std::vector<double> vals;
  int64_t max_index = -1;
};

bool is_space(char c) { return c == ' ' || c == '\t' || c == '\r'; }

// Parse one buffer; returns false and fills err on malformed input.
bool parse_buffer(const char* data, size_t size, bool zero_based,
                  ParsedFile* out, std::string* err) {
  const char* p = data;
  const char* end = data + size;
  size_t line_no = 0;
  out->row_offsets.push_back(0);
  while (p < end) {
    ++line_no;
    const char* line_end = static_cast<const char*>(memchr(p, '\n', end - p));
    if (line_end == nullptr) line_end = end;
    const char* q = p;
    while (q < line_end && is_space(*q)) ++q;
    if (q == line_end || *q == '#') {  // blank or comment line
      p = line_end + 1;
      continue;
    }
    // label. No ERANGE check: overflow yields ±inf and underflow a denormal,
    // matching Python float() semantics in the fallback parser.
    char* after = nullptr;
    double label = strtod(q, &after);
    if (after == q) {
      *err = "bad label at line " + std::to_string(line_no);
      return false;
    }
    out->labels.push_back(label);
    q = after;
    // idx:val tokens
    while (q < line_end) {
      while (q < line_end && is_space(*q)) ++q;
      if (q >= line_end || *q == '#') break;
      errno = 0;
      char* colon = nullptr;
      long long idx = strtoll(q, &colon, 10);
      if (colon == q || colon >= line_end || *colon != ':' ||
          errno == ERANGE) {
        *err = "bad feature index at line " + std::to_string(line_no);
        return false;
      }
      const char* vstart = colon + 1;
      // Bound the value parse to this line: strtod skips leading whitespace
      // (including '\n'), so a dangling "idx:" token would otherwise
      // silently consume the NEXT line's label as its value.
      if (vstart >= line_end || is_space(*vstart)) {
        *err = "bad feature value at line " + std::to_string(line_no);
        return false;
      }
      double value = strtod(vstart, &after);
      if (after == vstart) {
        *err = "bad feature value at line " + std::to_string(line_no);
        return false;
      }
      if (!zero_based) idx -= 1;
      if (idx < 0 || idx > UINT32_MAX) {
        *err = "feature index out of range at line " + std::to_string(line_no);
        return false;
      }
      out->cols.push_back(static_cast<uint32_t>(idx));
      out->vals.push_back(value);
      if (idx > out->max_index) out->max_index = idx;
      q = after;
    }
    out->row_offsets.push_back(out->cols.size());
    p = line_end + 1;
  }
  return true;
}

void set_err(char* err, uint64_t err_cap, const std::string& msg) {
  if (err != nullptr && err_cap > 0) {
    size_t n = msg.size() < err_cap - 1 ? msg.size() : err_cap - 1;
    memcpy(err, msg.data(), n);
    err[n] = '\0';
  }
}

}  // namespace

extern "C" {

void* lsvm_parse(const char* path, int zero_based, char* err,
                 uint64_t err_cap) try {
  // No exception may cross this extern "C" boundary: bad_alloc /
  // length_error (directory paths make ftell report LONG_MAX) must become
  // error returns, not std::terminate of the host interpreter.
  FILE* f = fopen(path, "rb");
  if (f == nullptr) {
    set_err(err, err_cap, std::string("cannot open ") + path);
    return nullptr;
  }
  long fsize = -1;
  if (fseek(f, 0, SEEK_END) == 0) fsize = ftell(f);
  if (fsize < 0 || fseek(f, 0, SEEK_SET) != 0) {
    fclose(f);
    set_err(err, err_cap, std::string("cannot stat ") + path);
    return nullptr;
  }
  std::string buf;
  buf.resize(static_cast<size_t>(fsize));
  size_t got = fsize > 0 ? fread(&buf[0], 1, buf.size(), f) : 0;
  fclose(f);
  if (got != buf.size()) {
    set_err(err, err_cap, std::string("short read on ") + path);
    return nullptr;
  }
  auto* parsed = new ParsedFile();
  std::string msg;
  if (!parse_buffer(buf.data(), buf.size(), zero_based != 0, parsed, &msg)) {
    delete parsed;
    set_err(err, err_cap, msg + " in " + path);
    return nullptr;
  }
  return parsed;
} catch (const std::exception& e) {
  set_err(err, err_cap, std::string("parse error: ") + e.what());
  return nullptr;
} catch (...) {
  set_err(err, err_cap, "parse error: unknown exception");
  return nullptr;
}

int64_t lsvm_num_rows(void* h) {
  return static_cast<int64_t>(static_cast<ParsedFile*>(h)->labels.size());
}

int64_t lsvm_nnz(void* h) {
  return static_cast<int64_t>(static_cast<ParsedFile*>(h)->cols.size());
}

int64_t lsvm_max_index(void* h) {
  return static_cast<ParsedFile*>(h)->max_index;
}

void lsvm_export(void* h, double* labels, uint64_t* row_offsets,
                 uint32_t* cols, double* vals) {
  auto* p = static_cast<ParsedFile*>(h);
  memcpy(labels, p->labels.data(), p->labels.size() * sizeof(double));
  memcpy(row_offsets, p->row_offsets.data(),
         p->row_offsets.size() * sizeof(uint64_t));
  memcpy(cols, p->cols.data(), p->cols.size() * sizeof(uint32_t));
  memcpy(vals, p->vals.data(), p->vals.size() * sizeof(double));
}

void lsvm_free(void* h) { delete static_cast<ParsedFile*>(h); }

}  // extern "C"
