// Off-heap immutable feature-index store ("photonix" format).
//
// TPU-native equivalent of the reference's PalDB-backed index maps
// (photon-api index/PalDBIndexMap.scala:26-56): feature-key -> int index
// lookups served from a memory-mapped file instead of process heap, so a
// multi-hundred-million-feature vocabulary costs no Python/JVM memory and
// is shared page-cache-resident across worker processes.
//
// File layout (all integers little-endian uint64):
//   [0]  magic "PHOTONIX"
//   [8]  version (=1)
//   [16] n               number of keys
//   [24] table_size      open-addressing slots (power of two, >= 2n)
//   [32] keys_blob_size  total bytes of concatenated keys
//   [40] offsets         (n+1) * u64   key i = blob[offsets[i], offsets[i+1])
//   [..] table           table_size * u64   slot value = index+1, 0 = empty
//   [..] keys blob
//
// Probing: FNV-1a 64 hash, linear probe, key bytes compared against the
// blob. Build is single-pass; the store is immutable after build (the
// same contract PalDB offers).
//
// C ABI only — consumed from Python via ctypes.

#include <cstdint>
#include <cstring>
#include <cstdio>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr char kMagic[8] = {'P', 'H', 'O', 'T', 'O', 'N', 'I', 'X'};
constexpr uint64_t kVersion = 1;
constexpr uint64_t kHeaderBytes = 40;

uint64_t fnv1a(const char* data, uint64_t len) {
  uint64_t h = 1469598103934665603ULL;
  for (uint64_t i = 0; i < len; ++i) {
    h ^= static_cast<unsigned char>(data[i]);
    h *= 1099511628211ULL;
  }
  return h;
}

uint64_t table_size_for(uint64_t n) {
  uint64_t size = 16;
  while (size < 2 * n) size <<= 1;  // load factor <= 0.5
  return size;
}

struct Store {
  int fd = -1;
  const char* base = nullptr;
  uint64_t bytes = 0;
  uint64_t n = 0;
  uint64_t table_size = 0;
  const uint64_t* offsets = nullptr;  // n + 1
  const uint64_t* table = nullptr;    // table_size
  const char* blob = nullptr;
};

}  // namespace

extern "C" {

void om_close(void* handle);

// Build the store. keys_blob: concatenated key bytes; offsets: n+1 entries.
// Index of key i is i. Returns 0 on success, negative errno-style code.
int64_t om_build(const char* path, const char* keys_blob,
                 const uint64_t* offsets, uint64_t n) {
  const uint64_t blob_size = offsets[n];
  const uint64_t table_size = table_size_for(n);

  std::vector<uint64_t> table(table_size, 0);
  const uint64_t mask = table_size - 1;
  for (uint64_t i = 0; i < n; ++i) {
    const char* key = keys_blob + offsets[i];
    const uint64_t len = offsets[i + 1] - offsets[i];
    uint64_t slot = fnv1a(key, len) & mask;
    for (;;) {
      if (table[slot] == 0) {
        table[slot] = i + 1;
        break;
      }
      // duplicate key check: identical bytes are a build error
      const uint64_t other = table[slot] - 1;
      const uint64_t olen = offsets[other + 1] - offsets[other];
      if (olen == len &&
          std::memcmp(keys_blob + offsets[other], key, len) == 0) {
        return -2;  // duplicate key
      }
      slot = (slot + 1) & mask;
    }
  }

  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  uint64_t header[5];
  std::memcpy(header, kMagic, 8);
  header[1] = kVersion;
  header[2] = n;
  header[3] = table_size;
  header[4] = blob_size;
  bool ok = std::fwrite(header, sizeof(header), 1, f) == 1;
  ok = ok && std::fwrite(offsets, sizeof(uint64_t), n + 1, f) == n + 1;
  ok = ok && std::fwrite(table.data(), sizeof(uint64_t), table_size, f) == table_size;
  ok = ok && (blob_size == 0 ||
              std::fwrite(keys_blob, 1, blob_size, f) == blob_size);
  if (std::fclose(f) != 0) ok = false;
  if (!ok) std::remove(path);  // never leave a truncated store behind
  return ok ? 0 : -1;
}

// Open a store; returns an opaque handle (heap pointer) or null.
void* om_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || static_cast<uint64_t>(st.st_size) < kHeaderBytes) {
    ::close(fd);
    return nullptr;
  }
  void* mapped = mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  if (mapped == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  const char* base = static_cast<const char*>(mapped);
  const uint64_t* header = reinterpret_cast<const uint64_t*>(base);
  const uint64_t file_size = static_cast<uint64_t>(st.st_size);
  bool valid = std::memcmp(base, kMagic, 8) == 0 && header[1] == kVersion;
  if (valid) {
    const uint64_t n = header[2];
    const uint64_t table_size = header[3];
    const uint64_t blob_size = header[4];
    // reject corrupt/truncated stores: sizes must be internally consistent
    // with the mapped length, table_size a power of two able to hold n
    valid = table_size != 0 && (table_size & (table_size - 1)) == 0 &&
            n <= table_size &&
            n < (UINT64_MAX - 1) / 8 &&
            file_size >= kHeaderBytes + 8 * (n + 1) + 8 * table_size &&
            file_size - (kHeaderBytes + 8 * (n + 1) + 8 * table_size) >=
                blob_size;
  }
  if (!valid) {
    munmap(mapped, st.st_size);
    ::close(fd);
    return nullptr;
  }
  auto* s = new Store();
  s->fd = fd;
  s->base = base;
  s->bytes = st.st_size;
  s->n = header[2];
  s->table_size = header[3];
  s->offsets = reinterpret_cast<const uint64_t*>(base + kHeaderBytes);
  s->table = s->offsets + (s->n + 1);
  s->blob = reinterpret_cast<const char*>(s->table + s->table_size);
  // one pass over the offsets: monotone and bounded by the blob keeps every
  // later key comparison in-bounds
  const uint64_t blob_size = header[4];
  for (uint64_t i = 0; i < s->n; ++i) {
    if (s->offsets[i] > s->offsets[i + 1] || s->offsets[i + 1] > blob_size) {
      om_close(s);
      return nullptr;
    }
  }
  return s;
}

void om_close(void* handle) {
  if (!handle) return;
  auto* s = static_cast<Store*>(handle);
  munmap(const_cast<char*>(s->base), s->bytes);
  ::close(s->fd);
  delete s;
}

int64_t om_size(void* handle) {
  return handle ? static_cast<int64_t>(static_cast<Store*>(handle)->n) : -1;
}

// Look up a key; returns its index or -1.
int64_t om_get(void* handle, const char* key, uint64_t len) {
  const auto* s = static_cast<Store*>(handle);
  const uint64_t mask = s->table_size - 1;
  uint64_t slot = fnv1a(key, len) & mask;
  for (;;) {
    const uint64_t entry = s->table[slot];
    if (entry == 0) return -1;
    const uint64_t idx = entry - 1;
    const uint64_t klen = s->offsets[idx + 1] - s->offsets[idx];
    if (klen == len &&
        std::memcmp(s->blob + s->offsets[idx], key, len) == 0) {
      return static_cast<int64_t>(idx);
    }
    slot = (slot + 1) & mask;
  }
}

// Reverse lookup: copy key bytes of `index` into buf (if it fits);
// returns the key length, or -1 for a bad index.
int64_t om_key_at(void* handle, uint64_t index, char* buf, uint64_t buflen) {
  const auto* s = static_cast<Store*>(handle);
  if (index >= s->n) return -1;
  const uint64_t len = s->offsets[index + 1] - s->offsets[index];
  if (len <= buflen) {
    std::memcpy(buf, s->blob + s->offsets[index], len);
  }
  return static_cast<int64_t>(len);
}

}  // extern "C"
