// Columnar Avro container decoder — the native ingestion fast path.
//
// Replaces the per-record Python decode of io/avro.py for the training-data
// hot path (the reference's ingestion is JVM-compiled Avro + Spark;
// photon-client data/avro/AvroDataReader.scala): one pass over each
// container block executing a PLAN compiled from the schema by
// io/avro_native.py, emitting columns:
//   numeric fields  -> double columns (NaN for null branches)
//   string fields   -> interned id columns + a string table
//   feature bags    -> (row, key_id, value) triples + an interned
//                      "name\x01term" key table
//   string maps     -> (row, key_id, value_id) triples + two tables
// Strings are interned HERE so Python never materializes per-entry
// strings — only the (small) unique tables cross the boundary.
//
// The plan is a prefix-serialized op tree (see io/avro_native.py for the
// compiler and the Python-side contract). Unsupported schema shapes never
// reach this file: the compiler refuses and callers fall back to the
// pure-Python reader.

#include <algorithm>
#include <cstdint>
#include <limits>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include <zlib.h>

namespace {

enum Op : int64_t {
  OP_RECORD = 1,
  OP_UNION = 2,
  OP_ARRAY = 3,
  OP_MAP = 4,
  OP_NULL = 5,
  OP_BOOL = 6,
  OP_INT = 7,
  OP_LONG = 8,
  OP_FLOAT = 9,
  OP_DOUBLE = 10,
  OP_STRING = 11,
  OP_BYTES = 12,
  OP_FIXED = 13,  // [op, size]
  OP_COL_DOUBLE = 20,  // [op, slot]
  OP_COL_FLOAT = 21,
  OP_COL_INT = 22,
  OP_COL_LONG = 23,
  OP_COL_BOOL = 24,
  OP_COL_NULLNUM = 25,
  OP_COL_STR = 26,
  OP_COL_NULLSTR = 27,
  OP_MAP_COLLECT = 28,  // [op, slot, value_child]
  OP_MAPVAL_STR = 29,
  OP_MAPVAL_NULL = 30,
  OP_BAG = 31,  // [op, slot, item_child]
  OP_BAG_NAME = 32,
  OP_BAG_TERM = 33,
  OP_BAG_TERM_NULL = 34,
  OP_BAG_VALUE = 35,  // [op, kind] kind: 0=double 1=float 2=int/long 3=bool
  OP_COL_STRNUM = 36,   // [op, slot] string parsed as double (NaN if not)
  OP_COL_LONGSTR = 37,  // [op, slot] long rendered as decimal -> strcol
  OP_COL_BOOLSTR = 38,  // [op, slot] bool -> "True"/"False" -> strcol
  OP_MAPVAL_LONGSTR = 39,
  OP_MAPVAL_BOOLSTR = 40,
  OP_MAPVAL_BAD = 41,  // runtime value we cannot render faithfully
};

constexpr uint32_t NULL_ID = 0xFFFFFFFFu;

struct Pool {
  std::unordered_map<std::string, uint32_t> ids;
  std::string blob;
  std::vector<uint64_t> offsets{0};

  uint32_t intern(const char* s, size_t len) {
    std::string key(s, len);
    auto it = ids.find(key);
    if (it != ids.end()) return it->second;
    uint32_t id = static_cast<uint32_t>(ids.size());
    ids.emplace(std::move(key), id);
    blob.append(s, len);
    offsets.push_back(blob.size());
    return id;
  }
};

struct BagOut {
  std::vector<uint32_t> rows;
  std::vector<uint32_t> keys;
  std::vector<double> vals;
  Pool pool;
};

struct MapOut {
  std::vector<uint32_t> rows;
  std::vector<uint32_t> keys;
  std::vector<uint32_t> valids;
  Pool kpool;
  Pool vpool;
};

struct Decoder {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;

  int64_t read_long() {
    uint64_t acc = 0;
    int shift = 0;
    while (p < end) {
      uint8_t b = *p++;
      acc |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) {
        return static_cast<int64_t>(acc >> 1) ^ -static_cast<int64_t>(acc & 1);
      }
      shift += 7;
      if (shift > 63) break;
    }
    fail = true;
    return 0;
  }
  double read_double() {
    if (end - p < 8) { fail = true; return 0.0; }
    double v;
    std::memcpy(&v, p, 8);
    p += 8;
    return v;
  }
  float read_float() {
    if (end - p < 4) { fail = true; return 0.0f; }
    float v;
    std::memcpy(&v, p, 4);
    p += 4;
    return v;
  }
  const char* read_bytes(int64_t* len) {
    *len = read_long();
    if (fail || *len < 0 || end - p < *len) { fail = true; return nullptr; }
    const char* s = reinterpret_cast<const char*>(p);
    p += *len;
    return s;
  }
  void skip(int64_t n) {
    if (end - p < n) { fail = true; return; }
    p += n;
  }
};

struct State {
  std::vector<std::vector<double>> numcols;
  std::vector<std::vector<uint8_t>> numnulls;  // 1 where the null branch fired
  std::vector<std::vector<uint32_t>> strcols;
  std::vector<Pool> strpools;
  std::vector<BagOut> bags;
  std::vector<MapOut> maps;
  uint32_t row = 0;
  // per-item bag registers (key carries "name\x01term" via the splice
  // logic in OP_BAG_NAME / OP_BAG_TERM)
  std::string bag_key;
  double bag_value = 0.0;
};

// Advance `i` past the node at plan[i] without executing (plan traversal).
void plan_skip(const int64_t* plan, size_t& i) {
  int64_t op = plan[i++];
  switch (op) {
    case OP_RECORD: case OP_UNION: {
      int64_t n = plan[i++];
      for (int64_t k = 0; k < n; ++k) plan_skip(plan, i);
      break;
    }
    case OP_ARRAY: case OP_MAP: case OP_MAP_COLLECT: case OP_BAG:
      if (op == OP_MAP_COLLECT || op == OP_BAG) i++;  // slot
      plan_skip(plan, i);
      break;
    case OP_FIXED: case OP_COL_DOUBLE: case OP_COL_FLOAT: case OP_COL_INT:
    case OP_COL_LONG: case OP_COL_BOOL: case OP_COL_NULLNUM:
    case OP_COL_STR: case OP_COL_NULLSTR: case OP_BAG_VALUE:
    case OP_COL_STRNUM: case OP_COL_LONGSTR: case OP_COL_BOOLSTR:
      i++;  // one param
      break;
    default:
      break;  // leaf with no params
  }
}

struct Exec {
  Decoder& d;
  State& st;
  const int64_t* plan;
  bool bad_plan = false;

  void run(size_t& i) {
    int64_t op = plan[i++];
    switch (op) {
      case OP_RECORD: {
        int64_t n = plan[i++];
        for (int64_t k = 0; k < n && !d.fail; ++k) run(i);
        break;
      }
      case OP_UNION: {
        int64_t n = plan[i++];
        int64_t branch = d.read_long();
        if (branch < 0 || branch >= n) { d.fail = true; branch = 0; }
        for (int64_t k = 0; k < n; ++k) {
          if (k == branch && !d.fail) run(i); else plan_skip(plan, i);
        }
        break;
      }
      case OP_ARRAY: {
        size_t child = i;
        plan_skip(plan, i);
        for (;;) {
          int64_t count = d.read_long();
          if (d.fail || count == 0) break;
          if (count < 0) { d.read_long(); count = -count; }  // block size
          for (int64_t k = 0; k < count && !d.fail; ++k) {
            size_t c = child;
            run(c);
          }
        }
        break;
      }
      case OP_MAP: {
        size_t child = i;
        plan_skip(plan, i);
        for (;;) {
          int64_t count = d.read_long();
          if (d.fail || count == 0) break;
          if (count < 0) { d.read_long(); count = -count; }
          for (int64_t k = 0; k < count && !d.fail; ++k) {
            int64_t len;
            d.read_bytes(&len);  // key
            size_t c = child;
            run(c);
          }
        }
        break;
      }
      case OP_NULL: break;
      case OP_BOOL: d.skip(1); break;
      case OP_INT: case OP_LONG: d.read_long(); break;
      case OP_FLOAT: d.skip(4); break;
      case OP_DOUBLE: d.skip(8); break;
      case OP_STRING: case OP_BYTES: {
        int64_t len;
        d.read_bytes(&len);
        break;
      }
      case OP_FIXED: d.skip(plan[i++]); break;
      case OP_COL_DOUBLE: {
        int64_t slot = plan[i++];
        st.numcols[slot].push_back(d.read_double());
        st.numnulls[slot].push_back(0);
        break;
      }
      case OP_COL_FLOAT: {
        int64_t slot = plan[i++];
        st.numcols[slot].push_back(d.read_float());
        st.numnulls[slot].push_back(0);
        break;
      }
      case OP_COL_INT: case OP_COL_LONG: {
        int64_t slot = plan[i++];
        st.numcols[slot].push_back(static_cast<double>(d.read_long()));
        st.numnulls[slot].push_back(0);
        break;
      }
      case OP_COL_BOOL: {
        double v = (d.p < d.end && *d.p) ? 1.0 : 0.0;
        d.skip(1);
        int64_t slot = plan[i++];
        st.numcols[slot].push_back(v);
        st.numnulls[slot].push_back(0);
        break;
      }
      case OP_COL_NULLNUM: {
        int64_t slot = plan[i++];
        st.numcols[slot].push_back(
            std::numeric_limits<double>::quiet_NaN());
        st.numnulls[slot].push_back(1);
        break;
      }
      case OP_COL_STR: {
        int64_t len;
        const char* s = d.read_bytes(&len);
        int64_t slot = plan[i++];
        if (!d.fail) st.strcols[slot].push_back(st.strpools[slot].intern(s, len));
        break;
      }
      case OP_COL_NULLSTR: st.strcols[plan[i++]].push_back(NULL_ID); break;
      case OP_COL_STRNUM: {
        int64_t len;
        const char* sp = d.read_bytes(&len);
        int64_t slot = plan[i++];
        if (!d.fail) {
          std::string tmp(sp, len);
          char* endp = nullptr;
          double v = std::strtod(tmp.c_str(), &endp);
          if (endp != tmp.c_str() + tmp.size() || tmp.empty())
            v = std::numeric_limits<double>::quiet_NaN();
          st.numcols[slot].push_back(v);
          st.numnulls[slot].push_back(0);
        }
        break;
      }
      case OP_COL_LONGSTR: {
        int64_t v = d.read_long();
        int64_t slot = plan[i++];
        if (!d.fail) {
          char buf[24];
          int blen = snprintf(buf, sizeof buf, "%lld",
                              static_cast<long long>(v));
          st.strcols[slot].push_back(st.strpools[slot].intern(buf, blen));
        }
        break;
      }
      case OP_COL_BOOLSTR: {
        bool v = (d.p < d.end && *d.p);
        d.skip(1);
        int64_t slot = plan[i++];
        if (!d.fail)
          st.strcols[slot].push_back(
              v ? st.strpools[slot].intern("True", 4)
                : st.strpools[slot].intern("False", 5));
        break;
      }
      case OP_MAP_COLLECT: {
        int64_t slot = plan[i++];
        size_t child = i;
        plan_skip(plan, i);
        MapOut& m = st.maps[slot];
        for (;;) {
          int64_t count = d.read_long();
          if (d.fail || count == 0) break;
          if (count < 0) { d.read_long(); count = -count; }
          for (int64_t k = 0; k < count && !d.fail; ++k) {
            int64_t klen;
            const char* ks = d.read_bytes(&klen);
            if (d.fail) break;
            uint32_t kid = m.kpool.intern(ks, klen);
            // value child: OP_MAPVAL_STR or a union over {STR, NULL}
            size_t c = child;
            uint32_t vid = run_mapval(c, m);
            if (d.fail) break;
            m.rows.push_back(st.row);
            m.keys.push_back(kid);
            m.valids.push_back(vid);
          }
        }
        break;
      }
      case OP_BAG: {
        int64_t slot = plan[i++];
        size_t child = i;
        plan_skip(plan, i);
        BagOut& b = st.bags[slot];
        for (;;) {
          int64_t count = d.read_long();
          if (d.fail || count == 0) break;
          if (count < 0) { d.read_long(); count = -count; }
          for (int64_t k = 0; k < count && !d.fail; ++k) {
            st.bag_key.clear();
            st.bag_value = 0.0;
            size_t c = child;
            run(c);
            if (d.fail) break;
            // key = name \x01 term (term absent/null -> empty)
            uint32_t kid = b.pool.intern(st.bag_key.data(), st.bag_key.size());
            b.rows.push_back(st.row);
            b.keys.push_back(kid);
            b.vals.push_back(st.bag_value);
          }
        }
        break;
      }
      case OP_BAG_NAME: {
        int64_t len;
        const char* s = d.read_bytes(&len);
        if (!d.fail) {
          // name goes first; term appended after the separator later
          std::string tail;
          size_t sep = st.bag_key.find('\x01');
          if (sep != std::string::npos) tail = st.bag_key.substr(sep);
          st.bag_key.assign(s, len);
          st.bag_key += tail.empty() ? std::string(1, '\x01') : tail;
        }
        break;
      }
      case OP_BAG_TERM: {
        int64_t len;
        const char* s = d.read_bytes(&len);
        if (!d.fail) {
          size_t sep = st.bag_key.find('\x01');
          if (sep == std::string::npos) {
            st.bag_key += '\x01';
            sep = st.bag_key.size() - 1;
          }
          st.bag_key.resize(sep + 1);
          st.bag_key.append(s, len);
        }
        break;
      }
      case OP_BAG_TERM_NULL:
        if (st.bag_key.find('\x01') == std::string::npos) st.bag_key += '\x01';
        break;
      case OP_BAG_VALUE: {
        int64_t kind = plan[i++];
        switch (kind) {
          case 0: st.bag_value = d.read_double(); break;
          case 1: st.bag_value = d.read_float(); break;
          case 2: st.bag_value = static_cast<double>(d.read_long()); break;
          case 3: {
            st.bag_value = (d.p < d.end && *d.p) ? 1.0 : 0.0;
            d.skip(1);
            break;
          }
          default: bad_plan = true;
        }
        break;
      }
      default:
        bad_plan = true;
        d.fail = true;
    }
  }

  uint32_t run_mapval(size_t& i, MapOut& m) {
    int64_t op = plan[i++];
    if (op == OP_MAPVAL_STR) {
      int64_t len;
      const char* s = d.read_bytes(&len);
      if (d.fail) return NULL_ID;
      return m.vpool.intern(s, len);
    }
    if (op == OP_MAPVAL_LONGSTR) {
      int64_t v = d.read_long();
      if (d.fail) return NULL_ID;
      char buf[24];
      int blen = snprintf(buf, sizeof buf, "%lld", static_cast<long long>(v));
      return m.vpool.intern(buf, blen);
    }
    if (op == OP_MAPVAL_BOOLSTR) {
      bool v = (d.p < d.end && *d.p);
      d.skip(1);
      if (d.fail) return NULL_ID;
      return v ? m.vpool.intern("True", 4) : m.vpool.intern("False", 5);
    }
    if (op == OP_MAPVAL_BAD) {
      // a runtime value (e.g. a double map entry) that Python's str() and
      // we cannot render identically — force the caller's fallback
      bad_plan = true;
      d.fail = true;
      return NULL_ID;
    }
    if (op == OP_UNION) {
      int64_t n = plan[i++];
      int64_t branch = d.read_long();
      if (branch < 0 || branch >= n) { d.fail = true; return NULL_ID; }
      uint32_t out = NULL_ID;
      for (int64_t k = 0; k < n; ++k) {
        if (k == branch) {
          int64_t sub = plan[i];
          if (sub == OP_MAPVAL_NULL) {
            i++;
          } else {
            out = run_mapval(i, m);
          }
        } else {
          size_t j = i;
          // mapval nodes are leaves
          i = j + 1;
        }
      }
      return out;
    }
    if (op == OP_MAPVAL_NULL) return NULL_ID;
    bad_plan = true;
    d.fail = true;
    return NULL_ID;
  }
};

struct Handle {
  State st;
  int64_t n_records = 0;
  // stable views for ctypes accessors
  std::vector<std::vector<uint64_t>> bag_offs;
  std::vector<std::vector<uint64_t>> str_offs;
  std::vector<std::vector<uint64_t>> mapk_offs;
  std::vector<std::vector<uint64_t>> mapv_offs;
};

// zigzag varint straight off the FILE stream (header + block framing; the
// in-block decoder has its own pointer-based reader)
int64_t file_varint(FILE* f, bool* ok) {
  uint64_t acc = 0;
  int shift = 0;
  int c;
  while ((c = std::fgetc(f)) != EOF) {
    acc |= static_cast<uint64_t>(c & 0x7F) << shift;
    if (!(c & 0x80))
      return static_cast<int64_t>(acc >> 1) ^ -static_cast<int64_t>(acc & 1);
    shift += 7;
    if (shift > 63) break;  // malformed varint; shifting past 64 is UB
  }
  *ok = false;
  return 0;
}

bool read_header(FILE* f, std::string* codec, uint8_t sync[16], char* err,
                 size_t errlen) {
  uint8_t magic[4];
  if (std::fread(magic, 1, 4, f) != 4 || std::memcmp(magic, "Obj\x01", 4)) {
    snprintf(err, errlen, "not an Avro container file");
    return false;
  }
  // metadata map: string -> bytes
  auto rl = [&](bool* ok2) { return file_varint(f, ok2); };
  bool ok = true;
  *codec = "null";
  for (;;) {
    int64_t count = rl(&ok);
    if (!ok) { snprintf(err, errlen, "truncated header"); return false; }
    if (count == 0) break;
    if (count < 0) { rl(&ok); count = -count; }
    for (int64_t k = 0; k < count; ++k) {
      int64_t klen = rl(&ok);
      std::string key(klen > 0 ? klen : 0, '\0');
      if (klen > 0 && std::fread(&key[0], 1, klen, f) != (size_t)klen) ok = false;
      int64_t vlen = rl(&ok);
      std::string val(vlen > 0 ? vlen : 0, '\0');
      if (vlen > 0 && std::fread(&val[0], 1, vlen, f) != (size_t)vlen) ok = false;
      if (!ok) { snprintf(err, errlen, "truncated header"); return false; }
      if (key == "avro.codec") *codec = val;
    }
  }
  if (std::fread(sync, 1, 16, f) != 16) {
    snprintf(err, errlen, "truncated sync marker");
    return false;
  }
  return true;
}

}  // namespace

extern "C" {

void* avdec_open(const char* path, const int64_t* plan, int64_t planlen,
                 int64_t n_num, int64_t n_str, int64_t n_bag, int64_t n_map,
                 char* err, uint64_t errlen) {
  FILE* f = std::fopen(path, "rb");
  if (!f) {
    snprintf(err, errlen, "cannot open %s", path);
    return nullptr;
  }
  std::string codec;
  uint8_t sync[16];
  if (!read_header(f, &codec, sync, err, errlen)) {
    std::fclose(f);
    return nullptr;
  }
  if (codec != "null" && codec != "deflate") {
    snprintf(err, errlen, "unsupported codec %s", codec.c_str());
    std::fclose(f);
    return nullptr;
  }
  auto* h = new Handle();
  h->st.numcols.resize(n_num);
  h->st.numnulls.resize(n_num);
  h->st.strcols.resize(n_str);
  h->st.strpools.resize(n_str);
  h->st.bags.resize(n_bag);
  h->st.maps.resize(n_map);

  std::vector<uint8_t> raw, inflated;
  auto fail = [&](const char* msg) -> void* {
    snprintf(err, errlen, "%s", msg);
    std::fclose(f);
    delete h;
    return nullptr;
  };
  for (;;) {
    int c = std::fgetc(f);
    if (c == EOF) break;
    std::ungetc(c, f);
    bool ok = true;
    int64_t count = file_varint(f, &ok);
    int64_t size = file_varint(f, &ok);
    if (!ok || size < 0) return fail("truncated block header");
    raw.resize(size);
    if (size > 0 && std::fread(raw.data(), 1, size, f) != (size_t)size)
      return fail("truncated block");
    const uint8_t* data = raw.data();
    size_t datalen = raw.size();
    if (codec == "deflate") {
      inflated.clear();
      inflated.resize(std::max<size_t>(datalen * 4, 1 << 16));
      z_stream zs{};
      if (inflateInit2(&zs, -15) != Z_OK) return fail("zlib init failed");
      zs.next_in = const_cast<Bytef*>(raw.data());
      zs.avail_in = raw.size();
      size_t out = 0;
      int zr = Z_OK;
      for (;;) {
        zs.next_out = inflated.data() + out;
        zs.avail_out = inflated.size() - out;
        zr = inflate(&zs, Z_NO_FLUSH);
        out = inflated.size() - zs.avail_out;
        if (zr == Z_STREAM_END) break;
        if (zr != Z_OK) { inflateEnd(&zs); return fail("deflate error"); }
        if (zs.avail_out == 0) inflated.resize(inflated.size() * 2);
      }
      inflateEnd(&zs);
      inflated.resize(out);
      data = inflated.data();
      datalen = out;
    }
    Decoder d{data, data + datalen};
    Exec ex{d, h->st, plan};
    for (int64_t k = 0; k < count; ++k) {
      size_t i = 0;
      ex.run(i);
      if (d.fail || ex.bad_plan)
        return fail(ex.bad_plan ? "bad plan" : "record decode error");
      h->st.row++;
      h->n_records++;
    }
    if (d.p != d.end) return fail("trailing bytes in block");
    uint8_t s2[16];
    if (std::fread(s2, 1, 16, f) != 16 || std::memcmp(s2, sync, 16))
      return fail("sync marker mismatch");
  }
  std::fclose(f);
  // freeze offset views
  for (auto& p : h->st.strpools) h->str_offs.push_back(p.offsets);
  for (auto& b : h->st.bags) h->bag_offs.push_back(b.pool.offsets);
  for (auto& m : h->st.maps) {
    h->mapk_offs.push_back(m.kpool.offsets);
    h->mapv_offs.push_back(m.vpool.offsets);
  }
  return h;
}

int64_t avdec_num_records(void* hv) {
  return static_cast<Handle*>(hv)->n_records;
}

int64_t avdec_numcol(void* hv, int64_t slot, const double** data,
                     const uint8_t** nulls) {
  auto* h = static_cast<Handle*>(hv);
  auto& c = h->st.numcols[slot];
  *data = c.data();
  *nulls = h->st.numnulls[slot].data();
  return static_cast<int64_t>(c.size());
}

int64_t avdec_strcol(void* hv, int64_t slot, const uint32_t** ids,
                     const char** blob, const uint64_t** offs,
                     uint64_t* table_n) {
  auto* h = static_cast<Handle*>(hv);
  auto& c = h->st.strcols[slot];
  *ids = c.data();
  *blob = h->st.strpools[slot].blob.data();
  *offs = h->str_offs[slot].data();
  *table_n = h->st.strpools[slot].ids.size();
  return static_cast<int64_t>(c.size());
}

int64_t avdec_bag(void* hv, int64_t slot, const uint32_t** rows,
                  const uint32_t** keys, const double** vals,
                  const char** blob, const uint64_t** offs,
                  uint64_t* table_n) {
  auto* h = static_cast<Handle*>(hv);
  auto& b = h->st.bags[slot];
  *rows = b.rows.data();
  *keys = b.keys.data();
  *vals = b.vals.data();
  *blob = b.pool.blob.data();
  *offs = h->bag_offs[slot].data();
  *table_n = b.pool.ids.size();
  return static_cast<int64_t>(b.rows.size());
}

int64_t avdec_map(void* hv, int64_t slot, const uint32_t** rows,
                  const uint32_t** keys, const uint32_t** valids,
                  const char** kblob, const uint64_t** koffs, uint64_t* kn,
                  const char** vblob, const uint64_t** voffs, uint64_t* vn) {
  auto* h = static_cast<Handle*>(hv);
  auto& m = h->st.maps[slot];
  *rows = m.rows.data();
  *keys = m.keys.data();
  *valids = m.valids.data();
  *kblob = m.kpool.blob.data();
  *koffs = h->mapk_offs[slot].data();
  *kn = m.kpool.ids.size();
  *vblob = m.vpool.blob.data();
  *voffs = h->mapv_offs[slot].data();
  *vn = m.vpool.ids.size();
  return static_cast<int64_t>(m.rows.size());
}

void avdec_free(void* hv) { delete static_cast<Handle*>(hv); }

}  // extern "C"
