"""Compile-on-demand loader for the native C++ libraries.

No reference analogue: the reference shipped JVM bytecode and leaned on
PalDB/off-heap JNI jars; this build's native components compile from
vendored C++ at first use instead.

Each .so is built once from its .cpp with the system g++ and cached next to
the source (rebuilt when the source changes, keyed by mtime+size).
Everything degrades gracefully: the ``*_available()`` probes return False
when no compiler exists, and callers fall back to pure-Python paths.
"""

from __future__ import annotations

import ctypes
import logging
import os
import shutil
import subprocess
import tempfile
import threading
from typing import Callable

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(__file__)
_LOCK = threading.Lock()
_LIBS: dict[str, ctypes.CDLL] = {}
_FAILED: set[str] = set()


def _lib_path(source: str) -> str:
    src_stat = os.stat(source)
    tag = f"{src_stat.st_mtime_ns}-{src_stat.st_size}"
    stem = os.path.splitext(os.path.basename(source))[0]
    return os.path.join(_DIR, f"_{stem}-{tag}.so")


#: per-source extra link flags (only the Avro decoder needs zlib; coupling
#: every native build to libz would let a missing dev link silently degrade
#: the others to their Python fallbacks)
_LINK_FLAGS = {"avro_decoder.cpp": ["-lz"]}


def _compile(source: str, out_path: str) -> None:
    gxx = shutil.which("g++") or shutil.which("c++")
    if gxx is None:
        raise RuntimeError("no C++ compiler found")
    # build into a temp file then atomically rename (concurrent test workers)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(out_path))
    os.close(fd)
    try:
        subprocess.run(
            [gxx, "-O2", "-std=c++17", "-shared", "-fPIC", source, "-o", tmp]
            + _LINK_FLAGS.get(os.path.basename(source), []),
            check=True,
            capture_output=True,
            text=True,
        )
        os.replace(tmp, out_path)
    except subprocess.CalledProcessError as e:
        os.unlink(tmp)
        raise RuntimeError(f"g++ failed: {e.stderr}") from e
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_native_library(
    source_basename: str, configure: Callable[[ctypes.CDLL], None]
) -> ctypes.CDLL:
    """Load (compiling if needed) a native library; raises on failure.

    ``configure`` sets restype/argtypes on the freshly loaded CDLL; it runs
    once per process per library.
    """
    source = os.path.join(_DIR, source_basename)
    with _LOCK:
        if source_basename in _LIBS:
            return _LIBS[source_basename]
        if source_basename in _FAILED:
            raise RuntimeError(
                f"native library {source_basename} previously failed to load"
            )
        try:
            path = _lib_path(source)
            if not os.path.exists(path):
                logger.info("compiling native library %s", source_basename)
                _compile(source, path)
            lib = ctypes.CDLL(path)
            configure(lib)
            _LIBS[source_basename] = lib
            return lib
        except Exception:
            _FAILED.add(source_basename)
            raise


def _configure_offheap(lib: ctypes.CDLL) -> None:
    lib.om_build.restype = ctypes.c_int64
    lib.om_build.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64,
    ]
    lib.om_open.restype = ctypes.c_void_p
    lib.om_open.argtypes = [ctypes.c_char_p]
    lib.om_close.restype = None
    lib.om_close.argtypes = [ctypes.c_void_p]
    lib.om_size.restype = ctypes.c_int64
    lib.om_size.argtypes = [ctypes.c_void_p]
    lib.om_get.restype = ctypes.c_int64
    lib.om_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
    lib.om_key_at.restype = ctypes.c_int64
    lib.om_key_at.argtypes = [
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.c_char_p,
        ctypes.c_uint64,
    ]


def load_offheap_library() -> ctypes.CDLL:
    return load_native_library("offheap_store.cpp", _configure_offheap)


def native_available() -> bool:
    try:
        load_offheap_library()
        return True
    except Exception:
        return False


def _configure_libsvm(lib: ctypes.CDLL) -> None:
    lib.lsvm_parse.restype = ctypes.c_void_p
    lib.lsvm_parse.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.c_char_p,
        ctypes.c_uint64,
    ]
    for fn in (lib.lsvm_num_rows, lib.lsvm_nnz, lib.lsvm_max_index):
        fn.restype = ctypes.c_int64
        fn.argtypes = [ctypes.c_void_p]
    lib.lsvm_export.restype = None
    lib.lsvm_export.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_double),
    ]
    lib.lsvm_free.restype = None
    lib.lsvm_free.argtypes = [ctypes.c_void_p]


def load_libsvm_library() -> ctypes.CDLL:
    return load_native_library("libsvm_loader.cpp", _configure_libsvm)


def libsvm_native_available() -> bool:
    try:
        load_libsvm_library()
        return True
    except Exception:
        return False


def _configure_avro(lib: ctypes.CDLL) -> None:
    lib.avdec_open.restype = ctypes.c_void_p
    lib.avdec_open.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_char_p,
        ctypes.c_uint64,
    ]
    lib.avdec_num_records.restype = ctypes.c_int64
    lib.avdec_num_records.argtypes = [ctypes.c_void_p]
    u32p = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint32))
    f64p = ctypes.POINTER(ctypes.POINTER(ctypes.c_double))
    chp = ctypes.POINTER(ctypes.c_char_p)
    u64pp = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64))
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.avdec_numcol.restype = ctypes.c_int64
    lib.avdec_numcol.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, f64p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
    ]
    lib.avdec_strcol.restype = ctypes.c_int64
    lib.avdec_strcol.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, u32p, chp, u64pp, u64p,
    ]
    lib.avdec_bag.restype = ctypes.c_int64
    lib.avdec_bag.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, u32p, u32p, f64p, chp, u64pp, u64p,
    ]
    lib.avdec_map.restype = ctypes.c_int64
    lib.avdec_map.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, u32p, u32p, u32p,
        chp, u64pp, u64p, chp, u64pp, u64p,
    ]
    lib.avdec_free.restype = None
    lib.avdec_free.argtypes = [ctypes.c_void_p]


def load_avro_library() -> ctypes.CDLL:
    return load_native_library("avro_decoder.cpp", _configure_avro)


def avro_native_available() -> bool:
    try:
        load_avro_library()
        return True
    except Exception:
        return False
