"""Compile-on-demand loader for the native off-heap store library.

The .so is built once from offheap_store.cpp with the system g++ and cached
next to the source (rebuilt when the source changes, keyed by mtime+size).
Everything degrades gracefully: ``native_available()`` is False when no
compiler exists, and callers fall back to the pure-Python reader.
"""

from __future__ import annotations

import ctypes
import logging
import os
import shutil
import subprocess
import tempfile
import threading

logger = logging.getLogger(__name__)

_SOURCE = os.path.join(os.path.dirname(__file__), "offheap_store.cpp")
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_LOAD_FAILED = False


def _lib_path() -> str:
    src_stat = os.stat(_SOURCE)
    tag = f"{src_stat.st_mtime_ns}-{src_stat.st_size}"
    return os.path.join(
        os.path.dirname(_SOURCE), f"_offheap_store-{tag}.so"
    )


def _compile(out_path: str) -> None:
    gxx = shutil.which("g++") or shutil.which("c++")
    if gxx is None:
        raise RuntimeError("no C++ compiler found")
    # build into a temp file then atomically rename (concurrent test workers)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(out_path))
    os.close(fd)
    try:
        subprocess.run(
            [gxx, "-O2", "-std=c++17", "-shared", "-fPIC", _SOURCE, "-o", tmp],
            check=True,
            capture_output=True,
            text=True,
        )
        os.replace(tmp, out_path)
    except subprocess.CalledProcessError as e:
        os.unlink(tmp)
        raise RuntimeError(f"g++ failed: {e.stderr}") from e
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_offheap_library() -> ctypes.CDLL:
    """Load (compiling if needed) the native library; raises on failure."""
    global _LIB, _LOAD_FAILED
    with _LOCK:
        if _LIB is not None:
            return _LIB
        if _LOAD_FAILED:
            raise RuntimeError("native off-heap library previously failed to load")
        try:
            path = _lib_path()
            if not os.path.exists(path):
                logger.info("compiling native off-heap store library")
                _compile(path)
            lib = ctypes.CDLL(path)
            lib.om_build.restype = ctypes.c_int64
            lib.om_build.argtypes = [
                ctypes.c_char_p,
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64,
            ]
            lib.om_open.restype = ctypes.c_void_p
            lib.om_open.argtypes = [ctypes.c_char_p]
            lib.om_close.restype = None
            lib.om_close.argtypes = [ctypes.c_void_p]
            lib.om_size.restype = ctypes.c_int64
            lib.om_size.argtypes = [ctypes.c_void_p]
            lib.om_get.restype = ctypes.c_int64
            lib.om_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
            lib.om_key_at.restype = ctypes.c_int64
            lib.om_key_at.argtypes = [
                ctypes.c_void_p,
                ctypes.c_uint64,
                ctypes.c_char_p,
                ctypes.c_uint64,
            ]
            _LIB = lib
            return lib
        except Exception:
            _LOAD_FAILED = True
            raise


def native_available() -> bool:
    try:
        load_offheap_library()
        return True
    except Exception:
        return False
