"""Per-entity dimensionality reduction for random-effect training.

Reference parity: photon-api projector/ — ProjectorType {IndexMapProjection,
RandomProjection, IdentityProjection} (projector/ProjectorType.scala),
IndexMapProjectorRDD.buildIndexMapProjector (collect active indices per
entity, build per-entity index maps, projector/IndexMapProjectorRDD.scala:
218-257), ProjectionMatrixBroadcast (random Gaussian matrix shared by all
entities), IdentityProjector.

TPU-native redesign: a per-entity index map becomes a per-entity gather
index array baked into the entity bucket at dataset-build time —
features[:, cols] — so the vmapped solver works on [e, cap, k] blocks with
k = the bucket's max active-column count instead of the full shard width.
Solved coefficients scatter back into the full [num_entities, dim] model
table (models always live in original space, like the reference's
RandomEffectModelInProjectedSpace un-projection). Random projection is one
PRNG-keyed [d, k] matrix applied to every entity (the broadcast matrix of
the reference); back-projection w = P w_k.
"""

from __future__ import annotations

import dataclasses
import enum

import numpy as np


class ProjectorType(enum.Enum):
    """Reference: projector/ProjectorType.scala."""

    IDENTITY = "IDENTITY"
    INDEX_MAP = "INDEX_MAP"
    RANDOM = "RANDOM"


@dataclasses.dataclass(frozen=True)
class RandomProjectionMatrix:
    """Gaussian projection shared across entities (reference
    ProjectionMatrixBroadcast). matrix: [d, k], entries N(0, 1/d) so
    E[Pᵀ P] = I — the warm start Pᵀw then approximates the previous
    projected solution without rescaling."""

    matrix: np.ndarray

    @classmethod
    def create(cls, dim: int, projected_dim: int, seed: int = 0) -> "RandomProjectionMatrix":
        if projected_dim >= dim:
            raise ValueError(
                f"random projection needs projected_dim < dim, got {projected_dim} >= {dim}"
            )
        rng = np.random.default_rng(seed)
        m = rng.normal(scale=1.0 / np.sqrt(dim), size=(dim, projected_dim))
        return cls(matrix=m.astype(np.float32))

    @property
    def dim(self) -> int:
        return self.matrix.shape[0]

    @property
    def projected_dim(self) -> int:
        return self.matrix.shape[1]

    def project_features(self, features: np.ndarray) -> np.ndarray:
        return features @ self.matrix

    def back_project(self, coefficients: np.ndarray) -> np.ndarray:
        """[..., k] solved coefficients -> [..., d] original space."""
        return coefficients @ self.matrix.T


def entity_active_columns(features: np.ndarray) -> np.ndarray:
    """Columns with any nonzero value across an entity's samples — the
    entity's observed support (IndexMapProjectorRDD.scala:218-257)."""
    cols = np.nonzero(np.any(features != 0, axis=0))[0]
    if cols.size == 0:
        cols = np.array([0], dtype=np.int64)
    return cols
