"""Random-effect projectors (reference photon-api projector/*.scala)."""

from photon_ml_tpu.projector.projectors import (
    ProjectorType,
    RandomProjectionMatrix,
    entity_active_columns,
)

__all__ = ["ProjectorType", "RandomProjectionMatrix", "entity_active_columns"]
