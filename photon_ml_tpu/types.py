"""Core type aliases and task types.

Reference parity: photon-lib Types.scala:21-44 and TaskType.scala.
"""

from __future__ import annotations

import enum

# Type aliases (reference Types.scala). In the TPU build, per-sample unique
# ids are int64 arrays; coordinate / random-effect / feature-shard ids are
# python strings (host-side metadata, never traced).
UniqueSampleId = int
CoordinateId = str
REType = str
REId = str
FeatureShardId = str


class TaskType(enum.Enum):
    """Supported training tasks (reference TaskType.scala)."""

    LINEAR_REGRESSION = "LINEAR_REGRESSION"
    LOGISTIC_REGRESSION = "LOGISTIC_REGRESSION"
    POISSON_REGRESSION = "POISSON_REGRESSION"
    SMOOTHED_HINGE_LOSS_LINEAR_SVM = "SMOOTHED_HINGE_LOSS_LINEAR_SVM"
    NONE = "NONE"

    @property
    def is_classification(self) -> bool:
        return self in (
            TaskType.LOGISTIC_REGRESSION,
            TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
        )
