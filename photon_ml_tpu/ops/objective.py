"""GLM objective functions: value / gradient / Hessian-vector / Hessian matrix.

This is the TPU-native replacement for the reference's hand-written streaming
aggregators (photon-lib function/glm/ValueAndGradientAggregator.scala,
HessianVectorAggregator.scala, HessianMatrixAggregator.scala) and the
objective-function hierarchy (function/ObjectiveFunction.scala:25-73,
DiffFunction, TwiceDiffFunction, L2Regularization.scala:26-72).

Design: the objective is a *pure scalar function* of the coefficients; the
gradient is ``jax.grad`` and the Hessian-vector product is a ``jax.jvp`` of
the gradient. XLA fuses the entire per-sample seqOp (margin dot product,
pointwise loss, axpy accumulation) into one pass over the feature block —
the fusion the reference implemented by hand, for free, on the MXU.

Normalization is folded in algebraically exactly as the reference does
(effective coefficients + margin shift, ValueAndGradientAggregator.scala:36-49)
so the feature data is never rewritten.

Distribution: there is no Distributed-vs-SingleNode split. Under jit with a
batch sharded along the sample axis, XLA inserts the cross-device reductions
(psum trees) that replace ``RDD.treeAggregate``
(DistributedGLMLossFunction.scala:91-135). The same objective vmaps over
per-entity blocks for random-effect local solves. An explicit ``axis_name``
is supported for shard_map contexts.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.batch import LabeledPointBatch
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.ops.normalization import NormalizationContext, no_normalization

Array = jax.Array

try:
    from jax._src.interpreters.batching import BatchTracer as _BatchTracer
except ImportError:  # pragma: no cover - jax internals moved
    _BatchTracer = None
    # Loud, once, at import: the fail-safe below silently downgrades EVERY
    # auto-mode solve to the 2-pass autodiff path (~0.5x the one-pass
    # kernel). tests/test_pallas_glm.py carries the matching canary test.
    import logging as _logging

    _logging.getLogger(__name__).warning(
        "jax private BatchTracer import broke (jax internals moved): "
        "vmap detection disabled, the single-pass Pallas GLM kernel is OFF "
        "for all auto-mode solves — update _under_vmap in %s", __name__,
    )


def _under_vmap(*arrays) -> bool:
    """True when any input is a vmap batch tracer (the Pallas kernel has no
    batching rule worth using; vmapped lanes stay on the autodiff path).
    Fails SAFE: if the private BatchTracer type is unavailable (jax
    internals moved), report "vmapped" so the kernel never silently bakes
    into a vmapped loop (the serial per-lane regression)."""
    if _BatchTracer is None:
        return True
    return any(isinstance(a, _BatchTracer) for a in arrays)


class GLMObjective:
    """Weighted GLM objective: sum_i w_i * l(margin_i, y_i) + (l2/2)‖w‖².

    The L1 term of elastic-net regularization is *not* part of this smooth
    objective — it is handled by OWL-QN's pseudo-gradient, mirroring the
    reference where L1 lives in breeze's OWLQN, not in the loss
    (optimization/OWLQN.scala:40-86).
    """

    def __init__(
        self,
        loss: PointwiseLoss,
        l2_weight: float = 0.0,
        normalization: NormalizationContext | None = None,
        axis_name: str | None = None,
        use_pallas: bool | None = None,
    ):
        self.loss = loss
        self.l2_weight = float(l2_weight)
        self.normalization = normalization if normalization is not None else no_normalization()
        self.axis_name = axis_name
        #: route value_and_gradient through the single-pass Pallas kernel
        #: (ops/pallas_glm.py). None (default) means "auto": the kernel on
        #: TPU whenever the call is not visibly vmapped. The kernel streams
        #: X across HBM once per eval where autodiff reads it twice —
        #: measured ~2x per eval f32 and more with bf16 feature blocks
        #: (BASELINE.md r4 study). False forces autodiff — REQUIRED for
        #: (a) solves that get vmapped (λ-grid lanes, per-entity RE/MF
        #: buckets): `lax.while_loop` bodies trace with UNBATCHED tracers,
        #: so the auto-detection below cannot see a vmap wrapping the
        #: solver loop, and a Pallas call baked into the loop body batches
        #: into a serial per-lane loop (~lanes x slower); and (b) GSPMD
        #: mesh-sharded batches, whose pallas_call XLA cannot partition
        #: (parallel/distributed.py sets it). True forces the kernel where
        #: supported (still falls back on a DIRECTLY visible vmap).
        self.use_pallas = use_pallas

    # Value-based identity so jit static-arg caching works across repeated
    # construction (coordinate-descent iterations reuse compiled programs).
    # Normalization contexts hold arrays, so they compare by object identity;
    # coordinates construct theirs once.
    def _key(self):
        return (type(self.loss), self.l2_weight, self.axis_name,
                id(self.normalization), self.use_pallas)

    def __eq__(self, other):
        return isinstance(other, GLMObjective) and self._key() == other._key()

    def __hash__(self):
        return hash(self._key())

    # -- core scalar function ------------------------------------------------

    def margins(self, coefficients: Array, batch: LabeledPointBatch) -> Array:
        eff = self.normalization.effective_coefficients(coefficients)
        shift = self.normalization.margin_shift(eff)
        x = batch.features
        if x.dtype == jnp.bfloat16 and eff.dtype != jnp.bfloat16:
            # bf16 feature blocks: keep X in bf16 across HBM (half the
            # traffic of the upcast a mixed-dtype matmul would do) and let
            # the MXU accumulate in f32. Coefficients stay f32; only the
            # per-product operand is rounded — same arithmetic as the
            # Pallas kernel's bf16 path.
            m = jnp.matmul(x, eff.astype(jnp.bfloat16),
                           preferred_element_type=eff.dtype)
        else:
            m = x @ eff
        return m - shift + batch.offsets

    def _data_value(self, coefficients: Array, batch: LabeledPointBatch) -> Array:
        margins = self.margins(coefficients, batch)
        losses = self.loss.loss(margins, batch.labels)
        total = jnp.sum(batch.weights * losses)
        if self.axis_name is not None:
            total = jax.lax.psum(total, self.axis_name)
        return total

    def value(self, coefficients: Array, batch: LabeledPointBatch) -> Array:
        total = self._data_value(coefficients, batch)
        if self.l2_weight > 0.0:
            total = total + 0.5 * self.l2_weight * jnp.vdot(coefficients, coefficients)
        return total

    # -- derivatives ---------------------------------------------------------

    def _pallas_enabled(self, coefficients: Array, batch: LabeledPointBatch) -> bool:
        if self.use_pallas is False or self.axis_name is not None:
            return False
        if _under_vmap(coefficients, batch.features):
            # vmapped lanes (λ-grid, per-entity RE solves) share X reads
            # across lanes in one XLA matmul — the kernel has no lane axis
            return False
        if self.use_pallas is None:
            return jax.default_backend() == "tpu"
        return True

    def value_and_gradient(
        self, coefficients: Array, batch: LabeledPointBatch
    ) -> tuple[Array, Array]:
        if self._pallas_enabled(coefficients, batch):
            from photon_ml_tpu.ops.pallas_glm import fused_value_and_gradient

            return fused_value_and_gradient(
                self.loss, coefficients, batch,
                l2_weight=self.l2_weight, normalization=self.normalization,
            )
        return jax.value_and_grad(self.value)(coefficients, batch)

    def gradient(self, coefficients: Array, batch: LabeledPointBatch) -> Array:
        return self.value_and_gradient(coefficients, batch)[1]

    def hessian_vector(
        self, coefficients: Array, vector: Array, batch: LabeledPointBatch
    ) -> Array:
        """H @ v via forward-over-reverse (one jvp of the gradient).

        Replaces HessianVectorAggregator + its treeAggregate; TRON calls this
        once per CG step (reference TRON.scala:298-300).
        """
        grad_fn = lambda w: jax.grad(self.value)(w, batch)
        return jax.jvp(grad_fn, (coefficients,), (vector,))[1]

    def hessian_matrix(self, coefficients: Array, batch: LabeledPointBatch) -> Array:
        """Dense Hessian X'ᵀ D X' + l2·I — for variance estimation / diagnostics
        on small dims only (reference HessianMatrixAggregator, used by
        DistributedOptimizationProblem variance computation).
        """
        margins = self.margins(coefficients, batch)
        d2 = self.loss.d2z(margins, batch.labels) * batch.weights
        factors = self.normalization.factors
        x = batch.features
        if factors is not None:
            x = x * factors
        if self.normalization.shifts is not None:
            shift_row = self.normalization.shifts * (
                factors if factors is not None else 1.0
            )
            x = x - shift_row
        h = x.T @ (d2[:, None] * x)
        if self.axis_name is not None:
            h = jax.lax.psum(h, self.axis_name)
        if self.l2_weight > 0.0:
            h = h + self.l2_weight * jnp.eye(h.shape[0], dtype=h.dtype)
        return h

    def hessian_diagonal(self, coefficients: Array, batch: LabeledPointBatch) -> Array:
        """diag(H) without materializing H — used for diagonal variance
        approximation at large dims."""
        margins = self.margins(coefficients, batch)
        d2 = self.loss.d2z(margins, batch.labels) * batch.weights
        factors = self.normalization.factors
        x = batch.features
        if factors is not None:
            x = x * factors
        if self.normalization.shifts is not None:
            shift_row = self.normalization.shifts * (
                factors if factors is not None else 1.0
            )
            x = x - shift_row
        diag = jnp.einsum("n,nd,nd->d", d2, x, x)
        if self.axis_name is not None:
            diag = jax.lax.psum(diag, self.axis_name)
        if self.l2_weight > 0.0:
            diag = diag + self.l2_weight
        return diag

    # -- functional views for the optimizers ---------------------------------

    def bind(self, batch: LabeledPointBatch) -> "BoundObjective":
        return BoundObjective(self, batch)


class BoundObjective:
    """Objective closed over a fixed batch: pure functions of coefficients.

    This is what optimizers consume; it is also what gets vmapped over entity
    blocks for random-effect coordinates.
    """

    def __init__(self, objective: GLMObjective, batch: LabeledPointBatch):
        self.objective = objective
        self.batch = batch

    def value(self, w: Array) -> Array:
        return self.objective.value(w, self.batch)

    def value_and_grad(self, w: Array) -> tuple[Array, Array]:
        return self.objective.value_and_gradient(w, self.batch)

    def hessian_vector(self, w: Array, v: Array) -> Array:
        return self.objective.hessian_vector(w, v, self.batch)

    def hessian_matrix(self, w: Array) -> Array:
        return self.objective.hessian_matrix(w, self.batch)


ValueAndGradFn = Callable[[Array], tuple[Array, Array]]
HessianVectorFn = Callable[[Array, Array], Array]
