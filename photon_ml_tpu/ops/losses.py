"""Pointwise GLM losses: scalar functions of (margin, label).

Every GLM loss in this framework is a function of the per-sample margin
z = w.x (+ offset) and the label. The objective layer only needs:

  - ``loss_and_dz(margin, label)``  -> (l, dl/dz)
  - ``d2z(margin, label)``          -> d2l/dz2

Reference parity: photon-lib function/glm/PointwiseLossFunction.scala:36-54
and the concrete losses in photon-api function/glm/{Logistic,Squared,Poisson}LossFunction.scala
and function/svm/SmoothedHingeLossFunction.scala:33-83.

All functions are elementwise, jit/vmap-safe, and numerically stable in
float32 (TPU native dtype); no python control flow on traced values.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from photon_ml_tpu.types import TaskType

Array = jax.Array


class PointwiseLoss:
    """Interface for pointwise losses. Subclasses are stateless singletons."""

    #: whether d2z is meaningful (TwiceDiffFunction in the reference)
    twice_differentiable: bool = True

    def loss_and_dz(self, margin: Array, label: Array) -> tuple[Array, Array]:
        raise NotImplementedError

    def d2z(self, margin: Array, label: Array) -> Array:
        raise NotImplementedError

    def loss(self, margin: Array, label: Array) -> Array:
        return self.loss_and_dz(margin, label)[0]


class LogisticLoss(PointwiseLoss):
    """Negative log-likelihood of the logistic model, labels in {0, 1}.

    l(z, y) = softplus(z) - y*z  (stable for all z)
    dl/dz   = sigmoid(z) - y
    d2l/dz2 = sigmoid(z) * (1 - sigmoid(z))

    Reference: photon-api function/glm/LogisticLossFunction.scala:45+.
    """

    def loss_and_dz(self, margin: Array, label: Array) -> tuple[Array, Array]:
        loss = jax.nn.softplus(margin) - label * margin
        dz = jax.nn.sigmoid(margin) - label
        return loss, dz

    def d2z(self, margin: Array, label: Array) -> Array:
        s = jax.nn.sigmoid(margin)
        return s * (1.0 - s)


class SquaredLoss(PointwiseLoss):
    """Squared loss for linear regression: l = (z - y)^2 / 2.

    Reference: photon-api function/glm/SquaredLossFunction.scala.
    """

    def loss_and_dz(self, margin: Array, label: Array) -> tuple[Array, Array]:
        diff = margin - label
        return 0.5 * diff * diff, diff

    def d2z(self, margin: Array, label: Array) -> Array:
        return jnp.ones_like(margin)


class PoissonLoss(PointwiseLoss):
    """Poisson regression negative log-likelihood: l = exp(z) - y*z.

    Reference: photon-api function/glm/PoissonLossFunction.scala.
    """

    def loss_and_dz(self, margin: Array, label: Array) -> tuple[Array, Array]:
        ez = jnp.exp(margin)
        return ez - label * margin, ez - label

    def d2z(self, margin: Array, label: Array) -> Array:
        return jnp.exp(margin)


class SmoothedHingeLoss(PointwiseLoss):
    """Rennie's smoothed hinge loss for linear SVM, labels in {0, 1}.

    With t = (2y - 1) * z:
        l = 1/2 - t        if t <= 0
        l = (1 - t)^2 / 2  if 0 < t < 1
        l = 0              if t >= 1

    Only first-order in the reference (DiffFunction — LBFGS family only,
    photon-api function/svm/SmoothedHingeLossFunction.scala:33-83); we expose
    the piecewise-constant second derivative for completeness but mark the
    loss as not twice differentiable so TRON refuses it, matching reference
    behavior.
    """

    twice_differentiable = False

    def loss_and_dz(self, margin: Array, label: Array) -> tuple[Array, Array]:
        y = 2.0 * label - 1.0
        t = y * margin
        loss = jnp.where(t <= 0.0, 0.5 - t, jnp.where(t < 1.0, 0.5 * (1.0 - t) ** 2, 0.0))
        dt = jnp.where(t <= 0.0, -1.0, jnp.where(t < 1.0, t - 1.0, 0.0))
        return loss, y * dt

    def d2z(self, margin: Array, label: Array) -> Array:
        y = 2.0 * label - 1.0
        t = y * margin
        return jnp.where((t > 0.0) & (t < 1.0), 1.0, 0.0)


_LOSS_BY_TASK: dict[TaskType, PointwiseLoss] = {
    TaskType.LINEAR_REGRESSION: SquaredLoss(),
    TaskType.LOGISTIC_REGRESSION: LogisticLoss(),
    TaskType.POISSON_REGRESSION: PoissonLoss(),
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: SmoothedHingeLoss(),
}


def loss_for_task(task: TaskType) -> PointwiseLoss:
    """Map a task type to its pointwise loss (reference GLMLossFunction factory)."""
    try:
        return _LOSS_BY_TASK[task]
    except KeyError:
        raise ValueError(f"No loss defined for task {task}") from None
