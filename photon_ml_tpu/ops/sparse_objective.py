"""GLM objective over flat-COO sparse batches (giant feature spaces).

Reference parity: the same value/gradient/Hessian-vector contract as
GLMObjective (reference function/ObjectiveFunction.scala hierarchy and the
sparse-aware aggregators in function/glm/ValueAndGradientAggregator.scala —
the whole point of their effectiveCoef/marginShift algebra was to keep
sparse vectors sparse; here the algebra is identical and XLA derives the
transpose scatter-add from the forward gather+segment-sum by autodiff).

Memory story: only O(nnz) per-entry arrays and O(d) vectors (coefficients,
gradient, normalization factors) — no [n, d] anywhere. d=10⁷ is a 40 MB f32
coefficient vector; the dense block it replaces would be n·d·4 bytes
(0.5 TB at n=10⁵ already). LBFGS history (m=10 pairs) adds 20·d floats —
at truly giant d prefer TRON (4-5 work vectors), matching the reference's
TRON-for-L2 positioning (SURVEY.md §7).

Mesh story: the coefficient axis shards over "model"
(``NamedSharding(mesh, P("model"))``); the gather at ``w[col_indices]``
and the transpose scatter lower to XLA collectives automatically under
jit. The flat entry arrays shard over "data" like dense sample axes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.sparse_batch import (
    SparseLabeledPointBatch,
    sparse_column_sum,
    sparse_margins,
)
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.ops.normalization import (
    NormalizationContext,
    no_normalization,
)
from photon_ml_tpu.ops.objective import BoundObjective

Array = jax.Array

#: chunk width of the sorted-run reduction: bounds the magnitude any prefix
#: difference can cancel against (f32 error ~ eps·|within-chunk prefix|) and
#: keeps the [C, B] cumsum VPU-friendly
_RUN_CHUNK = 4096


def _sorted_run_sums(contrib: Array, bounds: Array) -> Array:
    """Sum each contiguous run of a (column-)sorted contribution vector.

    ``bounds`` is the [dim+1] int32 run-boundary array (run j =
    ``contrib[bounds[j]:bounds[j+1]]``, precomputed on host by
    ``_column_sorted_arrays``). TPU-native replacement for
    ``segment_sum(..., num_segments=dim)``: a two-level prefix sum over
    [C, B] chunks plus one gather per boundary —
        P(p) = chunk_prefix[p // B] + intra_chunk_cumsum[p]
        run_sum[j] = P(bounds[j+1]-1) - P(bounds[j]-1)
    Everything is cumsum/reshape/gather (bandwidth-bound, compiles in
    seconds at any dim); no scatter appears anywhere. Empty runs subtract
    identical gathers and come out exactly 0. Cross-chunk cancellation only
    touches runs that span a chunk edge, whose sums are large relative to
    the f32 error it introduces.
    """
    nnz = contrib.shape[0]
    pad = (-nnz) % _RUN_CHUNK
    if pad:
        contrib = jnp.pad(contrib, (0, pad))
    c2 = contrib.reshape(-1, _RUN_CHUNK)
    intra = jnp.cumsum(c2, axis=1)
    chunk_prefix = jnp.concatenate(
        [jnp.zeros((1,), intra.dtype), jnp.cumsum(intra[:, -1])]
    )
    intra_flat = intra.reshape(-1)
    end = bounds[1:] - 1
    start = bounds[:-1] - 1

    def parts(pos):
        safe = jnp.maximum(pos, 0)
        valid = pos >= 0
        i = jnp.where(valid, intra_flat[safe], 0.0)
        p = jnp.where(valid, chunk_prefix[safe // _RUN_CHUNK], 0.0)
        return i, p

    i_end, p_end = parts(end)
    i_start, p_start = parts(start)
    # grouped so same-chunk runs cancel the chunk prefix exactly
    return (i_end - i_start) + (p_end - p_start)


class SparseGLMObjective:
    """Sparse twin of GLMObjective: same interface, flat-COO batches.

    Supports the full normalization algebra (factors + shifts): the margin
    uses effective coefficients and the scalar margin shift, so shifted
    (standardized) features never densify the data — autodiff turns the
    shift term into the dense rank-one gradient correction automatically.
    """

    def __init__(
        self,
        loss: PointwiseLoss,
        l2_weight: float = 0.0,
        normalization: NormalizationContext | None = None,
        axis_name: str | None = None,
    ):
        self.loss = loss
        self.l2_weight = float(l2_weight)
        self.normalization = (
            normalization if normalization is not None else no_normalization()
        )
        self.axis_name = axis_name

    # Value-based identity so jit static-arg caching works (same contract as
    # GLMObjective._key).
    def _key(self):
        return (type(self.loss), self.l2_weight, self.axis_name,
                id(self.normalization))

    def __eq__(self, other):
        return isinstance(other, SparseGLMObjective) and self._key() == other._key()

    def __hash__(self):
        return hash(self._key())

    # -- core scalar function ------------------------------------------------

    def margins(self, coefficients: Array, batch: SparseLabeledPointBatch) -> Array:
        eff = self.normalization.effective_coefficients(coefficients)
        shift = self.normalization.margin_shift(eff)
        return sparse_margins(batch, eff) - shift

    def value(self, coefficients: Array, batch: SparseLabeledPointBatch) -> Array:
        margins = self.margins(coefficients, batch)
        losses = self.loss.loss(margins, batch.labels)
        total = jnp.sum(batch.weights * losses)
        if self.axis_name is not None:
            total = jax.lax.psum(total, self.axis_name)
        if self.l2_weight > 0.0:
            total = total + 0.5 * self.l2_weight * jnp.vdot(coefficients, coefficients)
        return total

    # -- derivatives ---------------------------------------------------------

    def value_and_gradient(
        self, coefficients: Array, batch: SparseLabeledPointBatch
    ) -> tuple[Array, Array]:
        if batch.has_hybrid_view:
            return self._value_and_gradient_hybrid(coefficients, batch)
        if batch.has_column_sorted_view:
            return self._value_and_gradient_column_sorted(coefficients, batch)
        return jax.value_and_grad(self.value)(coefficients, batch)

    def _tail_gradient_update(
        self, g_eff: Array, dzw: Array, batch: SparseLabeledPointBatch
    ) -> Array:
        """Scatter the cold-tail contributions (ELL block + flat overflow)
        into the effective gradient — the same transpose scatters autodiff
        derives for the ELL path, written out so the hybrid value+gradient
        shares ONE dz evaluation across head and tail (the r4 dense-kernel
        single-pass discipline)."""
        if batch.has_ell_view:
            contrib = dzw[:, None] * batch.ell_vals
            g_eff = g_eff.at[batch.ell_cols.ravel()].add(contrib.ravel())
        if batch.values.shape[0]:
            g_eff = g_eff.at[batch.col_indices].add(
                dzw[batch.row_ids] * batch.values
            )
        return g_eff

    def _value_and_gradient_hybrid(
        self, coefficients: Array, batch: SparseLabeledPointBatch
    ) -> tuple[Array, Array]:
        """Hand-fused value+gradient over the hybrid dense-head/sparse-tail
        layout (ISSUE 5 tentpole).

        One forward margin evaluation (hot MXU matmul + ELL/flat tail), one
        dz, then the gradient assembles as
            head:  dzwᵀ X_hot  — a dense [n]·[n, k_hot] matvec plus a
                   k_hot-sized scatter into [dim] (amortized over n rows;
                   NO per-entry index ops for covered nonzeros)
            tail:  the existing ELL/flat transpose scatters, now over the
                   cold residual only
        with the full normalization algebra of the column-sorted path:
            ∂/∂w = f ⊙ (Σ dz·x − (Σ dz)·shifts) + λw.
        Verified against the flat autodiff path in tests (the view-contract
        property test)."""
        margins = self.margins(coefficients, batch)
        losses, dz = self.loss.loss_and_dz(margins, batch.labels)
        total = jnp.sum(batch.weights * losses)
        dzw = batch.weights * dz
        g_eff = jnp.zeros((batch.dim,), dtype=batch.values.dtype)
        g_eff = g_eff.at[batch.hot_col_ids].add(dzw @ batch.hot_vals)
        g_eff = self._tail_gradient_update(g_eff, dzw, batch)
        norm = self.normalization
        if norm.shifts is not None:
            g_eff = g_eff - jnp.sum(dzw) * norm.shifts
        grad = g_eff * norm.factors if norm.factors is not None else g_eff
        if self.axis_name is not None:
            total = jax.lax.psum(total, self.axis_name)
            grad = jax.lax.psum(grad, self.axis_name)
        if self.l2_weight > 0.0:
            total = total + 0.5 * self.l2_weight * jnp.vdot(
                coefficients, coefficients
            )
            grad = grad + self.l2_weight * coefficients
        return total, grad

    def _value_and_gradient_column_sorted(
        self, coefficients: Array, batch: SparseLabeledPointBatch
    ) -> tuple[Array, Array]:
        """Hand-fused value+gradient using the batch's column-sorted view.

        The autodiff gradient transposes the margin gather into a
        random-index scatter-add over [dim] — the dominant cost of giant-d
        solves on TPU (BENCH_r02: 733 ms/iter at d=10⁷, ~0.1 GB/s useful
        traffic). With the entries pre-sorted by column, each column's
        contributions form one contiguous run, and the whole reduction
        becomes chunked prefix sums + a boundary gather
        (:func:`_sorted_run_sums`) — cumsum/gather only, no scatter and no
        giant-``num_segments`` segment-sum (the latter failed to compile at
        d=10⁷ on the TPU compile service, BASELINE.md r2). Full
        normalization algebra:
            margin_i = Σ vals·eff[cols] − eff·shifts + offsets
            ∂/∂w     = f ⊙ (Σ_col dz·x  −  (Σ_i dz_i)·shifts) + λw
        (f = factors; dz = w_i·l'_i). Verified against the autodiff path in
        tests.
        """
        margins = self.margins(coefficients, batch)
        losses, dz = self.loss.loss_and_dz(margins, batch.labels)
        total = jnp.sum(batch.weights * losses)
        dzw = batch.weights * dz
        contrib = dzw[batch.rows_by_col] * batch.vals_by_col
        if batch.col_bounds is not None:
            g_eff = _sorted_run_sums(contrib, batch.col_bounds)
        else:
            g_eff = jax.ops.segment_sum(
                contrib, batch.cols_sorted,
                num_segments=batch.dim, indices_are_sorted=True,
            )
        norm = self.normalization
        if norm.shifts is not None:
            g_eff = g_eff - jnp.sum(dzw) * norm.shifts
        grad = g_eff * norm.factors if norm.factors is not None else g_eff
        if self.axis_name is not None:
            total = jax.lax.psum(total, self.axis_name)
            grad = jax.lax.psum(grad, self.axis_name)
        if self.l2_weight > 0.0:
            total = total + 0.5 * self.l2_weight * jnp.vdot(
                coefficients, coefficients
            )
            grad = grad + self.l2_weight * coefficients
        return total, grad

    def gradient(self, coefficients: Array, batch: SparseLabeledPointBatch) -> Array:
        return self.value_and_gradient(coefficients, batch)[1]

    def hessian_vector(
        self, coefficients: Array, vector: Array, batch: SparseLabeledPointBatch
    ) -> Array:
        """H @ v. With a column-sorted view (and no margin shifts) this is
        the scatter-free ladder TRON needs at giant d:
            H v = f ⊙ (Xᵀ D X (f ⊙ v)) + λ v,   D = diag(w_i·l''_i)
        — a row gather/segment-sum forward, then the same sorted-run
        reduction as the gradient. Otherwise forward-over-reverse jvp of
        the gradient, same as the dense path (TRON calls this per CG step).

        Hybrid view (and no margin shifts): the identical dense-head /
        sparse-tail split as the gradient — forward X(f·v) rides the hot
        MXU matmul + cold tail, and the transpose assembles as the head
        matvec + k_hot scatter plus the tail scatters. This is TRON's CG
        inner loop at giant d (the d=10⁸ bench row).
        """
        norm = self.normalization
        if batch.has_hybrid_view and norm.shifts is None:
            eff_v = norm.effective_coefficients(vector)
            mv = sparse_margins(batch, eff_v) - batch.offsets  # pure X @ f·v
            margins = self.margins(coefficients, batch)
            d2w = self.loss.d2z(margins, batch.labels) * batch.weights
            t = d2w * mv
            hv_eff = jnp.zeros((batch.dim,), dtype=batch.values.dtype)
            hv_eff = hv_eff.at[batch.hot_col_ids].add(t @ batch.hot_vals)
            hv_eff = self._tail_gradient_update(hv_eff, t, batch)
            hv = hv_eff * norm.factors if norm.factors is not None else hv_eff
            if self.axis_name is not None:
                hv = jax.lax.psum(hv, self.axis_name)
            if self.l2_weight > 0.0:
                hv = hv + self.l2_weight * vector
            return hv
        if batch.has_column_sorted_view and norm.shifts is None:
            eff_v = norm.effective_coefficients(vector)
            mv = sparse_margins(batch, eff_v) - batch.offsets  # pure X @ f·v
            margins = self.margins(coefficients, batch)
            d2w = self.loss.d2z(margins, batch.labels) * batch.weights
            t = d2w * mv
            contrib = t[batch.rows_by_col] * batch.vals_by_col
            if batch.col_bounds is not None:
                hv_eff = _sorted_run_sums(contrib, batch.col_bounds)
            else:
                hv_eff = jax.ops.segment_sum(
                    contrib, batch.cols_sorted,
                    num_segments=batch.dim, indices_are_sorted=True,
                )
            hv = hv_eff * norm.factors if norm.factors is not None else hv_eff
            if self.axis_name is not None:
                hv = jax.lax.psum(hv, self.axis_name)
            if self.l2_weight > 0.0:
                hv = hv + self.l2_weight * vector
            return hv
        grad_fn = lambda w: jax.grad(self.value)(w, batch)
        return jax.jvp(grad_fn, (coefficients,), (vector,))[1]

    def hessian_diagonal(
        self, coefficients: Array, batch: SparseLabeledPointBatch
    ) -> Array:
        """diag(H) = Σ_i w_i l''_i x'_ij² without materializing H.

        With shifts, x'_ij = f_j(x_ij - s_j) expands into sparse, cross, and
        dense terms — all three are one column-sum or one dense vector op.
        """
        margins = self.margins(coefficients, batch)
        d2 = self.loss.d2z(margins, batch.labels) * batch.weights
        f = self.normalization.factors
        s = self.normalization.shifts
        # Σ d2·x², Σ d2·x (per column), Σ d2 (scalar)
        sq = sparse_column_sum(batch, d2, square_values=True)
        if s is not None:
            lin = sparse_column_sum(batch, d2)
            tot = jnp.sum(d2)
            diag = sq - 2.0 * s * lin + s * s * tot
        else:
            diag = sq
        if f is not None:
            diag = diag * f * f
        if self.axis_name is not None:
            diag = jax.lax.psum(diag, self.axis_name)
        if self.l2_weight > 0.0:
            diag = diag + self.l2_weight
        return diag

    # -- functional views ----------------------------------------------------

    def bind(self, batch: SparseLabeledPointBatch) -> BoundObjective:
        """Optimizers consume the same duck-typed BoundObjective as the
        dense path — LBFGS/OWLQN/TRON run unchanged over sparse data."""
        return BoundObjective(self, batch)
