"""Coefficient-variance estimation for trained GLMs.

Reference parity: DistributedOptimizationProblem.computeVariances
(photon-api optimization/DistributedOptimizationProblem.scala:82-96) and
SingleNodeOptimizationProblem.computeVariances (:58-69) — both build the
full Hessian at the optimum and return diag(H⁻¹) via Cholesky inverse
(photon-lib util/Linalg.scala choleskyInverse).

TPU-native: H is one X'ᵀDX' matmul on the MXU (GLMObjective.hessian_matrix);
diag(H⁻¹) = column sums of squares of L⁻¹ where H = LLᵀ, i.e. one triangular
solve against I. O(d³) compute / O(d²) memory, so FULL is gated to small d;
above FULL_VARIANCE_MAX_DIM the AUTO mode falls back to the diagonal
approximation 1/diag(H) (exact when H is diagonal, and the only option at
giant-FE scale where H cannot be materialized).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

Array = jax.Array

#: AUTO uses the reference-fidelity full Cholesky inverse up to this many
#: coefficients (d² Hessian = 64 MB f32 at the boundary), diagonal beyond.
FULL_VARIANCE_MAX_DIM = 4096

_MODES = ("auto", "full", "diagonal")


def validate_variance_mode(mode: str) -> str:
    """Fail fast on typos (called at config-parse time, before any solve)."""
    if mode not in _MODES:
        raise ValueError(f"variance mode must be one of {_MODES}, got {mode!r}")
    return mode


def resolve_variance_mode(mode: str, dim: int, num_problems: int = 1) -> str:
    """Resolve "auto" to a concrete mode.

    num_problems: how many d×d Hessians materialize at once (e.g. vmapped
    λ-grid lanes) — AUTO's memory budget covers the whole stack, not one.
    """
    validate_variance_mode(mode)
    if mode == "auto":
        budget = FULL_VARIANCE_MAX_DIM * FULL_VARIANCE_MAX_DIM
        return "full" if num_problems * dim * dim <= budget else "diagonal"
    return mode


def resolve_variance_mode_for(
    objective, mode: str, dim: int, num_problems: int = 1
) -> str:
    """Like :func:`resolve_variance_mode`, but also accounts for objectives
    that cannot materialize a dense Hessian (sparse/giant-d): AUTO falls
    back to diagonal; an explicit "full" request raises."""
    resolved = resolve_variance_mode(mode, dim, num_problems)
    if resolved == "full" and not hasattr(objective, "hessian_matrix"):
        if mode == "full":
            raise ValueError(
                "variance_mode='full' requires a dense Hessian; this "
                f"objective ({type(objective).__name__}) only supports the "
                "diagonal approximation"
            )
        resolved = "diagonal"
    return resolved


def inverse_of_diagonal(diag: Array) -> Array:
    """The diagonal approximation's clamped inverse — single definition so
    every path (sequential, grid lanes, per-entity) uses the same floor."""
    return 1.0 / jnp.maximum(diag, 1e-12)


def diag_inverse_from_hessian(h: Array) -> Array:
    """diag(H⁻¹) via Cholesky, without forming H⁻¹, with a built-in guard:
    entries where the factorization produced non-finite values (H not
    positive definite — e.g. λ=0 with exactly collinear features, or a
    per-entity block with fewer samples than dimensions) fall back to the
    clamped diagonal approximation 1/diag(H) elementwise, instead of
    persisting NaN into saved models. (The reference's breeze `cholesky`
    throws outright on non-PD input — Linalg.scala choleskyInverse — but a
    traceable elementwise select is the jit/vmap-compatible equivalent.)
    Near-singular-but-factorizable H yields large variances, same as the
    reference.

    H = LLᵀ ⇒ H⁻¹ = L⁻ᵀL⁻¹ ⇒ diag(H⁻¹)ᵢ = Σⱼ (L⁻¹)ⱼᵢ².
    """
    chol = jnp.linalg.cholesky(h)
    eye = jnp.eye(h.shape[0], dtype=h.dtype)
    linv = jax.scipy.linalg.solve_triangular(chol, eye, lower=True)
    full = jnp.sum(linv * linv, axis=0)
    approx = inverse_of_diagonal(jnp.diagonal(h))
    return jnp.where(jnp.isfinite(full), full, approx)


def full_inverse_from_hessian(h: Array) -> Array:
    """Full H⁻¹ via Cholesky (for covariance PROPAGATION through a
    projection: diag(P H⁻¹ Pᵀ) needs the off-diagonal entries that
    :func:`diag_inverse_from_hessian` never materializes). Non-PD H falls
    back to the clamped diagonal-only inverse, mirroring that function's
    guard."""
    chol = jnp.linalg.cholesky(h)
    eye = jnp.eye(h.shape[0], dtype=h.dtype)
    linv = jax.scipy.linalg.solve_triangular(chol, eye, lower=True)
    full = linv.T @ linv
    approx = jnp.diag(inverse_of_diagonal(jnp.diagonal(h)))
    return jnp.where(jnp.isfinite(full).all(), full, approx)


@partial(jax.jit, static_argnums=(0,))
def _full_variances(objective, coefficients: Array, batch) -> Array:
    return diag_inverse_from_hessian(
        objective.hessian_matrix(coefficients, batch)
    )


@partial(jax.jit, static_argnums=(0,))
def _diagonal_variances(objective, coefficients: Array, batch) -> Array:
    return inverse_of_diagonal(objective.hessian_diagonal(coefficients, batch))


def coefficient_variances(
    objective, coefficients: Array, batch, mode: str = "auto"
) -> Array:
    """Per-coefficient variances at the optimum, in the objective's space.

    mode: "full" = diag(H⁻¹) (reference fidelity; requires H positive
    definite — guaranteed with l2_weight > 0, generically true for n > d);
    "diagonal" = 1/diag(H); "auto" picks by dimension.
    """
    resolved = resolve_variance_mode_for(
        objective, mode, int(coefficients.shape[-1])
    )
    if resolved == "full":
        return _full_variances(objective, coefficients, batch)
    return _diagonal_variances(objective, coefficients, batch)
