from photon_ml_tpu.ops.losses import (  # noqa: F401
    LogisticLoss,
    PointwiseLoss,
    PoissonLoss,
    SmoothedHingeLoss,
    SquaredLoss,
    loss_for_task,
)
from photon_ml_tpu.ops.normalization import (  # noqa: F401
    NormalizationContext,
    NormalizationType,
    no_normalization,
)
from photon_ml_tpu.ops.objective import GLMObjective  # noqa: F401
