"""Pallas TPU kernel: fused GLM value + gradient in one pass over X.

This is the reference's hot loop (ValueAndGradientAggregator.scala:133-177 —
per-sample margin dot product, pointwise loss, axpy accumulation, merged
tree-wise) as a single Pallas kernel: each row tile streams through VMEM
once, computing the margin, the pointwise loss/derivative (VPU), and the
gradient outer-accumulation before the tile leaves the chip.

Measured verdict (v5e, n=2^17 d=512 logistic, BASELINE.md): XLA *already*
performs this exact fusion on the autodiff path — the margin matvec, the
elementwise loss, and the gradient matvec compile to a single pass over X at
~750 GB/s marginal (near the 819 GB/s HBM roofline), while this kernel's
Mosaic lowering streams at ~270 GB/s (the [tile, 1] margin/residual columns
occupy one lane of each vreg, so the pointwise stage runs at 1/128th VPU
occupancy). The kernel therefore stays an OPT-IN (``use_pallas=True``)
correctness-tested alternative, not the default: "let XLA fuse — don't
hand-schedule what the compiler already does" won on measurement.

Grid: 1-D over row tiles; the value/gradient outputs map to the same block
in every grid step, making them sequential accumulators (TPU grids are
serialized), initialized at step 0. Padding rows carry weight 0 and padded
feature/coefficient columns are 0, so they contribute nothing.

Falls back to interpreter mode off-TPU, so the same code path is testable
on CPU (the guide's `interpret=True`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU-specific namespace; absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from photon_ml_tpu.data.batch import LabeledPointBatch
from photon_ml_tpu.ops.losses import PointwiseLoss

Array = jax.Array

_LANE = 128  # TPU lane width: last dim of every tile
_VMEM_BUDGET_BYTES = 8 * 1024 * 1024  # target VMEM footprint for the X tile


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _row_tile(d_pad: int) -> int:
    """Rows per grid step: fill the VMEM budget, stay MXU-aligned."""
    rows = _VMEM_BUDGET_BYTES // (4 * d_pad)
    return int(np.clip(_round_up(rows, 8) if rows >= 8 else 8, 8, 1024))


def _kernel(loss: PointwiseLoss, x_ref, y_ref, o_ref, ws_ref, w_ref,
            val_ref, grad_ref, rsum_ref):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        val_ref[0, 0] = jnp.float32(0.0)
        rsum_ref[0, 0] = jnp.float32(0.0)
        grad_ref[:] = jnp.zeros_like(grad_ref)

    x = x_ref[:]  # [tile, d_pad]
    # Margins via broadcast-multiply + lane reduction (constant accumulator —
    # Mosaic rejects reductions fused with a non-constant init, so the offset
    # is added in a separate op). M/N=1 dots lower to reductions anyway; the
    # op is HBM-bandwidth-bound, so the VPU path costs nothing.
    margins = jnp.sum(x * w_ref[:], axis=1, keepdims=True)  # [tile, 1]
    margins = margins + o_ref[:]
    l, dz = loss.loss_and_dz(margins, y_ref[:])
    ws = ws_ref[:]
    r = ws * dz
    val_ref[0, 0] += jnp.sum(ws * l)
    # Σr feeds the normalized-space chain rule (grad shift term) for free
    rsum_ref[0, 0] += jnp.sum(r)
    # gradient tile: [1, d_pad] = Σ_rows r ⊙ x
    g = jnp.sum(r * x, axis=0, keepdims=True)
    grad_ref[:] = grad_ref[:] + g


@functools.partial(jax.jit, static_argnums=(0, 5))
def _fused_padded(loss: PointwiseLoss, x, y, o, ws, interpret: bool, w):
    n_pad, d_pad = x.shape
    tile = _row_tile(d_pad)
    grid = (n_pad // tile,)

    vmem = dict(memory_space=pltpu.VMEM) if (_HAS_PLTPU and not interpret) else {}
    smem = dict(memory_space=pltpu.SMEM) if (_HAS_PLTPU and not interpret) else {}
    value, grad, rsum = pl.pallas_call(
        functools.partial(_kernel, loss),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, d_pad), lambda i: (i, 0), **vmem),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), **vmem),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), **vmem),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), **vmem),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0), **vmem),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), **smem),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0), **vmem),
            pl.BlockSpec((1, 1), lambda i: (0, 0), **smem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, y, o, ws, w.reshape(1, d_pad))
    return value[0, 0], grad[0], rsum[0, 0]


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


def fused_value_and_gradient(
    loss: PointwiseLoss,
    coefficients: Array,
    batch: LabeledPointBatch,
    *,
    l2_weight: float = 0.0,
    normalization=None,
    interpret: bool | None = None,
) -> tuple[Array, Array]:
    """Fused (value, gradient) of the weighted GLM objective.

    Numerically equivalent to ``jax.value_and_grad`` of GLMObjective.value,
    including the normalization algebra (effective coefficients + margin
    shift, ValueAndGradientAggregator.scala:36-49): the kernel streams X once
    with ``eff = factors*w`` and a shifted offset column, and the chain rule
    back to ``w`` uses the kernel's Σr output —
    ``grad_w = factors * (X'r - (Σr)*shifts)``. Use inside jit.
    Inputs of any shape are zero-padded to (8k rows, 128m cols); padded rows
    get weight 0 and padded columns 0 coefficients, contributing nothing.
    """
    if interpret is None:
        interpret = _should_interpret()
    x = jnp.asarray(batch.features, jnp.float32)
    n, d = x.shape
    tile = _row_tile(_round_up(d, _LANE))
    n_pad, d_pad = _round_up(max(n, 1), tile), _round_up(d, _LANE)
    x = jnp.pad(x, ((0, n_pad - n), (0, d_pad - d)))
    col = lambda v: jnp.pad(
        jnp.asarray(v, jnp.float32).reshape(-1, 1), ((0, n_pad - n), (0, 0))
    )
    factors = shifts = None
    if normalization is not None:
        factors, shifts = normalization.factors, normalization.shifts
    eff = jnp.asarray(coefficients, jnp.float32)
    if factors is not None:
        eff = eff * jnp.asarray(factors, jnp.float32)
    offsets = jnp.asarray(batch.offsets, jnp.float32)
    if shifts is not None:
        offsets = offsets - jnp.dot(eff, jnp.asarray(shifts, jnp.float32))
    w = jnp.pad(eff, (0, d_pad - d))
    value, grad, rsum = _fused_padded(
        loss, x, col(batch.labels), col(offsets), col(batch.weights),
        bool(interpret), w,
    )
    grad = grad[:d]
    if shifts is not None:
        grad = grad - rsum * jnp.asarray(shifts, jnp.float32)
    if factors is not None:
        grad = grad * jnp.asarray(factors, jnp.float32)
    grad = grad.astype(coefficients.dtype)
    if l2_weight > 0.0:
        value = value + 0.5 * l2_weight * jnp.vdot(coefficients, coefficients)
        grad = grad + l2_weight * coefficients
    return value.astype(coefficients.dtype), grad
