"""Pallas TPU kernel: fused GLM value + gradient in one pass over X.

This is the reference's hot loop (ValueAndGradientAggregator.scala:133-177 —
per-sample margin dot product, pointwise loss, axpy accumulation, merged
tree-wise) as a single Pallas kernel: each row tile streams through VMEM
once; the margin matvec, the pointwise loss/derivative, and the gradient
accumulation all consume the tile while it is resident, so X crosses HBM
once per evaluation where the autodiff/XLA path reads it twice (forward
margin matvec + backward transpose matvec — XLA does not fuse them into one
read; BASELINE.md r3 bandwidth study).

Measured on v5e (r4 kernel probes, experiments/kernel_probe*.py, all
numbers same-run-calibrated against a one-X-read stream probe):

- f32 tiles, margins via a [tile, d]@[d, 1] MXU dot and gradient via a
  [1, tile]@[tile, d] MXU dot: ~1.1x the same-run stream-probe rate per
  eval (740-757 GB/s actual; the XLA-matvec stream probe slightly
  UNDERESTIMATES achievable bandwidth) — vs the autodiff path's ~0.55x
  (two X passes, each at bandwidth). Net ~2.0x per eval.
- bf16 tiles (VPU cast + lane/sublane reductions at tile 2048; bf16
  MXU-dot variants either crash the Mosaic compiler or run slower):
  ~1.3x the f32 one-pass rate — another ~1.17x over the f32 kernel,
  ~2.4x over the f32 autodiff default, at half the HBM footprint.
- The r3 kernel measured 0.45-0.49x stream. Root cause (kernel_probe5/6
  bisect): its three separate [tile, 1] label/offset/weight inputs each
  cost ~0.07 ms/eval in narrow DMAs — more than the entire X stream.
  This rewrite packs them into ONE [tile, 3] block and moves both
  matvecs onto the MXU for f32.

Accumulator outputs (value, gradient, Σr) map to the same block every grid
step, making them sequential accumulators (TPU grids are serialized),
initialized at step 0. Padding rows carry weight 0 and padded feature /
coefficient columns are 0, so they contribute nothing.

Falls back to interpreter mode off-TPU, so the same code path is testable
on CPU (the guide's `interpret=True`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # TPU-specific namespace; absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from photon_ml_tpu.data.batch import LabeledPointBatch
from photon_ml_tpu.ops.losses import PointwiseLoss

Array = jax.Array

_LANE = 128  # TPU lane width: last dim of every tile
_VMEM_BUDGET_BYTES = 8 * 1024 * 1024  # target VMEM footprint for the X tile


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _row_tile(d_pad: int, itemsize: int) -> int:
    """Rows per grid step: measured optima with the packed-aux layout
    (1024 f32 / 2048 bf16 at d=512, kernel_probe7), shrunk to fit the VMEM
    budget for very wide feature blocks."""
    cap = 1024 if itemsize >= 4 else 2048
    rows = _VMEM_BUDGET_BYTES // (itemsize * d_pad)
    return int(np.clip(_round_up(rows, 8) if rows >= 8 else 8, 8, cap))


def _kernel(loss: PointwiseLoss, use_mxu: bool, x_ref, aux_ref,
            w_ref, val_ref, grad_ref, rsum_ref):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        val_ref[0, 0] = jnp.float32(0.0)
        rsum_ref[0, 0] = jnp.float32(0.0)
        grad_ref[:] = jnp.zeros_like(grad_ref)

    x = x_ref[:]  # [tile, d_pad], f32 or bf16
    w = w_ref[:]  # [1, d_pad], f32
    # per-sample columns ride as ONE [tile, 3] block (labels | offsets |
    # weights): three separate [tile, 1] inputs cost ~0.07 ms/eval EACH in
    # narrow DMAs — packing them removed the entire gap to stream rate
    # (kernel_probe5/6 logs: 0.79 -> 0.36 ms/eval)
    aux = aux_ref[:]
    y, o, ws = aux[:, 0:1], aux[:, 1:2], aux[:, 2:3]
    if use_mxu:
        # f32 tiles: both matvecs ride the MXU ([tile,d]@[d,1] margins,
        # [1,tile]@[tile,d] gradient) — measured ~1.4x the VPU reductions
        margins = jnp.dot(x, w.reshape(-1, 1),
                          preferred_element_type=jnp.float32)
    else:
        # bf16 tiles: every MXU-dot shape crashes the Mosaic compiler
        # (kernel_probe2/3 logs); VPU cast + lane reduction still nets
        # ~1.8x from the halved bytes
        margins = jnp.sum(x.astype(jnp.float32) * w, axis=1, keepdims=True)
    margins = margins + o
    l, dz = loss.loss_and_dz(margins, y)
    r = ws * dz  # [tile, 1] f32
    val_ref[0, 0] += jnp.sum(ws * l)
    # Σr feeds the normalized-space chain rule (grad shift term) for free
    rsum_ref[0, 0] += jnp.sum(r)
    if use_mxu:
        g = jax.lax.dot_general(
            r, x, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        g = jnp.sum(r * x.astype(jnp.float32), axis=0, keepdims=True)
    grad_ref[:] = grad_ref[:] + g


@functools.partial(jax.jit, static_argnums=(0, 3))
def _fused_padded(loss: PointwiseLoss, x, aux, interpret: bool, w):
    n_pad, d_pad = x.shape
    tile = _row_tile(d_pad, x.dtype.itemsize)
    grid = (n_pad // tile,)
    use_mxu = x.dtype == jnp.float32

    vmem = dict(memory_space=pltpu.VMEM) if (_HAS_PLTPU and not interpret) else {}
    smem = dict(memory_space=pltpu.SMEM) if (_HAS_PLTPU and not interpret) else {}
    value, grad, rsum = pl.pallas_call(
        functools.partial(_kernel, loss, use_mxu),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, d_pad), lambda i: (i, 0), **vmem),
            pl.BlockSpec((tile, 3), lambda i: (i, 0), **vmem),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0), **vmem),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), **smem),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0), **vmem),
            pl.BlockSpec((1, 1), lambda i: (0, 0), **smem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(x, aux, w.reshape(1, d_pad))
    return value[0, 0], grad[0], rsum[0, 0]


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


def fused_value_and_gradient(
    loss: PointwiseLoss,
    coefficients: Array,
    batch: LabeledPointBatch,
    *,
    l2_weight: float = 0.0,
    normalization=None,
    interpret: bool | None = None,
) -> tuple[Array, Array]:
    """Fused (value, gradient) of the weighted GLM objective.

    Numerically equivalent to ``jax.value_and_grad`` of GLMObjective.value,
    including the normalization algebra (effective coefficients + margin
    shift, ValueAndGradientAggregator.scala:36-49): the kernel streams X once
    with ``eff = factors*w`` and a shifted offset column, and the chain rule
    back to ``w`` uses the kernel's Σr output —
    ``grad_w = factors * (X'r - (Σr)*shifts)``. Use inside jit.

    bf16 feature blocks stream as bf16 (half the HBM traffic) with all
    accumulation in f32; coefficients/value/gradient stay f32 throughout.
    Inputs of any shape are zero-padded to (tile-multiple rows, 128m cols);
    padded rows get weight 0 and padded columns 0 coefficients,
    contributing nothing.
    """
    if interpret is None:
        interpret = _should_interpret()
    x = batch.features
    if x.dtype not in (jnp.float32, jnp.bfloat16):
        x = jnp.asarray(x, jnp.float32)
    n, d = x.shape
    tile = _row_tile(_round_up(d, _LANE), x.dtype.itemsize)
    n_pad, d_pad = _round_up(max(n, 1), tile), _round_up(d, _LANE)
    x = jnp.pad(x, ((0, n_pad - n), (0, d_pad - d)))
    factors = shifts = None
    if normalization is not None:
        factors, shifts = normalization.factors, normalization.shifts
    eff = jnp.asarray(coefficients, jnp.float32)
    if factors is not None:
        eff = eff * jnp.asarray(factors, jnp.float32)
    offsets = jnp.asarray(batch.offsets, jnp.float32)
    if shifts is not None:
        offsets = offsets - jnp.dot(eff, jnp.asarray(shifts, jnp.float32))
    w = jnp.pad(eff, (0, d_pad - d))
    aux = jnp.stack([
        jnp.asarray(batch.labels, jnp.float32),
        offsets,
        jnp.asarray(batch.weights, jnp.float32),
    ], axis=1)
    aux = jnp.pad(aux, ((0, n_pad - n), (0, 0)))
    value, grad, rsum = _fused_padded(loss, x, aux, bool(interpret), w)
    grad = grad[:d]
    if shifts is not None:
        grad = grad - rsum * jnp.asarray(shifts, jnp.float32)
    if factors is not None:
        grad = grad * jnp.asarray(factors, jnp.float32)
    grad = grad.astype(coefficients.dtype)
    if l2_weight > 0.0:
        value = value + 0.5 * l2_weight * jnp.vdot(coefficients, coefficients)
        grad = grad + l2_weight * coefficients
    return value.astype(coefficients.dtype), grad
