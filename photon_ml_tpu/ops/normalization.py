"""Feature normalization folded into the objective algebra.

The reference never rewrites the data: normalized margins are computed as

    x' = (x - shift) * factor
    margin = w . x' = (w * factor) . x  -  (w * factor) . shift

so the data stays raw/sparse and normalization is two elementwise ops on the
coefficient vector (reference: photon-lib
function/glm/ValueAndGradientAggregator.scala:36-49 — effectiveCoefficients +
marginShift — and normalization/NormalizationContext.scala).

On TPU this matters for the same reason: the feature matrix is the big
operand living in HBM; transforming coefficients instead of data keeps the
hot matmul untouched and lets XLA fuse the elementwise ops into it.

The intercept coordinate is exempt from shift/factor (factor=1, shift=0), so
that standardization does not destroy the intercept semantics
(reference NormalizationContext builder).
"""

from __future__ import annotations

import enum
import weakref

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

#: host-side copies of context factor vectors, fetched once per context —
#: build-time consumers (entity-block pre-normalization) would otherwise
#: re-pull a [dim] device array through the transfer path on every dataset
#: build / prepare call (at giant d_re that is a ~GiB device-to-host copy)
_HOST_FACTOR_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def host_factors(ctx: "NormalizationContext") -> "np.ndarray | None":
    """Cached numpy view of ``ctx.factors`` (None when identity)."""
    if ctx.factors is None:
        return None
    try:
        return _HOST_FACTOR_CACHE[ctx]
    except (KeyError, TypeError):
        pass
    arr = np.asarray(ctx.factors)
    try:
        _HOST_FACTOR_CACHE[ctx] = arr
    except TypeError:  # unhashable/non-weakrefable context
        pass
    return arr


def host_shifts(ctx: "NormalizationContext") -> "np.ndarray | None":
    if ctx.shifts is None:
        return None
    return np.asarray(ctx.shifts)


class NormalizationType(enum.Enum):
    """Reference: photon-lib normalization/NormalizationType.scala:26-41."""

    NONE = "NONE"
    SCALE_WITH_STANDARD_DEVIATION = "SCALE_WITH_STANDARD_DEVIATION"
    SCALE_WITH_MAX_MAGNITUDE = "SCALE_WITH_MAX_MAGNITUDE"
    STANDARDIZATION = "STANDARDIZATION"


@flax.struct.dataclass
class NormalizationContext:
    """Per-feature-shard normalization factors and shifts.

    ``factors`` / ``shifts`` are [dim] arrays or None (identity). A pytree, so
    it can be closed over or passed through jit boundaries freely.
    """

    factors: Array | None = None
    shifts: Array | None = None

    @property
    def is_identity(self) -> bool:
        return self.factors is None and self.shifts is None

    def effective_coefficients(self, coefficients: Array) -> Array:
        if self.factors is None:
            return coefficients
        return coefficients * self.factors

    def margin_shift(self, effective_coefficients: Array) -> Array:
        """The constant subtracted from every margin: (w*factor) . shift."""
        if self.shifts is None:
            return jnp.zeros((), dtype=effective_coefficients.dtype)
        return jnp.dot(effective_coefficients, self.shifts)

    def to_model_space(self, coefficients: Array, intercept_index: int | None = None) -> Array:
        """Map coefficients trained in normalized space to original space.

        Training minimizes L(w') over x' = (x - shift)*factor, i.e. margins
        are X @ (w'*factor) - (w'*factor).shift. The equivalent original-space
        model is w = w'*factor with the constant -(w'*factor).shift absorbed
        into the intercept (whose factor is 1 and shift is 0). Models are
        always persisted/scored in original space, so scoring needs no
        normalization context (reference NormalizationContext
        modelToOriginalSpace). Batched over leading axes.
        """
        if self.is_identity:
            return coefficients
        eff = coefficients * self.factors if self.factors is not None else coefficients
        if self.shifts is not None:
            if intercept_index is None:
                raise ValueError(
                    "Normalization with shifts (STANDARDIZATION) requires an "
                    "intercept column to absorb the margin shift"
                )
            shift_total = eff @ self.shifts
            eff = eff.at[..., intercept_index].add(-shift_total)
        return eff

    def from_model_space(self, coefficients: Array, intercept_index: int | None = None) -> Array:
        """Inverse of ``to_model_space`` — used to warm-start a solver in
        normalized space from a persisted original-space model."""
        if self.is_identity:
            return coefficients
        w = coefficients
        if self.shifts is not None:
            if intercept_index is None:
                raise ValueError(
                    "Normalization with shifts (STANDARDIZATION) requires an "
                    "intercept column to absorb the margin shift"
                )
            # eff_j = w_j for j != intercept (since shift_int = 0), so the
            # intercept recovers as w_int + sum_j w_j * shift_j.
            shift_total = w @ self.shifts
            w = w.at[..., intercept_index].add(shift_total)
        if self.factors is not None:
            w = w / self.factors
        return w

    def variances_to_model_space(self, variances: Array) -> Array:
        """Diagonal-approximation variance scaling: var(w_i) = var(w'_i)·f_i²
        (ignores intercept covariance terms)."""
        if self.factors is None:
            return variances
        return variances * self.factors * self.factors

    # -- compact ([E, K] active-column) table variants -----------------------
    # Compact (giant-d_re) coordinates store per-entity tables over
    # active_cols [E, K] (pad = dim); the context's [dim] factor vector is
    # gathered per slot. SCALE-only: mean shifts would densify a sparse
    # shard, so compact coordinates reject contexts with shifts upstream.

    def _compact_factors(self, active_cols: Array) -> Array:
        fac = jnp.concatenate(
            [self.factors, jnp.ones((1,), self.factors.dtype)]
        )  # pad slot (col == dim) keeps factor 1
        return fac[jnp.minimum(active_cols, self.factors.shape[0])]

    def _check_compact(self):
        if self.shifts is not None:
            raise ValueError(
                "compact (sparse-shard) coordinates support SCALE-only "
                "normalization; mean shifts (STANDARDIZATION) would densify "
                "the feature space"
            )

    def to_model_space_compact(self, table: Array, active_cols: Array) -> Array:
        self._check_compact()
        if self.factors is None:
            return table
        return table * self._compact_factors(active_cols)

    def from_model_space_compact(self, table: Array, active_cols: Array) -> Array:
        self._check_compact()
        if self.factors is None:
            return table
        return table / self._compact_factors(active_cols)

    def variances_to_model_space_compact(self, variances: Array,
                                         active_cols: Array) -> Array:
        self._check_compact()
        if self.factors is None:
            return variances
        f = self._compact_factors(active_cols)
        return variances * f * f


_NO_NORMALIZATION = NormalizationContext(factors=None, shifts=None)


def no_normalization() -> NormalizationContext:
    """Identity context (singleton, so identity-keyed jit caches stay warm)."""
    return _NO_NORMALIZATION


def build_normalization(
    norm_type: NormalizationType,
    *,
    mean: Array,
    variance: Array,
    max_magnitude: Array,
    intercept_index: int | None = None,
) -> NormalizationContext:
    """Build a NormalizationContext from feature summary statistics.

    Reference: NormalizationContext.apply over BasicStatisticalSummary, per
    NormalizationType {SCALE_WITH_STANDARD_DEVIATION, SCALE_WITH_MAX_MAGNITUDE,
    STANDARDIZATION, NONE}. Zero std / zero magnitude features get factor 1 so
    constant columns are left alone instead of exploding.
    """
    if norm_type == NormalizationType.NONE:
        return no_normalization()

    std = jnp.sqrt(variance)
    inv_std = jnp.where(std > 0.0, 1.0 / jnp.maximum(std, 1e-30), 1.0)
    inv_mag = jnp.where(
        max_magnitude > 0.0, 1.0 / jnp.maximum(max_magnitude, 1e-30), 1.0
    )

    factors: Array | None
    shifts: Array | None
    if norm_type == NormalizationType.SCALE_WITH_STANDARD_DEVIATION:
        factors, shifts = inv_std, None
    elif norm_type == NormalizationType.SCALE_WITH_MAX_MAGNITUDE:
        factors, shifts = inv_mag, None
    elif norm_type == NormalizationType.STANDARDIZATION:
        factors, shifts = inv_std, mean
    else:  # pragma: no cover
        raise ValueError(f"Unknown normalization type {norm_type}")

    if intercept_index is not None:
        if factors is not None:
            factors = factors.at[intercept_index].set(1.0)
        if shifts is not None:
            shifts = shifts.at[intercept_index].set(0.0)
    return NormalizationContext(factors=factors, shifts=shifts)
