"""Fitting (learning-curve) diagnostic.

Reference parity: photon-diagnostics diagnostics/fitting/
FittingDiagnostic.scala:1-131 — train on growing portions of the data,
record train and held-out metrics per portion; diverging curves indicate
over/under-fitting.

TPU-native: portions are weight masks over the fixed-shape batch (prefix of
a stable shuffled order), so every portion reuses the compiled solver.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

import numpy as np

from photon_ml_tpu.data.batch import LabeledPointBatch
from photon_ml_tpu.diagnostics.metrics import evaluate_model
from photon_ml_tpu.models.glm import GeneralizedLinearModel

TrainFn = Callable[[LabeledPointBatch], GeneralizedLinearModel]

DEFAULT_PORTIONS = (0.125, 0.25, 0.5, 0.75, 1.0)


@dataclasses.dataclass
class FittingReport:
    portions: list[float]
    train_metrics: list[dict[str, float]]
    test_metrics: list[dict[str, float]]

    def metric_curve(self, metric: str) -> tuple[list[float], list[float], list[float]]:
        """(portion, train, test) series for one metric."""
        return (
            self.portions,
            [m.get(metric, float("nan")) for m in self.train_metrics],
            [m.get(metric, float("nan")) for m in self.test_metrics],
        )


def fitting_diagnostic(
    train_fn: TrainFn,
    batch: LabeledPointBatch,
    validation_batch: LabeledPointBatch,
    *,
    portions: Sequence[float] = DEFAULT_PORTIONS,
    seed: int = 0,
) -> FittingReport:
    rng = np.random.default_rng(seed)
    n = batch.num_samples
    order = rng.permutation(n)
    base_weights = np.asarray(batch.weights)

    train_metrics, test_metrics = [], []
    for portion in portions:
        if not 0.0 < portion <= 1.0:
            raise ValueError(f"portion must be in (0, 1], got {portion}")
        k = max(1, int(round(portion * n)))
        mask = np.zeros(n, dtype=base_weights.dtype)
        mask[order[:k]] = 1.0
        sub = batch.replace(weights=base_weights * mask)
        model = train_fn(sub)
        train_metrics.append(evaluate_model(model, sub))
        test_metrics.append(evaluate_model(model, validation_batch))
    return FittingReport(
        portions=list(portions),
        train_metrics=train_metrics,
        test_metrics=test_metrics,
    )
