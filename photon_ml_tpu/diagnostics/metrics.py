"""Metrics suite: evaluate a GLM on a labeled batch.

Reference parity: photon-diagnostics Evaluation.scala —
``Evaluation.evaluate(model, data)`` returns a MetricsMap with every metric
applicable to the task (RMSE always for regression; AUC/AUPR + losses for
classification), and metric/MetricMetadata.scala's per-metric direction.
"""

from __future__ import annotations

import numpy as np

from photon_ml_tpu.data.batch import LabeledPointBatch
from photon_ml_tpu.evaluation import local_metrics as lm
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.types import TaskType


def evaluate_model(
    model: GeneralizedLinearModel, batch: LabeledPointBatch
) -> dict[str, float]:
    """Compute the task-appropriate metrics map."""
    scores = np.asarray(model.score(batch.features, batch.offsets))
    labels = np.asarray(batch.labels)
    weights = np.asarray(batch.weights)
    task = model.task

    metrics: dict[str, float] = {}
    if task in (TaskType.LOGISTIC_REGRESSION, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM):
        metrics["AUC"] = lm.area_under_roc_curve(scores, labels, weights)
        metrics["AUPR"] = lm.area_under_precision_recall_curve(scores, labels, weights)
        if task == TaskType.LOGISTIC_REGRESSION:
            metrics["LOGISTIC_LOSS"] = lm.logistic_loss(scores, labels, weights)
        else:
            metrics["SMOOTHED_HINGE_LOSS"] = lm.smoothed_hinge_loss(scores, labels, weights)
    elif task == TaskType.POISSON_REGRESSION:
        metrics["POISSON_LOSS"] = lm.poisson_loss(scores, labels, weights)
        metrics["RMSE"] = lm.root_mean_squared_error(np.exp(scores), labels, weights)
    else:
        metrics["RMSE"] = lm.root_mean_squared_error(scores, labels, weights)
        metrics["MAE"] = lm.mean_absolute_error(scores, labels, weights)
        metrics["SQUARED_LOSS"] = lm.squared_loss(scores, labels, weights)
    return metrics


#: larger-is-better direction per metric (reference MetricMetadata)
METRIC_DIRECTIONS = {
    "AUC": True,
    "AUPR": True,
    "RMSE": False,
    "MAE": False,
    "SQUARED_LOSS": False,
    "LOGISTIC_LOSS": False,
    "POISSON_LOSS": False,
    "SMOOTHED_HINGE_LOSS": False,
}
