"""Feature importance diagnostics.

Reference parity: photon-diagnostics diagnostics/featureimportance/ —
expected-magnitude importance (|w_j|·E|x_j|: contribution scale of the
feature to the margin) and variance-based importance (w_j²·Var[x_j]:
contribution to margin variance), ranked descending.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from photon_ml_tpu.data.batch import LabeledPointBatch
from photon_ml_tpu.io.index_map import IndexMap
from photon_ml_tpu.models.glm import GeneralizedLinearModel


@dataclasses.dataclass(frozen=True)
class FeatureImportance:
    index: int
    name: str
    importance: float


@dataclasses.dataclass
class FeatureImportanceReport:
    kind: str  # "expected_magnitude" | "variance"
    ranked: list[FeatureImportance]

    def top(self, k: int) -> list[FeatureImportance]:
        return self.ranked[:k]


def feature_importance(
    model: GeneralizedLinearModel,
    batch: LabeledPointBatch,
    *,
    kind: str = "expected_magnitude",
    index_map: IndexMap | None = None,
) -> FeatureImportanceReport:
    w = np.asarray(model.coefficients.means, dtype=np.float64)
    x = np.asarray(batch.features, dtype=np.float64)
    sw = np.asarray(batch.weights, dtype=np.float64)
    total = sw.sum()
    if kind == "expected_magnitude":
        scores = np.abs(w) * (sw @ np.abs(x)) / total
    elif kind == "variance":
        mean = (sw @ x) / total
        var = (sw @ (x - mean) ** 2) / total
        scores = w**2 * var
    else:
        raise ValueError(f"unknown importance kind {kind!r}")

    order = np.argsort(-scores)
    ranked = [
        FeatureImportance(
            index=int(j),
            name=(index_map.get_feature_name(int(j)) or str(j)) if index_map else str(j),
            importance=float(scores[j]),
        )
        for j in order
    ]
    return FeatureImportanceReport(kind=kind, ranked=ranked)
