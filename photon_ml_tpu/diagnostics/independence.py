"""Prediction-error independence analysis (Kendall tau).

Reference parity: photon-diagnostics diagnostics/independence/ — rank
correlation between prediction errors and predictions; significant
correlation indicates structure the model missed.
"""

from __future__ import annotations

import dataclasses

import numpy as np
from scipy.stats import kendalltau


@dataclasses.dataclass
class IndependenceReport:
    tau: float
    p_value: float
    num_samples: int

    @property
    def independent(self) -> bool:
        """p > 0.05: no evidence of dependence."""
        return self.p_value > 0.05


def kendall_tau_independence(
    scores: np.ndarray,
    labels: np.ndarray,
    *,
    max_samples: int = 5000,
    seed: int = 0,
) -> IndependenceReport:
    """Kendall tau between predictions and their errors. Subsampled above
    ``max_samples`` (tau is O(n²) pairs; the reference subsamples too)."""
    scores = np.asarray(scores, dtype=np.float64).ravel()
    labels = np.asarray(labels, dtype=np.float64).ravel()
    n = len(scores)
    if n > max_samples:
        sel = np.random.default_rng(seed).choice(n, size=max_samples, replace=False)
        scores, labels = scores[sel], labels[sel]
    errors = labels - scores
    tau, p = kendalltau(scores, errors)
    return IndependenceReport(
        tau=float(tau), p_value=float(p), num_samples=len(scores)
    )
