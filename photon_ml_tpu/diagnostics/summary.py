"""Per-coefficient distribution summary.

Reference parity: photon-diagnostics supervised/model/CoefficientSummary.scala
— tracks min/max/mean/std and quartile estimates of one coefficient across
bootstrap retrains.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class CoefficientSummary:
    min: float
    q1: float
    median: float
    q3: float
    max: float
    mean: float
    std: float

    @classmethod
    def from_samples(cls, samples: np.ndarray) -> "CoefficientSummary":
        samples = np.asarray(samples, dtype=np.float64)
        q1, med, q3 = np.percentile(samples, [25, 50, 75])
        return cls(
            min=float(samples.min()),
            q1=float(q1),
            median=float(med),
            q3=float(q3),
            max=float(samples.max()),
            mean=float(samples.mean()),
            std=float(samples.std()),
        )

    def straddles_zero(self) -> bool:
        """True if the IQR contains 0 — the bootstrap instability signal the
        reference's report flags."""
        return self.q1 <= 0.0 <= self.q3
