"""Diagnostic report tree + HTML/text renderers.

Reference parity: photon-diagnostics diagnostics/reporting/ — a logical
report tree (chapters -> sections -> items) transformed to a physical
rendering; HTMLRenderStrategy renders to HTML, text renderers to plain text
(plots in the reference use xchart; here tables and inline SVG line charts,
no external deps).
"""

from __future__ import annotations

import dataclasses
import html
from typing import Sequence


@dataclasses.dataclass
class Text:
    body: str


@dataclasses.dataclass
class Table:
    headers: Sequence[str]
    rows: Sequence[Sequence[object]]
    caption: str = ""


@dataclasses.dataclass
class LineChart:
    """Simple multi-series line chart rendered as inline SVG."""

    title: str
    x: Sequence[float]
    series: dict[str, Sequence[float]]
    x_label: str = ""
    y_label: str = ""


Item = Text | Table | LineChart


@dataclasses.dataclass
class Section:
    title: str
    items: list[Item] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Chapter:
    title: str
    sections: list[Section] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Report:
    title: str
    chapters: list[Chapter] = dataclasses.field(default_factory=list)


# --- text rendering ---------------------------------------------------------


def render_text(report: Report) -> str:
    out = [report.title, "=" * len(report.title), ""]
    for ci, chapter in enumerate(report.chapters, 1):
        out += [f"{ci}. {chapter.title}", "-" * (len(chapter.title) + 4), ""]
        for si, section in enumerate(chapter.sections, 1):
            out.append(f"{ci}.{si} {section.title}")
            for item in section.items:
                if isinstance(item, Text):
                    out.append("  " + item.body)
                elif isinstance(item, Table):
                    if item.caption:
                        out.append(f"  [{item.caption}]")
                    widths = [
                        max(len(str(h)), *(len(_fmt(r[i])) for r in item.rows))
                        if item.rows
                        else len(str(h))
                        for i, h in enumerate(item.headers)
                    ]
                    out.append(
                        "  " + " | ".join(str(h).ljust(w) for h, w in zip(item.headers, widths))
                    )
                    for row in item.rows:
                        out.append(
                            "  " + " | ".join(_fmt(v).ljust(w) for v, w in zip(row, widths))
                        )
                elif isinstance(item, LineChart):
                    out.append(f"  [chart: {item.title} — series {list(item.series)}]")
            out.append("")
    return "\n".join(out)


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


# --- HTML rendering ---------------------------------------------------------

_CSS = """
body { font-family: sans-serif; margin: 2em; color: #222; }
h1 { border-bottom: 2px solid #444; }
h2 { border-bottom: 1px solid #999; margin-top: 1.5em; }
table { border-collapse: collapse; margin: 0.8em 0; }
th, td { border: 1px solid #bbb; padding: 4px 10px; text-align: right; }
th { background: #eee; }
caption { caption-side: top; font-style: italic; text-align: left; }
svg { background: #fafafa; border: 1px solid #ddd; margin: 0.8em 0; }
"""

_PALETTE = ("#1f77b4", "#d62728", "#2ca02c", "#9467bd", "#ff7f0e")


def _svg_chart(chart: LineChart, width: int = 560, height: int = 320) -> str:
    pad = 48
    xs = list(chart.x)
    all_y = [y for series in chart.series.values() for y in series if y == y]
    if not xs or not all_y:
        return "<p>(empty chart)</p>"
    x_min, x_max = min(xs), max(xs)
    y_min, y_max = min(all_y), max(all_y)
    if x_max == x_min:
        x_max = x_min + 1.0
    if y_max == y_min:
        y_max = y_min + 1.0

    def sx(x):
        return pad + (x - x_min) / (x_max - x_min) * (width - 2 * pad)

    def sy(y):
        return height - pad - (y - y_min) / (y_max - y_min) * (height - 2 * pad)

    parts = [
        f'<svg width="{width}" height="{height}" role="img" aria-label="{html.escape(chart.title)}">',
        f'<text x="{width/2:.0f}" y="18" text-anchor="middle" font-weight="bold">{html.escape(chart.title)}</text>',
        f'<line x1="{pad}" y1="{height-pad}" x2="{width-pad}" y2="{height-pad}" stroke="#333"/>',
        f'<line x1="{pad}" y1="{pad}" x2="{pad}" y2="{height-pad}" stroke="#333"/>',
        f'<text x="{width/2:.0f}" y="{height-8}" text-anchor="middle" font-size="11">{html.escape(chart.x_label)}</text>',
        f'<text x="14" y="{height/2:.0f}" text-anchor="middle" font-size="11" transform="rotate(-90 14 {height/2:.0f})">{html.escape(chart.y_label)}</text>',
        f'<text x="{pad}" y="{height-pad+14}" font-size="10" text-anchor="middle">{x_min:.3g}</text>',
        f'<text x="{width-pad}" y="{height-pad+14}" font-size="10" text-anchor="middle">{x_max:.3g}</text>',
        f'<text x="{pad-4}" y="{height-pad}" font-size="10" text-anchor="end">{y_min:.3g}</text>',
        f'<text x="{pad-4}" y="{pad+4}" font-size="10" text-anchor="end">{y_max:.3g}</text>',
    ]
    for i, (name, ys) in enumerate(chart.series.items()):
        color = _PALETTE[i % len(_PALETTE)]
        points = " ".join(
            f"{sx(x):.1f},{sy(y):.1f}" for x, y in zip(xs, ys) if y == y
        )
        parts.append(
            f'<polyline fill="none" stroke="{color}" stroke-width="2" points="{points}"/>'
        )
        parts.append(
            f'<text x="{width-pad+6}" y="{pad + 16*i}" font-size="11" fill="{color}">{html.escape(name)}</text>'
        )
    parts.append("</svg>")
    return "".join(parts)


def render_html(report: Report) -> str:
    out = [
        "<!DOCTYPE html><html><head><meta charset='utf-8'>",
        f"<title>{html.escape(report.title)}</title>",
        f"<style>{_CSS}</style></head><body>",
        f"<h1>{html.escape(report.title)}</h1>",
    ]
    for chapter in report.chapters:
        out.append(f"<h2>{html.escape(chapter.title)}</h2>")
        for section in chapter.sections:
            out.append(f"<h3>{html.escape(section.title)}</h3>")
            for item in section.items:
                if isinstance(item, Text):
                    out.append(f"<p>{html.escape(item.body)}</p>")
                elif isinstance(item, Table):
                    out.append("<table>")
                    if item.caption:
                        out.append(f"<caption>{html.escape(item.caption)}</caption>")
                    out.append(
                        "<tr>" + "".join(f"<th>{html.escape(str(h))}</th>" for h in item.headers) + "</tr>"
                    )
                    for row in item.rows:
                        out.append(
                            "<tr>" + "".join(f"<td>{html.escape(_fmt(v))}</td>" for v in row) + "</tr>"
                        )
                    out.append("</table>")
                elif isinstance(item, LineChart):
                    out.append(_svg_chart(item))
    out.append("</body></html>")
    return "".join(out)
