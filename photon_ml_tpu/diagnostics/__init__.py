"""Model diagnostics (reference photon-diagnostics module)."""

from photon_ml_tpu.diagnostics.bootstrap import BootstrapReport, bootstrap_training
from photon_ml_tpu.diagnostics.feature_importance import (
    FeatureImportanceReport,
    feature_importance,
)
from photon_ml_tpu.diagnostics.fitting import FittingReport, fitting_diagnostic
from photon_ml_tpu.diagnostics.hosmer_lemeshow import (
    HosmerLemeshowReport,
    hosmer_lemeshow,
)
from photon_ml_tpu.diagnostics.independence import (
    IndependenceReport,
    kendall_tau_independence,
)
from photon_ml_tpu.diagnostics.metrics import evaluate_model
from photon_ml_tpu.diagnostics.summary import CoefficientSummary

__all__ = [
    "BootstrapReport",
    "bootstrap_training",
    "FeatureImportanceReport",
    "feature_importance",
    "FittingReport",
    "fitting_diagnostic",
    "HosmerLemeshowReport",
    "hosmer_lemeshow",
    "IndependenceReport",
    "kendall_tau_independence",
    "evaluate_model",
    "CoefficientSummary",
]
