"""Assemble diagnostics into the full report tree.

Reference parity: the legacy Driver's DIAGNOSED stage (photon-client
Driver.scala:608-635, 719-739) — per-λ model metrics, fitting curves,
bootstrap tables, Hosmer-Lemeshow (logistic only), Kendall-tau independence,
feature importance — rendered by diagnostics/reporting to HTML.
"""

from __future__ import annotations

from typing import Callable, Mapping

import numpy as np

from photon_ml_tpu.data.batch import LabeledPointBatch
from photon_ml_tpu.diagnostics.bootstrap import bootstrap_training
from photon_ml_tpu.diagnostics.feature_importance import feature_importance
from photon_ml_tpu.diagnostics.fitting import fitting_diagnostic
from photon_ml_tpu.diagnostics.hosmer_lemeshow import hosmer_lemeshow
from photon_ml_tpu.diagnostics.independence import kendall_tau_independence
from photon_ml_tpu.diagnostics.metrics import evaluate_model
from photon_ml_tpu.diagnostics.reporting import (
    Chapter,
    LineChart,
    Report,
    Section,
    Table,
    Text,
)
from photon_ml_tpu.io.index_map import IndexMap
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.types import TaskType


def build_diagnostic_report(
    models: Mapping[float, GeneralizedLinearModel],
    train_batch: LabeledPointBatch,
    validation_batch: LabeledPointBatch,
    *,
    task: TaskType,
    train_fn_for_lambda: Callable[[float], Callable[[LabeledPointBatch], GeneralizedLinearModel]],
    best_lambda: float,
    index_map: IndexMap | None = None,
    num_bootstraps: int = 0,
    seed: int = 0,
    validation_metrics: Mapping[float, Mapping[str, float]] | None = None,
) -> Report:
    """Build the model-diagnostics report over a λ grid of trained models.

    ``train_fn_for_lambda(lam)`` returns a retraining closure used by the
    bootstrap and fitting diagnostics (so they retrain with the same config).
    ``validation_metrics`` reuses per-λ metrics the caller already computed.
    """
    report = Report(title=f"Photon-ML-TPU model diagnostics ({task.name})")

    # Chapter 1: metrics per λ
    metric_rows = []
    metric_names: list[str] = []
    for lam, model in sorted(models.items()):
        if validation_metrics is not None and lam in validation_metrics:
            metrics = validation_metrics[lam]
        else:
            metrics = evaluate_model(model, validation_batch)
        if not metric_names:
            metric_names = list(metrics)
        metric_rows.append([lam, *(metrics[m] for m in metric_names)])
    report.chapters.append(
        Chapter(
            title="Model summary and metrics",
            sections=[
                Section(
                    title="Validation metrics per regularization weight",
                    items=[
                        Table(headers=["lambda", *metric_names], rows=metric_rows),
                        Text(f"Selected lambda = {best_lambda:g}"),
                    ],
                )
            ],
        )
    )

    best_model = models[best_lambda]
    scores = np.asarray(
        best_model.score(validation_batch.features, validation_batch.offsets)
    )
    # mean-scale predictions (probabilities for logistic, rates for Poisson)
    # so residuals labels - predictions are comparable to the labels
    predictions = np.asarray(best_model.mean(scores))
    labels = np.asarray(validation_batch.labels)
    weights = np.asarray(validation_batch.weights)
    train_fn = train_fn_for_lambda(best_lambda)

    # Chapter 2: fit quality
    fit = fitting_diagnostic(train_fn, train_batch, validation_batch, seed=seed)
    fit_sections = []
    for metric in fit.train_metrics[0]:
        portions, train_curve, test_curve = fit.metric_curve(metric)
        fit_sections.append(
            Section(
                title=f"Learning curve: {metric}",
                items=[
                    LineChart(
                        title=f"{metric} vs training portion",
                        x=portions,
                        series={"train": train_curve, "validation": test_curve},
                        x_label="portion of training data",
                        y_label=metric,
                    )
                ],
            )
        )
    report.chapters.append(Chapter(title="Fitting diagnostic", sections=fit_sections))

    # Chapter 3: calibration + independence
    checks = Chapter(title="Error structure", sections=[])
    if task == TaskType.LOGISTIC_REGRESSION:
        hl = hosmer_lemeshow(scores, labels, weights)
        checks.sections.append(
            Section(
                title="Hosmer-Lemeshow calibration",
                items=[
                    Table(
                        headers=["p lower", "p upper", "count", "observed+", "expected+"],
                        rows=[
                            [b.lower, b.upper, b.count, b.observed_positives, b.expected_positives]
                            for b in hl.bins
                        ],
                        caption=(
                            f"chi²={hl.chi_square:.4g}, dof={hl.degrees_of_freedom}, "
                            f"p={hl.p_value:.4g} "
                            f"({'well calibrated' if hl.well_calibrated else 'MISCALIBRATED'})"
                        ),
                    )
                ],
            )
        )
    if task == TaskType.LINEAR_REGRESSION:
        # Rank correlation of prediction vs residual is only meaningful for
        # continuous residuals: with binary/count outcomes the conditional
        # error distribution is monotone in the prediction by construction,
        # so tau is biased away from 0 even for a perfect model.
        ind = kendall_tau_independence(predictions, labels, seed=seed)
        checks.sections.append(
            Section(
                title="Prediction-error independence (Kendall tau)",
                items=[
                    Text(
                        f"tau={ind.tau:.4g}, p={ind.p_value:.4g} over {ind.num_samples} "
                        f"samples ({'independent' if ind.independent else 'DEPENDENT'})"
                    )
                ],
            )
        )
    report.chapters.append(checks)

    # Chapter 4: feature importance
    imp = feature_importance(
        best_model, train_batch, kind="expected_magnitude", index_map=index_map
    )
    var_imp = feature_importance(
        best_model, train_batch, kind="variance", index_map=index_map
    )
    report.chapters.append(
        Chapter(
            title="Feature importance",
            sections=[
                Section(
                    title=f"Top features ({r.kind})",
                    items=[
                        Table(
                            headers=["rank", "feature", "importance"],
                            rows=[
                                [i + 1, fi.name, fi.importance]
                                for i, fi in enumerate(r.top(20))
                            ],
                        )
                    ],
                )
                for r in (imp, var_imp)
            ],
        )
    )

    # Chapter 5: bootstrap (optional — expensive)
    if num_bootstraps >= 2:
        boot = bootstrap_training(
            train_fn,
            train_batch,
            validation_batch,
            num_bootstraps=num_bootstraps,
            seed=seed,
        )
        unstable = boot.unstable_coefficients
        report.chapters.append(
            Chapter(
                title="Bootstrap analysis",
                sections=[
                    Section(
                        title="Metric distributions",
                        items=[
                            Table(
                                headers=["metric", "min", "q1", "median", "q3", "max", "mean", "std"],
                                rows=[
                                    [m, s.min, s.q1, s.median, s.q3, s.max, s.mean, s.std]
                                    for m, s in boot.metric_distributions.items()
                                ],
                            )
                        ],
                    ),
                    Section(
                        title="Coefficient stability",
                        items=[
                            Text(
                                f"{len(unstable)} of {len(boot.coefficient_summaries)} "
                                "coefficients have an IQR straddling zero"
                            ),
                            Table(
                                headers=["coefficient", "feature", "q1", "median", "q3"],
                                rows=[
                                    [
                                        j,
                                        (index_map.get_feature_name(j) or str(j))
                                        if index_map
                                        else str(j),
                                        boot.coefficient_summaries[j].q1,
                                        boot.coefficient_summaries[j].median,
                                        boot.coefficient_summaries[j].q3,
                                    ]
                                    for j in unstable[:20]
                                ],
                                caption="unstable coefficients (first 20)",
                            ),
                        ],
                    ),
                ],
            )
        )
    return report
