"""Hosmer-Lemeshow calibration test for logistic models.

Reference parity: photon-diagnostics diagnostics/hl/ — bin scored samples by
predicted probability into deciles, compare observed vs expected positives
per bin, chi-square statistic with (bins - 2) degrees of freedom, plus the
per-bin table the HTML report renders.
"""

from __future__ import annotations

import dataclasses

import numpy as np
from scipy.stats import chi2


@dataclasses.dataclass(frozen=True)
class HosmerLemeshowBin:
    lower: float
    upper: float
    count: float
    observed_positives: float
    expected_positives: float


@dataclasses.dataclass
class HosmerLemeshowReport:
    bins: list[HosmerLemeshowBin]
    chi_square: float
    degrees_of_freedom: int
    p_value: float

    @property
    def well_calibrated(self) -> bool:
        """p > 0.05: no evidence of miscalibration."""
        return self.p_value > 0.05


def hosmer_lemeshow(
    scores: np.ndarray,
    labels: np.ndarray,
    weights: np.ndarray | None = None,
    *,
    num_bins: int = 10,
    scores_are_probabilities: bool = False,
) -> HosmerLemeshowReport:
    """HL test. ``scores`` are margins unless ``scores_are_probabilities``."""
    scores = np.asarray(scores, dtype=np.float64).ravel()
    labels = np.asarray(labels, dtype=np.float64).ravel()
    weights = (
        np.ones_like(scores) if weights is None else np.asarray(weights, np.float64)
    )
    probs = scores if scores_are_probabilities else 1.0 / (1.0 + np.exp(-scores))

    # equal-count (decile) bin edges on the predicted probabilities
    quantiles = np.quantile(probs, np.linspace(0.0, 1.0, num_bins + 1))
    quantiles[0], quantiles[-1] = 0.0, 1.0
    edges = np.unique(quantiles)
    bin_idx = np.clip(np.searchsorted(edges, probs, side="right") - 1, 0, len(edges) - 2)

    bins = []
    chi_sq = 0.0
    for b in range(len(edges) - 1):
        sel = bin_idx == b
        w = weights[sel]
        count = float(w.sum())
        observed = float((w * labels[sel]).sum())
        expected = float((w * probs[sel]).sum())
        bins.append(
            HosmerLemeshowBin(
                lower=float(edges[b]),
                upper=float(edges[b + 1]),
                count=count,
                observed_positives=observed,
                expected_positives=expected,
            )
        )
        if count > 0:
            variance = max(expected * (1.0 - expected / count), 1e-12)
            chi_sq += (observed - expected) ** 2 / variance

    dof = max(len(bins) - 2, 1)
    return HosmerLemeshowReport(
        bins=bins,
        chi_square=chi_sq,
        degrees_of_freedom=dof,
        p_value=float(chi2.sf(chi_sq, dof)),
    )
