"""Bootstrap diagnostics: retrain on resamples, aggregate distributions.

Reference parity: photon-diagnostics BootstrapTraining.scala — k
sample-with-replacement retrains; aggregates per-coefficient distributions
(CoefficientSummary) and per-metric distributions; bootstrap report
(diagnostics/bootstrap/BootstrapReport.scala).

TPU-native: resampling is a weight transform — a multinomial draw of counts
over samples becomes the batch's weight vector, so every retrain reuses the
same compiled solver on identically-shaped data (no gather, no recompile).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Mapping

import numpy as np

from photon_ml_tpu.data.batch import LabeledPointBatch
from photon_ml_tpu.diagnostics.metrics import evaluate_model
from photon_ml_tpu.diagnostics.summary import CoefficientSummary
from photon_ml_tpu.models.glm import GeneralizedLinearModel

TrainFn = Callable[[LabeledPointBatch], GeneralizedLinearModel]


@dataclasses.dataclass
class BootstrapReport:
    coefficient_summaries: list[CoefficientSummary]
    metric_distributions: dict[str, CoefficientSummary]
    num_samples: int

    @property
    def unstable_coefficients(self) -> list[int]:
        """Indices whose IQR straddles zero (reference report's
        'coefficients indistinguishable from 0' table)."""
        return [
            j for j, s in enumerate(self.coefficient_summaries) if s.straddles_zero()
        ]


def bootstrap_training(
    train_fn: TrainFn,
    batch: LabeledPointBatch,
    validation_batch: LabeledPointBatch,
    *,
    num_bootstraps: int = 10,
    seed: int = 0,
) -> BootstrapReport:
    """Run ``num_bootstraps`` weighted-resample retrains."""
    if num_bootstraps < 2:
        raise ValueError("need at least 2 bootstrap rounds")
    rng = np.random.default_rng(seed)
    n = batch.num_samples
    base_weights = np.asarray(batch.weights)

    coeffs = []
    metric_rows: list[Mapping[str, float]] = []
    for _ in range(num_bootstraps):
        counts = rng.multinomial(n, np.full(n, 1.0 / n))
        resampled = batch.replace(
            weights=(base_weights * counts).astype(base_weights.dtype)
        )
        model = train_fn(resampled)
        coeffs.append(np.asarray(model.coefficients.means))
        metric_rows.append(evaluate_model(model, validation_batch))

    coeff_matrix = np.stack(coeffs)  # [k, d]
    summaries = [
        CoefficientSummary.from_samples(coeff_matrix[:, j])
        for j in range(coeff_matrix.shape[1])
    ]
    metric_dists = {
        name: CoefficientSummary.from_samples(
            np.array([row[name] for row in metric_rows])
        )
        for name in metric_rows[0]
    }
    return BootstrapReport(
        coefficient_summaries=summaries,
        metric_distributions=metric_dists,
        num_samples=num_bootstraps,
    )
