"""Matrix-factorization model: latent factors over two entity axes.

Reference parity: the reference *declares* this model family but never
implements it — only the wire format survives
(photon-avro-schemas/src/main/avro/LatentFactorAvro.avsc: effectId +
latentFactor array) plus dead converter helpers
(photon-client data/avro/AvroUtils.scala:418-445) and the README mention of
a matrix-factorization coordinate (README.md:92-95). This module implements
the capability the schema promises: a GAME coordinate whose score for a
sample is ``dot(row_factor[rowId], col_factor[colId])``, trained on the
coordinate-descent residuals.

TPU-native: both factor tables are dense [num_entities, k] arrays; scoring
is two gathers + a fused row-wise dot, and training (algorithm/
mf_coordinate.py) is alternating minimization where each half-step is the
same vmapped per-entity GLM solve used by random-effect coordinates — the
"features" of a row-entity's local problem are the gathered column factors
of its samples.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.models.game import DatumScoringModel
from photon_ml_tpu.types import TaskType

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class MatrixFactorizationModel(DatumScoringModel):
    """Latent-factor model over a (row entity, col entity) pair.

    row_factors: [num_row_entities, k]
    col_factors: [num_col_entities, k]
    row/col_keys: host-side vocabs, position == row index (same convention
    as RandomEffectModel.entity_keys).
    """

    row_factors: Array
    col_factors: Array
    row_effect_type: str
    col_effect_type: str
    row_keys: np.ndarray
    col_keys: np.ndarray
    task: TaskType

    @property
    def num_latent_factors(self) -> int:
        return int(self.row_factors.shape[1])

    def score_dataset(self, dataset) -> Array:
        row_idx = dataset.entity_indices(self.row_effect_type)
        col_idx = dataset.entity_indices(self.col_effect_type)
        return score_matrix_factorization(
            self.row_factors, self.col_factors, row_idx, col_idx
        )

    def with_factors(
        self, row_factors: Array, col_factors: Array
    ) -> "MatrixFactorizationModel":
        return dataclasses.replace(
            self, row_factors=row_factors, col_factors=col_factors
        )


def score_matrix_factorization(
    row_factors: Array, col_factors: Array, row_idx: Array, col_idx: Array
) -> Array:
    """scores_i = row_factors[row_idx_i] . col_factors[col_idx_i].

    Samples whose row OR col entity is unseen (idx < 0) score 0 — the same
    missing-entity semantics as RandomEffectModel scoring.
    """
    if row_factors.shape[0] == 0 or col_factors.shape[0] == 0:
        # empty factor table: every sample is "unseen" (gathers from empty
        # tables are compile errors)
        return jnp.zeros(row_idx.shape, dtype=row_factors.dtype)
    both = (row_idx >= 0) & (col_idx >= 0)
    rows = row_factors[jnp.maximum(row_idx, 0)]
    cols = col_factors[jnp.maximum(col_idx, 0)]
    scores = jnp.einsum("nk,nk->n", rows, cols)
    return jnp.where(both, scores, 0.0)


def init_factors(
    num_rows: int,
    num_cols: int,
    num_latent: int,
    *,
    seed: int = 0,
    scale: float | None = None,
    dtype=jnp.float32,
) -> tuple[Array, Array]:
    """Seeded small-random factor init.

    Zeros are a saddle point of the bilinear objective (each side's gradient
    is proportional to the other side's factors), so MF must start off-zero;
    the default scale keeps initial scores O(scale²).
    """
    if scale is None:
        scale = 1.0 / float(np.sqrt(num_latent))
    # Python-float scale: a numpy scalar would promote float32 tables to
    # float64 under jax_enable_x64.
    scale = float(scale)
    kr, kc = jax.random.split(jax.random.PRNGKey(seed))
    row = scale * jax.random.normal(kr, (num_rows, num_latent), dtype=dtype)
    col = scale * jax.random.normal(kc, (num_cols, num_latent), dtype=dtype)
    return row, col
