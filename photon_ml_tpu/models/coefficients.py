"""Model coefficients: means + optional variances.

Reference parity: photon-lib model/Coefficients.scala — a coefficient vector
with optional per-coefficient variances (from the inverse Hessian diagonal),
persisted as BayesianLinearModelAvro.
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

Array = jax.Array


@flax.struct.dataclass
class Coefficients:
    means: Array
    variances: Array | None = None

    @property
    def dim(self) -> int:
        return self.means.shape[-1]

    def compute_score(self, features: Array) -> Array:
        """Dot product score (reference Coefficients.computeScore)."""
        return features @ self.means

    @classmethod
    def zeros(cls, dim: int, dtype=jnp.float32) -> "Coefficients":
        return cls(means=jnp.zeros((dim,), dtype=dtype))
