"""Generalized linear models: coefficients + link functions per task.

Reference parity: photon-api supervised/model/GeneralizedLinearModel.scala and
subclasses (LogisticRegressionModel, LinearRegressionModel,
PoissonRegressionModel, SmoothedHingeLossLinearSVMModel) with
predictWithOffset and the BinaryClassifier / Regression interfaces.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.types import TaskType

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class GeneralizedLinearModel:
    """A trained GLM for one task type.

    ``score`` is the raw margin x.w (+ offset); ``predict`` applies the mean
    (inverse-link) function of the task.
    """

    coefficients: Coefficients
    task: TaskType

    @property
    def dim(self) -> int:
        return self.coefficients.dim

    def score(self, features: Array, offsets: Array | None = None) -> Array:
        margins = self.coefficients.compute_score(features)
        if offsets is not None:
            margins = margins + offsets
        return margins

    def predict(self, features: Array, offsets: Array | None = None) -> Array:
        margins = self.score(features, offsets)
        return self.mean(margins)

    def mean(self, margins: Array) -> Array:
        t = self.task
        if t == TaskType.LOGISTIC_REGRESSION:
            return jax.nn.sigmoid(margins)
        if t == TaskType.LINEAR_REGRESSION:
            return margins
        if t == TaskType.POISSON_REGRESSION:
            return jnp.exp(margins)
        if t == TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
            # margin sign is the classification; expose the margin itself
            return margins
        raise ValueError(f"No mean function for task {t}")

    def classify(self, features: Array, offsets: Array | None = None, threshold: float = 0.5) -> Array:
        """Binary classification (reference BinaryClassifier.predictClassWithOffset)."""
        if not self.task.is_classification:
            raise ValueError(f"{self.task} is not a classification task")
        if self.task == TaskType.LOGISTIC_REGRESSION:
            return (self.predict(features, offsets) >= threshold).astype(jnp.int32)
        return (self.score(features, offsets) >= 0.0).astype(jnp.int32)

    def with_coefficients(self, coefficients: Coefficients) -> "GeneralizedLinearModel":
        return dataclasses.replace(self, coefficients=coefficients)
