from photon_ml_tpu.models.coefficients import Coefficients  # noqa: F401
from photon_ml_tpu.models.glm import GeneralizedLinearModel  # noqa: F401
from photon_ml_tpu.models.game import (  # noqa: F401
    DatumScoringModel,
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
    score_random_effect,
)
from photon_ml_tpu.models.matrix_factorization import (  # noqa: F401
    MatrixFactorizationModel,
    init_factors,
    score_matrix_factorization,
)
