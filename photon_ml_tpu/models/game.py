"""GAME models: fixed-effect, random-effect, and the composite GameModel.

Reference parity: photon-api model/FixedEffectModel.scala (broadcast GLM +
feature shard id), model/RandomEffectModel.scala (RDD[(REId, GLM)] + RE type
+ shard id, scoring by join), photon-lib model/GameModel.scala (map
CoordinateId -> DatumScoringModel, score = Σ sub-scores, GameModel.scala:101-107;
type consistency check :163-169).

TPU-native: a random-effect model is one dense [num_entities, dim] matrix —
the per-entity GLMs of the reference collapsed into an embedding-style table.
Scoring is a gather + row-wise dot (one fused XLA op), replacing the
datum-by-REId RDD join. Entities unseen at training time score 0, matching
the reference's behavior for missing REIds.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.types import TaskType

Array = jax.Array


class DatumScoringModel:
    """Anything that can score a GameDataset (reference DatumScoringModel)."""

    task: TaskType

    def score_dataset(self, dataset) -> Array:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class FixedEffectModel(DatumScoringModel):
    """A single GLM applied to one feature shard (reference FixedEffectModel.scala)."""

    glm: GeneralizedLinearModel
    feature_shard_id: str

    @property
    def task(self) -> TaskType:
        return self.glm.task

    def score_dataset(self, dataset) -> Array:
        from photon_ml_tpu.data.sparse_batch import SparseShard

        features = dataset.shard_features(self.feature_shard_id)
        if isinstance(features, SparseShard):
            return features.device().matvec(self.glm.coefficients.means)
        return features @ self.glm.coefficients.means


@dataclasses.dataclass(frozen=True)
class RandomEffectModel(DatumScoringModel):
    """Per-entity coefficient table for one random-effect type.

    coefficients: [num_entities, dim]; entity i's GLM lives in row i.
    variances: optional [num_entities, dim].
    entity_keys: host-side vocab, position == row index.
    """

    coefficients: Array
    entity_keys: np.ndarray  # [num_entities] of str/int keys
    random_effect_type: str
    feature_shard_id: str
    task: TaskType
    variances: Array | None = None
    #: compact (giant-d_re) mode: coefficients are [E, K] over each entity's
    #: sorted active GLOBAL columns (active_cols [E, K] int32, pad =
    #: feature_dim); set feature_dim to the true shard width
    active_cols: np.ndarray | None = None
    feature_dim: int | None = None

    @property
    def num_entities(self) -> int:
        return self.coefficients.shape[0]

    @property
    def is_compact(self) -> bool:
        return self.active_cols is not None

    @property
    def dim(self) -> int:
        if self.active_cols is not None:
            return int(self.feature_dim)
        return self.coefficients.shape[1]

    def score_dataset(self, dataset) -> Array:
        features = dataset.shard_features(self.feature_shard_id)
        entity_idx = dataset.entity_indices(self.random_effect_type)
        if self.active_cols is not None:
            from photon_ml_tpu.data.sparse_batch import SparseShard

            if not isinstance(features, SparseShard):
                # dense shard, compact model (e.g. a model loaded compact
                # via the size threshold scoring a dense dataset): gather
                # each sample's entity's active columns — O(n·K), no [E, d]
                dim = int(self.feature_dim)
                if int(features.shape[1]) != dim:
                    # a clamped gather on a narrower shard would silently
                    # read the wrong column for every active col >= width
                    raise ValueError(
                        f"compact random-effect model "
                        f"'{self.random_effect_type}' lives in a "
                        f"{dim}-column feature space but the dense shard "
                        f"'{self.feature_shard_id}' has "
                        f"{int(features.shape[1])} columns"
                    )
                idx = jnp.asarray(entity_idx)
                safe = jnp.maximum(idx, 0)
                cols = jnp.asarray(self.active_cols, dtype=jnp.int32)[safe]
                x = jnp.take_along_axis(
                    jnp.asarray(features),
                    jnp.minimum(cols, dim - 1), axis=1,
                ) * (cols < dim)
                scores = jnp.einsum("nk,nk->n", x, self.coefficients[safe])
                return jnp.where(idx >= 0, scores, 0.0)
            ent, pos, rows, vals = compact_entry_positions(
                features, np.asarray(entity_idx), self.active_cols
            )
            return score_random_effect_compact(
                self.coefficients,
                jnp.asarray(ent), jnp.asarray(pos),
                jnp.asarray(rows), jnp.asarray(vals),
                dataset.num_samples,
            )
        return score_random_effect(self.coefficients, features, entity_idx)

    def with_coefficients(self, coefficients: Array) -> "RandomEffectModel":
        """New table, dropping any variances (they were computed at the old
        coefficients and would silently go stale)."""
        return dataclasses.replace(self, coefficients=coefficients, variances=None)


def score_random_effect(table: Array, features: Array, entity_idx: Array) -> Array:
    """scores_i = x_i . table[entity_idx_i], 0 for unseen entities (idx < 0).

    The gather + einsum that replaces RandomEffectModel.scala's scoring join.
    """
    if table.shape[0] == 0:
        # 0-entity model (e.g. an untrained coordinate loaded from disk):
        # every sample is "unseen" — and a gather from an empty table is a
        # compile error, not a no-op
        return jnp.zeros(entity_idx.shape, dtype=features.dtype)
    safe_idx = jnp.maximum(entity_idx, 0)
    rows = table[safe_idx]
    scores = jnp.einsum("nd,nd->n", features, rows)
    return jnp.where(entity_idx >= 0, scores, 0.0)


def match_active_positions(
    ent: np.ndarray, cols: np.ndarray, active_cols: np.ndarray, dim: int
) -> np.ndarray:
    """Position of each (entity, global column) query in the entity's sorted
    active-column list, or K (the scratch slot) when absent.

    The shared core of every compact-layout lookup (entry scoring, warm-start
    remaps): encode (entity, col) as entity·(dim+1)+col — globally
    non-decreasing because active_cols rows are sorted ascending with pads
    == dim — and binary-search the flattened lists. Pad queries (col >= dim)
    and negative entities resolve to K.
    """
    e, k = active_cols.shape
    dimp = int(dim) + 1
    ent = np.asarray(ent, dtype=np.int64)
    valid = (ent >= 0) & (np.asarray(cols) < dim)
    ent_safe = np.where(ent >= 0, ent, 0)
    keys = ent_safe * dimp + np.asarray(cols, dtype=np.int64)
    flat = (
        (np.arange(e, dtype=np.int64) * dimp)[:, None]
        + np.asarray(active_cols, dtype=np.int64)
    ).ravel()
    idx = np.clip(np.searchsorted(flat, keys), 0, max(e * k - 1, 0))
    hit = (flat[idx] == keys) if e * k else np.zeros(len(keys), bool)
    return np.where(hit & valid, idx - ent_safe * k, k).astype(np.int32)


def compact_entry_positions(
    shard, entity_idx: np.ndarray, active_cols: np.ndarray
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Map each COO entry of ``shard`` to its position in its sample's
    entity's active-column list (host precompute for compact RE scoring).

    Returns (ent [nnz], pos [nnz], rows [nnz], vals [nnz]): entry k of
    sample i with column j scores vals·table[ent, pos]; pos = K (the
    scratch/zero slot) when j is not among entity's active columns or the
    sample's entity is unseen (idx < 0) — those entries contribute 0, the
    reference's untrained-column semantics. Cached on the shard keyed by
    the active-column content.
    """
    import hashlib

    # key on BOTH inputs: the same shard object can appear in datasets with
    # different sample/entity mappings (a stale entity_idx would silently
    # score the wrong samples)
    key = (
        active_cols.shape,
        hashlib.sha1(np.ascontiguousarray(active_cols)).hexdigest(),
        hashlib.sha1(
            np.ascontiguousarray(np.asarray(entity_idx, dtype=np.int64))
        ).hexdigest(),
    )
    cache = getattr(shard, "_compact_pos_cache", None)
    if cache is None:
        cache = {}
        object.__setattr__(shard, "_compact_pos_cache", cache)
    if key in cache:
        return cache[key]

    rows_s, cols_s, vals_s = shard.coalesced()
    rows_s = np.asarray(rows_s)
    cols_s = np.asarray(cols_s)
    vals_s = np.asarray(vals_s)
    ent = entity_idx[rows_s].astype(np.int64)
    pos = match_active_positions(ent, cols_s, active_cols, shard.feature_dim)
    out = (
        np.where(ent >= 0, ent, 0).astype(np.int32), pos,
        rows_s.astype(np.int32), vals_s,
    )
    cache[key] = out
    return out


def score_random_effect_compact(
    table: Array, ent: Array, pos: Array, rows: Array, vals: Array, n: int
) -> Array:
    """scores from a compact [E, K] table: one gather over the entry-to-
    table-slot mapping + a row segment-sum — O(nnz), nothing of size d_re.
    """
    if table.shape[0] == 0:
        return jnp.zeros((n,), dtype=vals.dtype)
    table_ext = jnp.concatenate(
        [table, jnp.zeros((table.shape[0], 1), table.dtype)], axis=1
    )
    contrib = vals * table_ext[ent, pos]
    return jax.ops.segment_sum(
        contrib, rows, num_segments=n, indices_are_sorted=True
    )


@dataclasses.dataclass(frozen=True)
class GameModel:
    """Ordered map coordinate-id -> sub-model; score = sum of sub-scores."""

    models: Mapping[str, DatumScoringModel]

    def __post_init__(self):
        # Reference GameModel.scala:163-169 type-consistency check.
        tasks = {m.task for m in self.models.values() if m.task != TaskType.NONE}
        if len(tasks) > 1:
            raise ValueError(f"Inconsistent task types across coordinates: {tasks}")

    @property
    def task(self) -> TaskType:
        for m in self.models.values():
            if m.task != TaskType.NONE:
                return m.task
        return TaskType.NONE

    def get(self, coordinate_id: str) -> DatumScoringModel:
        return self.models[coordinate_id]

    def score_dataset(self, dataset) -> Array:
        total = None
        for model in self.models.values():
            s = model.score_dataset(dataset)
            total = s if total is None else total + s
        if total is None:
            raise ValueError("GameModel has no sub-models")
        return total

    def updated(self, coordinate_id: str, model: DatumScoringModel) -> "GameModel":
        new = dict(self.models)
        new[coordinate_id] = model
        return GameModel(models=new)
