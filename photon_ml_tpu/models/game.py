"""GAME models: fixed-effect, random-effect, and the composite GameModel.

Reference parity: photon-api model/FixedEffectModel.scala (broadcast GLM +
feature shard id), model/RandomEffectModel.scala (RDD[(REId, GLM)] + RE type
+ shard id, scoring by join), photon-lib model/GameModel.scala (map
CoordinateId -> DatumScoringModel, score = Σ sub-scores, GameModel.scala:101-107;
type consistency check :163-169).

TPU-native: a random-effect model is one dense [num_entities, dim] matrix —
the per-entity GLMs of the reference collapsed into an embedding-style table.
Scoring is a gather + row-wise dot (one fused XLA op), replacing the
datum-by-REId RDD join. Entities unseen at training time score 0, matching
the reference's behavior for missing REIds.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.types import TaskType

Array = jax.Array


class DatumScoringModel:
    """Anything that can score a GameDataset (reference DatumScoringModel)."""

    task: TaskType

    def score_dataset(self, dataset) -> Array:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class FixedEffectModel(DatumScoringModel):
    """A single GLM applied to one feature shard (reference FixedEffectModel.scala)."""

    glm: GeneralizedLinearModel
    feature_shard_id: str

    @property
    def task(self) -> TaskType:
        return self.glm.task

    def score_dataset(self, dataset) -> Array:
        from photon_ml_tpu.data.sparse_batch import SparseShard

        features = dataset.shard_features(self.feature_shard_id)
        if isinstance(features, SparseShard):
            return features.device().matvec(self.glm.coefficients.means)
        return features @ self.glm.coefficients.means


@dataclasses.dataclass(frozen=True)
class RandomEffectModel(DatumScoringModel):
    """Per-entity coefficient table for one random-effect type.

    coefficients: [num_entities, dim]; entity i's GLM lives in row i.
    variances: optional [num_entities, dim].
    entity_keys: host-side vocab, position == row index.
    """

    coefficients: Array
    entity_keys: np.ndarray  # [num_entities] of str/int keys
    random_effect_type: str
    feature_shard_id: str
    task: TaskType
    variances: Array | None = None

    @property
    def num_entities(self) -> int:
        return self.coefficients.shape[0]

    @property
    def dim(self) -> int:
        return self.coefficients.shape[1]

    def score_dataset(self, dataset) -> Array:
        features = dataset.shard_features(self.feature_shard_id)
        entity_idx = dataset.entity_indices(self.random_effect_type)
        return score_random_effect(self.coefficients, features, entity_idx)

    def with_coefficients(self, coefficients: Array) -> "RandomEffectModel":
        """New table, dropping any variances (they were computed at the old
        coefficients and would silently go stale)."""
        return dataclasses.replace(self, coefficients=coefficients, variances=None)


def score_random_effect(table: Array, features: Array, entity_idx: Array) -> Array:
    """scores_i = x_i . table[entity_idx_i], 0 for unseen entities (idx < 0).

    The gather + einsum that replaces RandomEffectModel.scala's scoring join.
    """
    if table.shape[0] == 0:
        # 0-entity model (e.g. an untrained coordinate loaded from disk):
        # every sample is "unseen" — and a gather from an empty table is a
        # compile error, not a no-op
        return jnp.zeros(entity_idx.shape, dtype=features.dtype)
    safe_idx = jnp.maximum(entity_idx, 0)
    rows = table[safe_idx]
    scores = jnp.einsum("nd,nd->n", features, rows)
    return jnp.where(entity_idx >= 0, scores, 0.0)


@dataclasses.dataclass(frozen=True)
class GameModel:
    """Ordered map coordinate-id -> sub-model; score = sum of sub-scores."""

    models: Mapping[str, DatumScoringModel]

    def __post_init__(self):
        # Reference GameModel.scala:163-169 type-consistency check.
        tasks = {m.task for m in self.models.values() if m.task != TaskType.NONE}
        if len(tasks) > 1:
            raise ValueError(f"Inconsistent task types across coordinates: {tasks}")

    @property
    def task(self) -> TaskType:
        for m in self.models.values():
            if m.task != TaskType.NONE:
                return m.task
        return TaskType.NONE

    def get(self, coordinate_id: str) -> DatumScoringModel:
        return self.models[coordinate_id]

    def score_dataset(self, dataset) -> Array:
        total = None
        for model in self.models.values():
            s = model.score_dataset(dataset)
            total = s if total is None else total + s
        if total is None:
            raise ValueError("GameModel has no sub-models")
        return total

    def updated(self, coordinate_id: str, model: DatumScoringModel) -> "GameModel":
        new = dict(self.models)
        new[coordinate_id] = model
        return GameModel(models=new)
