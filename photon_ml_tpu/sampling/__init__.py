"""Down-sampling (reference photon-lib sampling/*.scala)."""

from photon_ml_tpu.sampling.down_sampler import (
    BinaryClassificationDownSampler,
    DefaultDownSampler,
    DownSampler,
    down_sampler_for_task,
)

__all__ = [
    "BinaryClassificationDownSampler",
    "DefaultDownSampler",
    "DownSampler",
    "down_sampler_for_task",
]
