"""Deterministic down-sampling as a weight transform.

Reference parity: photon-lib sampling/DownSampler.scala,
sampling/DefaultDownSampler.scala (uniform sample of all rows, no
reweighting), sampling/BinaryClassificationDownSampler.scala:31-68 (keep
every positive, thin negatives at ``rate`` and rescale their weights by
1/rate so the effective class balance of the objective is unchanged).

TPU-native redesign: the reference filters RDD rows; a jitted program wants
fixed shapes, so down-sampling here *zeroes weights* instead of dropping
rows — a zero-weight sample contributes nothing to any weighted aggregate
(data/batch.py), which is exactly the semantics of removal, and the batch
keeps its compiled shape. Selection is keyed on stable sample ids via a
splitmix64 hash, so the same (ids, seed) always selects the same rows —
no RDD-recompute instability (cf. RandomEffectDataSet.scala:389-395).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from photon_ml_tpu.types import TaskType

_U64 = np.uint64


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer: uint64 -> well-mixed uint64."""
    x = x.astype(_U64)
    with np.errstate(over="ignore"):
        x = (x + _U64(0x9E3779B97F4A7C15)) & _U64(0xFFFFFFFFFFFFFFFF)
        x = ((x ^ (x >> _U64(30))) * _U64(0xBF58476D1CE4E5B9)) & _U64(0xFFFFFFFFFFFFFFFF)
        x = ((x ^ (x >> _U64(27))) * _U64(0x94D049BB133111EB)) & _U64(0xFFFFFFFFFFFFFFFF)
        x = x ^ (x >> _U64(31))
    return x


def stable_uniform(unique_ids: np.ndarray, seed: int) -> np.ndarray:
    """Deterministic per-sample uniform in [0, 1) keyed on (id, seed)."""
    ids = np.asarray(unique_ids).astype(np.int64).view(_U64)
    seed_key = _splitmix64(np.asarray([seed], dtype=np.int64).view(_U64))[0]
    mixed = _splitmix64(ids ^ seed_key)
    return (mixed >> _U64(11)).astype(np.float64) * (1.0 / float(1 << 53))


@dataclasses.dataclass(frozen=True)
class DownSampler:
    """Base: subclasses return a per-sample weight multiplier array.

    ``down_sample_weights`` maps (labels, weights, ids) -> new weights with
    dropped rows at 0; callers multiply into the batch/dataset weights.
    """

    down_sampling_rate: float

    def __post_init__(self):
        if not (0.0 < self.down_sampling_rate < 1.0):
            raise ValueError(
                f"down-sampling rate must be in (0, 1), got {self.down_sampling_rate}"
            )

    def down_sample_weights(
        self, labels: np.ndarray, weights: np.ndarray, unique_ids: np.ndarray, seed: int = 0
    ) -> np.ndarray:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class DefaultDownSampler(DownSampler):
    """Uniform sampling of all rows with weights left untouched — the
    reference's DefaultDownSampler is a plain RDD.sample with no
    reweighting, so the effective data term shrinks by ``rate`` relative to
    any fixed regularization weight; matched here for config parity."""

    def down_sample_weights(self, labels, weights, unique_ids, seed: int = 0) -> np.ndarray:
        weights = np.asarray(weights, dtype=np.float64)
        keep = stable_uniform(unique_ids, seed) < self.down_sampling_rate
        return np.where(keep, weights, 0.0)


@dataclasses.dataclass(frozen=True)
class BinaryClassificationDownSampler(DownSampler):
    """Keep all positives; sample negatives at ``rate`` with weights
    rescaled by 1/rate (reference BinaryClassificationDownSampler.scala:31-68)."""

    def down_sample_weights(self, labels, weights, unique_ids, seed: int = 0) -> np.ndarray:
        labels = np.asarray(labels, dtype=np.float64)
        weights = np.asarray(weights, dtype=np.float64)
        positive = labels > 0.5
        keep_neg = stable_uniform(unique_ids, seed) < self.down_sampling_rate
        return np.where(
            positive,
            weights,
            np.where(keep_neg, weights / self.down_sampling_rate, 0.0),
        )


def down_sampler_for_task(task: TaskType, rate: float) -> DownSampler:
    """Factory matching the reference's DownSamplerHelper: classification
    tasks thin only negatives; regression tasks sample uniformly."""
    if task in (TaskType.LOGISTIC_REGRESSION, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM):
        return BinaryClassificationDownSampler(rate)
    return DefaultDownSampler(rate)
