"""Device-side (mesh-shardable) evaluators: metrics without the host funnel.

Reference parity: the reference's evaluators are distributed end-to-end —
AUC/RMSE over RDDs (photon-lib evaluation/Evaluator.scala:39-49), per-query
metrics via groupByKey on executors (photon-api
evaluation/MultiEvaluator.scala:40-88). The host evaluators here
(evaluation/evaluators.py) are exact but consume a full [n] score gather —
at validation scale that funnels billions of rows through one host core
(VERDICT r4 missing #2).

This module computes the same metrics ON DEVICE from the still-sharded
score vector; only scalars cross to the host:

- RMSE / MAE / the four losses: weighted psum-style reductions — exact.
- AUC / AUPR: one device sort by score then tie-run arithmetic — the same
  exact tie-aware formulas as the host metrics (average-rank Mann-Whitney
  AUC; trapezoidal PR area at distinct-score thresholds including the
  (0, p_first) start). Global AUC was a threshold-histogram approximation
  (|Δ| ≲ 1e-3) through r5; it now rides the exact sort machinery the
  per-query metrics already used (VERDICT r5 weak #2 — a 1e-3 metric
  error could flip best-model selection between near-tied candidates).
- Per-query RMSE: segment reductions over dense query codes — exact.
- Per-query AUC / PRECISION@k: one device lexsort by (query, score) then
  segmented run arithmetic — exact (average-rank ties, stable-order
  tie-break, both matching the host evaluators). NOTE: XLA may gather the
  sorted operand across devices; the computation still never leaves the
  device side.

Padding contract: rows appended to reach a mesh-divisible length carry
weight 0 and query code Q (their own excluded segment), so they contribute
nothing to any metric (the sort-based metrics are weight-linear, so
weight-0 rows land in some tie run and add zero).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from photon_ml_tpu.evaluation.evaluators import (
    EvaluationData,
    Evaluator,
    MultiEvaluator,
    _GlobalEvaluator,
)

Array = jax.Array


# --- global metrics (weighted reductions) -----------------------------------


def _wsum_metric(fn):
    def compute(scores, c):
        w = c["weights"]
        wsum = jnp.sum(w)
        total = jnp.sum(w * fn(scores, c["labels"]))
        return jnp.where(wsum > 0, total / wsum, jnp.nan)

    return compute


def _rmse(scores, c):
    w = c["weights"]
    wsum = jnp.sum(w)
    se = jnp.sum(w * (scores - c["labels"]) ** 2)
    return jnp.where(wsum > 0, jnp.sqrt(se / wsum), jnp.nan)


def _auc_exact(scores, c):
    """Exact weighted Mann-Whitney AUC with average-rank ties: one device
    sort by score, then tie-run cumulative arithmetic — the single-query
    form of :func:`_per_query_auc`, matching
    ``local_metrics.area_under_roc_curve`` term for term:

    AUC = [ Σ_{i∈pos} w_i (W⁻_{<s_i} + ½ W⁻_{=s_i}) ] / (W⁺ W⁻)
    """
    w, y = c["weights"], c["labels"]
    pos = y > 0.5
    wp_all = jnp.where(pos, w, 0.0)
    wn_all = jnp.where(~pos, w, 0.0)
    wp, wn = jnp.sum(wp_all), jnp.sum(wn_all)
    order = jnp.argsort(scores)
    s_sorted = scores[order]
    wpos = wp_all[order]
    wneg = wn_all[order]
    n = scores.shape[0]
    idx = jnp.arange(n)
    new_run = jnp.concatenate(
        [jnp.ones(1, bool), s_sorted[1:] != s_sorted[:-1]]
    )
    run_id = jnp.cumsum(new_run) - 1
    run_start = jax.ops.segment_min(idx, run_id, num_segments=n)[run_id]
    cneg = jnp.concatenate([jnp.zeros(1), jnp.cumsum(wneg)])
    neg_before_run = cneg[run_start]
    run_neg = jax.ops.segment_sum(wneg, run_id, num_segments=n)[run_id]
    contrib = jnp.sum(wpos * (neg_before_run + 0.5 * run_neg))
    return jnp.where((wp > 0) & (wn > 0), contrib / (wp * wn), jnp.nan)


def _aupr_exact(scores, c):
    """Exact weighted AUPR: trapezoidal area over the PR curve at
    distinct-score thresholds, including the (0, p_first) starting point —
    ``local_metrics.area_under_precision_recall_curve`` on device. The
    host's boolean run-end selection becomes per-RUN cumulative sums
    (segment reductions over tie runs of the descending sort); runs past
    the true distinct-score count stay flat (zero recall width), so the
    fixed-shape cumsum adds nothing."""
    w, y = c["weights"], c["labels"]
    # mesh-padding rows must not become PR thresholds: their (arbitrary)
    # scores could otherwise lead the descending sort and zero the curve's
    # (0, p_first) start. Real weight-0 rows DO stay thresholds — the host
    # metric counts them (zero-width trapezoids, and a weight-free leading
    # run pins p_first to 0), so only the appended pads are masked.
    sort_key = jnp.where(c["valid"] > 0, scores, -jnp.inf)
    order = jnp.argsort(-sort_key)
    s_desc = sort_key[order]
    w_sorted = w[order]
    tp_w = jnp.where(y[order] > 0.5, w_sorted, 0.0)
    total_pos = jnp.sum(tp_w)
    n = scores.shape[0]
    new_run = jnp.concatenate(
        [jnp.ones(1, bool), s_desc[1:] != s_desc[:-1]]
    )
    run_id = jnp.cumsum(new_run) - 1
    # per-run sums, then cumulative over runs = (cum_tp, cum_all) at each
    # run's END — the host's is_run_end gather
    run_tp = jnp.cumsum(jax.ops.segment_sum(tp_w, run_id, num_segments=n))
    run_all = jnp.cumsum(
        jax.ops.segment_sum(w_sorted, run_id, num_segments=n)
    )
    precision = jnp.where(run_all > 0, run_tp / jnp.maximum(run_all, 1e-30), 0.0)
    recall = run_tp / jnp.maximum(total_pos, 1e-30)
    r_prev = jnp.concatenate([jnp.zeros(1), recall[:-1]])
    p_prev = jnp.concatenate([precision[:1], precision[:-1]])
    area = jnp.sum((recall - r_prev) * 0.5 * (precision + p_prev))
    return jnp.where(total_pos > 0, area, jnp.nan)


_GLOBAL_DEVICE: dict[str, Callable] = {
    "RMSE": _rmse,
    "MAE": _wsum_metric(lambda s, y: jnp.abs(s - y)),
    "LOGISTIC_LOSS": _wsum_metric(
        lambda s, y: jnp.logaddexp(0.0, s) - y * s
    ),
    "SQUARED_LOSS": _wsum_metric(lambda s, y: 0.5 * (s - y) ** 2),
    "POISSON_LOSS": _wsum_metric(lambda s, y: jnp.exp(s) - y * s),
    "SMOOTHED_HINGE_LOSS": _wsum_metric(
        lambda s, y: _smoothed_hinge(s, y)
    ),
    "AUC": _auc_exact,
    "AUPR": _aupr_exact,
}


def _smoothed_hinge(s, y):
    t = (2.0 * y - 1.0) * s
    return jnp.where(
        t >= 1.0, 0.0, jnp.where(t <= 0.0, 0.5 - t, 0.5 * (1.0 - t) ** 2)
    )


# --- per-query metrics -------------------------------------------------------


def _per_query_rmse(scores, c):
    q, w, y = c["qid"], c["weights"], c["labels"]
    nq = int(c["num_queries"])
    se = jax.ops.segment_sum(w * (scores - y) ** 2, q, num_segments=nq + 1)
    ws = jax.ops.segment_sum(w, q, num_segments=nq + 1)
    per = jnp.sqrt(se[:nq] / jnp.maximum(ws[:nq], 1e-30))
    valid = ws[:nq] > 0
    cnt = jnp.sum(valid)
    return jnp.where(
        cnt > 0, jnp.sum(jnp.where(valid, per, 0.0)) / cnt, jnp.nan
    )


def _sorted_query_layout(scores, c, order_key_scores):
    """Lexsort rows by (query, key) — stable, so equal keys keep original
    order like the host's kind='stable' argsorts. Returns sorted gathers +
    per-element segment bookkeeping."""
    q = c["qid"]
    order = jnp.lexsort((order_key_scores, q))
    qs = q[order]
    n = q.shape[0]
    idx = jnp.arange(n)
    nq = int(c["num_queries"])
    # first sorted position of each query, gathered back per element
    q_start = jax.ops.segment_min(idx, qs, num_segments=nq + 1)[qs]
    return order, qs, idx, q_start


def _per_query_auc(scores, c):
    """Exact per-query Mann-Whitney AUC (average-rank ties): one lexsort by
    (query, score), then run/segment cumulative arithmetic. Queries missing
    a class are skipped (MultiEvaluator requires_both_classes)."""
    q, w, y = c["qid"], c["weights"], c["labels"]
    nq = int(c["num_queries"])
    order, qs, idx, q_start = _sorted_query_layout(scores, c, scores)
    s_sorted = scores[order]
    w_sorted = w[order]
    pos_sorted = y[order] > 0.5
    wpos = jnp.where(pos_sorted, w_sorted, 0.0)
    wneg = jnp.where(~pos_sorted, w_sorted, 0.0)
    # tie runs: equal (query, score)
    new_run = jnp.concatenate([
        jnp.ones(1, bool),
        (qs[1:] != qs[:-1]) | (s_sorted[1:] != s_sorted[:-1]),
    ])
    run_id = jnp.cumsum(new_run) - 1
    n = q.shape[0]
    run_start = jax.ops.segment_min(idx, run_id, num_segments=n)[run_id]
    cneg = jnp.concatenate([jnp.zeros(1), jnp.cumsum(wneg)])
    neg_before_run = cneg[run_start] - cneg[q_start]
    run_neg = jax.ops.segment_sum(wneg, run_id, num_segments=n)[run_id]
    contrib = wpos * (neg_before_run + 0.5 * run_neg)
    auc_num = jax.ops.segment_sum(contrib, qs, num_segments=nq + 1)
    wp_q = jax.ops.segment_sum(wpos, qs, num_segments=nq + 1)
    wn_q = jax.ops.segment_sum(wneg, qs, num_segments=nq + 1)
    valid = (wp_q[:nq] > 0) & (wn_q[:nq] > 0)
    per = auc_num[:nq] / jnp.maximum(wp_q[:nq] * wn_q[:nq], 1e-30)
    cnt = jnp.sum(valid)
    return jnp.where(
        cnt > 0, jnp.sum(jnp.where(valid, per, 0.0)) / cnt, jnp.nan
    )


def _per_query_precision_at_k(k: int):
    def compute(scores, c):
        q, y = c["qid"], c["labels"]
        nq = int(c["num_queries"])
        # stable (query asc, score desc): host tie-break is original order
        order, qs, idx, q_start = _sorted_query_layout(scores, c, -scores)
        rank = idx - q_start  # 0-based within-query rank
        pos_sorted = y[order] > 0.5
        in_top = rank < k
        hits = jax.ops.segment_sum(
            jnp.where(in_top & pos_sorted, 1.0, 0.0), qs, num_segments=nq + 1
        )
        size = jax.ops.segment_sum(
            jnp.ones_like(scores), qs, num_segments=nq + 1
        )
        denom = jnp.minimum(size[:nq], float(k))
        valid = size[:nq] > 0
        per = hits[:nq] / jnp.maximum(denom, 1.0)
        cnt = jnp.sum(valid)
        return jnp.where(
            cnt > 0, jnp.sum(jnp.where(valid, per, 0.0)) / cnt, jnp.nan
        )

    return compute


# --- preparation / adaptation ------------------------------------------------


@dataclasses.dataclass
class DeviceEvaluator:
    """A host Evaluator compiled against one dataset layout: ``compute`` is
    jittable over (scores, consts); consts live on device. Metric
    direction stays with the host evaluator (callers keep using its
    ``better_than``)."""

    name: str
    larger_is_better: bool
    compute: Callable[[Array, dict], Array]
    consts: dict


@functools.partial(jax.jit, static_argnums=0)
def jit_metric(fn, scores, consts):
    """One device metric over still-sharded scores — XLA reduces on-mesh, a
    scalar comes back. fn is static: prepared evaluators hold one closure
    per run, so the compilation caches across sweeps."""
    return fn(scores, consts)


def mesh_data_placer(mesh, put_fn=None):
    """Placement closure for evaluator consts: sharded P("data") over the
    mesh (put_fn = e.g. multihost.global_put on multi-process runs)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    put = put_fn if put_fn is not None else jax.device_put

    def place(a):
        return put(np.asarray(a), NamedSharding(mesh, P("data")))

    return place


def evaluate_prepared(
    evaluators: Sequence[Evaluator],
    device_evals: Sequence["DeviceEvaluator | None"],
    scores: Array,
    eval_data: EvaluationData,
    host_scores_fn: Callable[[], np.ndarray],
) -> list[float]:
    """Metric values in evaluator order: device twins reduce on-mesh (only
    scalars cross to the host); evaluators without one (custom/unknown
    types) share a single host gather via ``host_scores_fn``."""
    out: list[float] = []
    host_scores: np.ndarray | None = None
    for ev, dev in zip(evaluators, device_evals):
        if dev is not None:
            out.append(float(jit_metric(dev.compute, scores, dev.consts)))
        else:
            if host_scores is None:
                host_scores = host_scores_fn()
            out.append(float(ev.evaluate(host_scores, eval_data)))
    return out


def device_evaluator(
    evaluator: Evaluator,
    data: EvaluationData,
    n_pad: int | None = None,
    place: Callable[[np.ndarray], Array] | None = None,
) -> DeviceEvaluator | None:
    """Adapt a host evaluator to its device twin for one dataset, or None
    when no device form exists (custom/unknown evaluator types — callers
    fall back to the host path). ``n_pad``: padded score length (mesh-divisible); appended rows
    get weight 0 / query code Q. ``place``: array placement (device_put
    with the mesh's P("data") sharding); default jnp.asarray."""
    n = len(data.labels)
    n_pad = n if n_pad is None else int(n_pad)
    place = place or jnp.asarray

    def padded(a, fill=0.0):
        # float64 on host; jnp.asarray narrows to f32 when x64 is off (the
        # production TPU config) and keeps f64 under the x64 test config —
        # where the device metrics then match the host metrics exactly
        a = np.asarray(a, np.float64)
        if n_pad > n:
            a = np.concatenate([a, np.full(n_pad - n, fill, a.dtype)])
        return place(a)

    consts = {
        "labels": padded(data.labels),
        "weights": padded(data.weights),  # pad weight 0 = inert rows
        # 1 on real rows, 0 on appended mesh pads — lets sort-based metrics
        # (AUPR) keep real weight-0 rows as thresholds while masking pads
        "valid": padded(np.ones(n)),
    }
    if isinstance(evaluator, _GlobalEvaluator):
        fn = _GLOBAL_DEVICE.get(evaluator.name)
        if fn is None:
            return None
        return DeviceEvaluator(
            evaluator.name, evaluator.larger_is_better, fn, consts
        )
    if isinstance(evaluator, MultiEvaluator):
        ids = data.ids.get(evaluator.id_column)
        if ids is None:
            raise KeyError(
                f"id column '{evaluator.id_column}' not present in "
                "evaluation data"
            )
        _, codes = np.unique(np.asarray(ids), return_inverse=True)
        nq = int(codes.max()) + 1 if len(codes) else 0
        codes = codes.astype(np.int32)
        if n_pad > n:
            codes = np.concatenate(
                [codes, np.full(n_pad - n, nq, np.int32)]
            )
        consts["qid"] = place(codes)
        metric = evaluator.name.split(":", 1)[0]
        if metric == "RMSE":
            fn = _per_query_rmse
        elif metric == "AUC":
            fn = _per_query_auc
        elif metric.startswith("PRECISION@"):
            fn = _per_query_precision_at_k(int(metric.split("@", 1)[1]))
        else:
            return None

        # num_queries is a STATIC segment count — baked into the compute
        # closure (a traced value could not size segment_sums). The closure
        # is created once per prepared evaluator, so jit caches by identity
        # across sweeps.
        def compute(scores, c, _fn=fn, _nq=nq):
            return _fn(scores, {**c, "num_queries": _nq})

        return DeviceEvaluator(
            evaluator.name, evaluator.larger_is_better, compute, consts
        )
    return None


def prepare_device_evaluators(
    evaluators: Sequence[Evaluator],
    data: EvaluationData,
    n_pad: int | None = None,
    place: Callable[[np.ndarray], Array] | None = None,
) -> list["DeviceEvaluator | None"]:
    """Per-evaluator device twins (None where only the host form exists)."""
    return [device_evaluator(ev, data, n_pad, place) for ev in evaluators]
