"""Evaluator objects: metric + comparison direction + per-query variants.

Reference parity: photon-lib evaluation/Evaluator.scala:39-49 (evaluate joins
scores with labels/offsets/weights), EvaluatorType.scala:35-43 (AUC, AUPR,
RMSE, per-task losses, with betterThan direction per metric), photon-api
evaluation/MultiEvaluator.scala:40-88 (per-query grouping + mean of local
metric), MultiEvaluatorType ("AUC:queryId"-style names), and
EvaluatorFactory.scala.

Scoring note: as in the reference, evaluators consume *raw scores* (margins
including offsets); classification metrics interpret them as ranking scores,
regression metrics as predictions.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np

from photon_ml_tpu.evaluation import local_metrics as lm
from photon_ml_tpu.types import TaskType


@dataclasses.dataclass(frozen=True)
class EvaluationData:
    """Host-side (scores, labels, offsets, weights) + optional id columns."""

    labels: np.ndarray
    offsets: np.ndarray
    weights: np.ndarray
    ids: dict[str, np.ndarray] = dataclasses.field(default_factory=dict)


class Evaluator:
    """A named metric with a preference direction."""

    name: str
    #: True if larger metric values are better (AUC) — reference betterThan
    larger_is_better: bool

    def evaluate(self, scores: np.ndarray, data: EvaluationData) -> float:
        raise NotImplementedError

    def better_than(self, a: float, b: float) -> bool:
        if np.isnan(b):
            return True
        if np.isnan(a):
            return False
        return a > b if self.larger_is_better else a < b


@dataclasses.dataclass(frozen=True)
class _GlobalEvaluator(Evaluator):
    name: str
    larger_is_better: bool
    fn: Callable[..., float]

    def evaluate(self, scores: np.ndarray, data: EvaluationData) -> float:
        return self.fn(scores, data.labels, data.weights)


@dataclasses.dataclass(frozen=True)
class MultiEvaluator(Evaluator):
    """Per-query ("sharded") metric: group rows by an id column, compute the
    local metric per group, return the unweighted mean over groups with >0
    valid result (reference MultiEvaluator.scala:40-88)."""

    name: str
    larger_is_better: bool
    id_column: str
    local_fn: Callable[..., float]
    #: groups must contain both classes for ranking metrics to be defined
    requires_both_classes: bool = False

    def evaluate(self, scores: np.ndarray, data: EvaluationData) -> float:
        ids = data.ids.get(self.id_column)
        if ids is None:
            raise KeyError(
                f"id column '{self.id_column}' not present in evaluation data"
            )
        scores = np.asarray(scores).reshape(-1)
        order = np.argsort(ids, kind="stable")
        sorted_ids = np.asarray(ids)[order]
        boundaries = np.concatenate(
            [[0], np.nonzero(sorted_ids[1:] != sorted_ids[:-1])[0] + 1, [len(sorted_ids)]]
        )
        values = []
        for start, end in zip(boundaries[:-1], boundaries[1:]):
            sel = order[start:end]
            y = data.labels[sel]
            if self.requires_both_classes and (np.all(y > 0.5) or np.all(y <= 0.5)):
                continue
            v = self.local_fn(scores[sel], y, data.weights[sel])
            if not np.isnan(v):
                values.append(v)
        return float(np.mean(values)) if values else float("nan")


# --- evaluator registry (reference EvaluatorType + EvaluatorFactory) --------

_GLOBALS = {
    "AUC": ("AUC", True, lm.area_under_roc_curve),
    "AUPR": ("AUPR", True, lm.area_under_precision_recall_curve),
    "RMSE": ("RMSE", False, lm.root_mean_squared_error),
    "MAE": ("MAE", False, lm.mean_absolute_error),
    "LOGISTIC_LOSS": ("LOGISTIC_LOSS", False, lm.logistic_loss),
    "SQUARED_LOSS": ("SQUARED_LOSS", False, lm.squared_loss),
    "POISSON_LOSS": ("POISSON_LOSS", False, lm.poisson_loss),
    "SMOOTHED_HINGE_LOSS": ("SMOOTHED_HINGE_LOSS", False, lm.smoothed_hinge_loss),
}

_LOCAL_FOR_MULTI = {
    "AUC": (True, lm.area_under_roc_curve, True),
    "RMSE": (False, lm.root_mean_squared_error, False),
}


def parse_evaluator(spec: str) -> Evaluator:
    """Parse an evaluator spec string.

    Global: "AUC", "RMSE", ... Per-query: "AUC:queryId" or
    "PRECISION@5:queryId" (reference MultiEvaluatorType name grammar).
    """
    spec = spec.strip()
    if ":" in spec:
        metric, id_col = spec.split(":", 1)
        metric = metric.strip().upper()
        id_col = id_col.strip()
        if not id_col:
            raise ValueError(f"Per-query evaluator '{spec}' is missing an id column")
        if metric.startswith("PRECISION@"):
            k_str = metric.split("@", 1)[1]
            if not k_str.isdigit() or int(k_str) < 1:
                raise ValueError(
                    f"Bad precision@k spec '{spec}': k must be a positive integer"
                )
            k = int(k_str)
            return MultiEvaluator(
                name=f"PRECISION@{k}:{id_col}",
                larger_is_better=True,
                id_column=id_col,
                local_fn=lambda s, y, w, _k=k: lm.precision_at_k(_k, s, y, w),
            )
        if metric not in _LOCAL_FOR_MULTI:
            raise ValueError(f"Unsupported per-query metric '{metric}'")
        larger, fn, both = _LOCAL_FOR_MULTI[metric]
        return MultiEvaluator(
            name=f"{metric}:{id_col}",
            larger_is_better=larger,
            id_column=id_col,
            local_fn=fn,
            requires_both_classes=both,
        )
    metric = spec.upper()
    if metric not in _GLOBALS:
        raise ValueError(f"Unknown evaluator '{spec}'")
    name, larger, fn = _GLOBALS[metric]
    return _GlobalEvaluator(name=name, larger_is_better=larger, fn=fn)


def default_evaluator_for_task(task: TaskType) -> Evaluator:
    """Reference: training-loss evaluator selection in
    GameEstimator.prepareTrainingLossEvaluator (GameEstimator.scala:592-614)."""
    mapping = {
        TaskType.LOGISTIC_REGRESSION: "LOGISTIC_LOSS",
        TaskType.LINEAR_REGRESSION: "SQUARED_LOSS",
        TaskType.POISSON_REGRESSION: "POISSON_LOSS",
        TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: "SMOOTHED_HINGE_LOSS",
    }
    return parse_evaluator(mapping[task])
