from photon_ml_tpu.evaluation.evaluators import (  # noqa: F401
    EvaluationData,
    Evaluator,
    MultiEvaluator,
    default_evaluator_for_task,
    parse_evaluator,
)
from photon_ml_tpu.evaluation import local_metrics  # noqa: F401
