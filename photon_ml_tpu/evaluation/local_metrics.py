"""Vectorized metric primitives (weighted, tie-aware), host-side float64.

Reference parity: photon-api evaluation/*.scala — AUC/AUPR via Spark MLLIB
BinaryClassificationMetrics, RMSE, per-task losses, and the local evaluators
used per query (AreaUnderROCCurveLocalEvaluator.scala,
PrecisionAtKLocalEvaluator.scala).

Evaluation runs once per coordinate update, not in the jitted hot loop, so
these are numpy (float64, exact tie handling via sort + run-boundary
arithmetic — the vectorized replacement of groupByKey + local computation).
"""

from __future__ import annotations

import numpy as np


def _as1d(a) -> np.ndarray:
    return np.asarray(a, dtype=np.float64).reshape(-1)


def area_under_roc_curve(scores, labels, weights=None) -> float:
    """Weighted AUC with average-rank tie handling (Mann-Whitney form).

    AUC = [ Σ_{i∈pos} w_i (W⁻_{<s_i} + ½ W⁻_{=s_i}) ] / (W⁺ W⁻)
    """
    s, y = _as1d(scores), _as1d(labels)
    w = np.ones_like(s) if weights is None else _as1d(weights)
    pos = y > 0.5
    w_pos = np.where(pos, w, 0.0)
    w_neg = np.where(~pos, w, 0.0)
    wp, wn = w_pos.sum(), w_neg.sum()
    if wp == 0.0 or wn == 0.0:
        return float("nan")
    order = np.argsort(s, kind="stable")
    s_sorted = s[order]
    wneg_sorted = w_neg[order]
    cum_neg = np.concatenate([[0.0], np.cumsum(wneg_sorted)])
    left = np.searchsorted(s_sorted, s_sorted, side="left")
    right = np.searchsorted(s_sorted, s_sorted, side="right")
    neg_less = cum_neg[left]
    neg_eq = cum_neg[right] - cum_neg[left]
    contrib = w_pos[order] * (neg_less + 0.5 * neg_eq)
    return float(contrib.sum() / (wp * wn))


def area_under_precision_recall_curve(scores, labels, weights=None) -> float:
    """Weighted AUPR via trapezoidal area on the PR curve evaluated at
    distinct-score thresholds (matches MLLIB's areaUnderPR construction,
    including the (0, p_first) starting point)."""
    s, y = _as1d(scores), _as1d(labels)
    w = np.ones_like(s) if weights is None else _as1d(weights)
    order = np.argsort(-s, kind="stable")
    s_desc = s[order]
    tp_w = np.where(y[order] > 0.5, w[order], 0.0)
    all_w = w[order]
    total_pos = tp_w.sum()
    if total_pos == 0.0:
        return float("nan")
    cum_tp = np.cumsum(tp_w)
    cum_all = np.cumsum(all_w)
    # threshold boundaries: last index of each tie-run of equal scores
    is_run_end = np.concatenate([s_desc[1:] != s_desc[:-1], [True]])
    tp_k = cum_tp[is_run_end]
    all_k = cum_all[is_run_end]
    precision = np.divide(tp_k, all_k, out=np.zeros_like(tp_k), where=all_k > 0)
    recall = tp_k / total_pos
    r = np.concatenate([[0.0], recall])
    p = np.concatenate([[precision[0] if len(precision) else 1.0], precision])
    return float(np.sum((r[1:] - r[:-1]) * 0.5 * (p[1:] + p[:-1])))


def root_mean_squared_error(scores, labels, weights=None) -> float:
    s, y = _as1d(scores), _as1d(labels)
    w = np.ones_like(s) if weights is None else _as1d(weights)
    wsum = w.sum()
    if wsum == 0.0:
        return float("nan")
    return float(np.sqrt(np.sum(w * (s - y) ** 2) / wsum))


def mean_absolute_error(scores, labels, weights=None) -> float:
    s, y = _as1d(scores), _as1d(labels)
    w = np.ones_like(s) if weights is None else _as1d(weights)
    wsum = w.sum()
    if wsum == 0.0:
        return float("nan")
    return float(np.sum(w * np.abs(s - y)) / wsum)


def logistic_loss(scores, labels, weights=None) -> float:
    """Mean weighted logistic loss of margins (reference LogisticLossEvaluator)."""
    s, y = _as1d(scores), _as1d(labels)
    w = np.ones_like(s) if weights is None else _as1d(weights)
    wsum = w.sum()
    # stable softplus
    loss = np.logaddexp(0.0, s) - y * s
    return float(np.sum(w * loss) / wsum) if wsum else float("nan")


def squared_loss(scores, labels, weights=None) -> float:
    s, y = _as1d(scores), _as1d(labels)
    w = np.ones_like(s) if weights is None else _as1d(weights)
    wsum = w.sum()
    return float(np.sum(w * 0.5 * (s - y) ** 2) / wsum) if wsum else float("nan")


def poisson_loss(scores, labels, weights=None) -> float:
    s, y = _as1d(scores), _as1d(labels)
    w = np.ones_like(s) if weights is None else _as1d(weights)
    wsum = w.sum()
    loss = np.exp(s) - y * s
    return float(np.sum(w * loss) / wsum) if wsum else float("nan")


def smoothed_hinge_loss(scores, labels, weights=None) -> float:
    s, y = _as1d(scores), _as1d(labels)
    w = np.ones_like(s) if weights is None else _as1d(weights)
    wsum = w.sum()
    t = (2.0 * y - 1.0) * s
    loss = np.where(t <= 0.0, 0.5 - t, np.where(t < 1.0, 0.5 * (1.0 - t) ** 2, 0.0))
    return float(np.sum(w * loss) / wsum) if wsum else float("nan")


def precision_at_k(k: int, scores, labels, weights=None) -> float:
    """Fraction of positives among the top-k scored items
    (reference PrecisionAtKLocalEvaluator.scala; per-query use)."""
    s, y = _as1d(scores), _as1d(labels)
    order = np.argsort(-s, kind="stable")
    top = order[: min(k, len(order))]
    if len(top) == 0:
        return float("nan")
    return float((y[top] > 0.5).mean())
