from photon_ml_tpu.serving.batching import (
    MicroBatchServer,
    RequestError,
    ServeError,
    ServeFuture,
    ServeTimeout,
)
from photon_ml_tpu.serving.resident import (
    DEFAULT_MICROBATCH_SHAPES,
    ModelSwapError,
    ResidentScorer,
)

__all__ = [
    "DEFAULT_MICROBATCH_SHAPES",
    "MicroBatchServer",
    "ModelSwapError",
    "RequestError",
    "ResidentScorer",
    "ServeError",
    "ServeFuture",
    "ServeTimeout",
]
