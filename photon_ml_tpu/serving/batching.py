"""Request queue + deadline-aware micro-batching loop for the resident
scorer.

Reference parity: photon-client cli/game/scoring/GameScoringDriver.scala
(:133-194) scores one partitioned dataset per job — its "batching" is the
Spark partition. An online service instead coalesces a stream of small
requests: a bounded queue feeds ONE consumer thread that flushes a
micro-batch on max-batch-rows or max-wait, whichever comes first, merges
the requests into one GameDataset (``concat_game_datasets``), and issues a
single bucketed dispatch through :class:`serving.resident.ResidentScorer`
— on this platform each dispatch costs ~80-110 ms of tunnel latency, so
requests-per-dispatch is the throughput lever.

Failure discipline (the chaos-suite contract):

- **A poisoned request fails THAT request, never the loop.** A batch-level
  scoring failure routes through ``resilience.classify_exception`` and
  falls back to per-request isolation: each request is re-scored alone, so
  only the poisoned one surfaces — as a :class:`RequestError` attributed
  with its request id — while the rest resolve normally and the loop keeps
  serving.
- **Nothing waits unbounded.** ``submit`` times out typed when the bounded
  queue stays full; ``ServeFuture.result`` times out typed
  (:class:`ServeTimeout`) when the consumer wedges; ``stop()`` joins the
  consumer with a bounded deadline and fails any still-queued futures —
  the StreamDecodeError discipline (io/stream_reader.py), because the
  chaos suite has no pytest-timeout to save it.
- **Observable.** Per-request latency (perf_counter, submit→resolve),
  queue depth, request/batch/pad counters feed the process-wide registry
  (telemetry/serving_counters.py); ``serve/`` spans observe — they never
  gate or reorder a dispatch.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time

import numpy as np

from photon_ml_tpu.data.game_data import GameDataset, concat_game_datasets
from photon_ml_tpu.resilience import classify_exception
from photon_ml_tpu.telemetry import serving_counters, tracing

#: default flush deadline: a request waits at most this long for batch
#: company before the loop dispatches what it has
DEFAULT_MAX_WAIT_MS = 2.0

#: default bounded queue depth; submit times out typed when exceeded
DEFAULT_QUEUE_DEPTH = 1024

#: default bound on ServeFuture.result — generous for a compile-on-first-
#: request, bounded so a wedged consumer surfaces typed instead of hanging
DEFAULT_RESULT_TIMEOUT = 60.0

#: bounded join for the consumer thread at stop()
JOIN_TIMEOUT = 10.0


class ServeError(RuntimeError):
    """Serving-layer failure (queue rejected, server stopped)."""


class RequestError(ServeError):
    """ONE request failed (poisoned input or scoring error); the message
    carries the request id. The serving loop itself keeps running."""


class ServeTimeout(ServeError):
    """A bounded serving deadline expired (result wait, queue admission) —
    the typed hang-free surface of a wedged consumer."""


class ServeFuture:
    """Result handle for one submitted request."""

    def __init__(self, request_id: str):
        self.request_id = request_id
        self._event = threading.Event()
        self._scores: np.ndarray | None = None
        self._error: BaseException | None = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None) -> np.ndarray:
        """Block (bounded) for the request's scores; raises the request's
        own typed failure, or :class:`ServeTimeout` when no result arrives
        within ``timeout`` (default DEFAULT_RESULT_TIMEOUT) — a wedged
        serving loop surfaces here, attributed, never as a hang."""
        bound = DEFAULT_RESULT_TIMEOUT if timeout is None else float(timeout)
        if not self._event.wait(bound):
            raise ServeTimeout(
                f"request {self.request_id!r}: no result within "
                f"{bound:.1f}s (wedged serving loop?)"
            )
        if self._error is not None:
            raise self._error
        return self._scores

    def _resolve(self, scores: np.ndarray) -> None:
        # first write wins: a stop()-drain fail racing a late consumer
        # resolve must not leave a future carrying both states
        if self._event.is_set():
            return
        self._scores = scores
        self._event.set()

    def _fail(self, error: BaseException) -> None:
        if self._event.is_set():
            return
        self._error = error
        self._event.set()


@dataclasses.dataclass
class _Queued:
    request_id: str
    dataset: GameDataset
    future: ServeFuture
    rows: int
    t_submit: float


class MicroBatchServer:
    """Bounded-queue micro-batching loop over a :class:`ResidentScorer`.

    Use as a context manager (or ``start()``/``stop()``); ``submit`` a
    GameDataset request, hold the returned :class:`ServeFuture`. The loop
    flushes a micro-batch when queued rows reach ``max_batch_rows``
    (default: the scorer's largest bucket) or the oldest queued request
    has waited ``max_wait_ms`` — whichever comes first.
    """

    def __init__(
        self,
        scorer,
        *,
        max_wait_ms: float = DEFAULT_MAX_WAIT_MS,
        max_batch_rows: int | None = None,
        queue_depth: int = DEFAULT_QUEUE_DEPTH,
        submit_timeout: float = 1.0,
    ):
        self.scorer = scorer
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.max_batch_rows = int(
            max_batch_rows if max_batch_rows is not None
            else scorer.shapes[-1]
        )
        if self.max_batch_rows <= 0:
            raise ValueError("max_batch_rows must be positive")
        self.submit_timeout = float(submit_timeout)
        self._queue: "queue.Queue[_Queued]" = queue.Queue(
            maxsize=max(1, int(queue_depth))
        )
        self._carry: _Queued | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._seq = 0
        self._seq_lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "MicroBatchServer":
        if self._thread is not None:
            raise ServeError("server already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._serve_loop, name="serve-microbatch", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Idempotent bounded shutdown: the consumer joins within
        JOIN_TIMEOUT and every still-queued request fails typed (never a
        silently-lost future)."""
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=JOIN_TIMEOUT)
        leftovers = []
        if self._carry is not None:
            leftovers.append(self._carry)
            self._carry = None
        try:
            while True:
                leftovers.append(self._queue.get_nowait())
        except queue.Empty:
            pass
        for item in leftovers:
            item.future._fail(ServeError(
                f"request {item.request_id!r}: server stopped before "
                "serving it"
            ))

    def __enter__(self) -> "MicroBatchServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()

    def swap_model(self, new_model) -> None:
        """Zero-downtime model refresh against a LIVE serving loop:
        delegates to the scorer's guarded swap API
        (``ResidentScorer.swap_model`` — the one sanctioned resident-param
        mutation site, lint check 14) while the consumer thread keeps
        draining the queue. A same-layout swap is a reference assignment
        the consumer picks up at its next micro-batch (requests in flight
        score under whichever model is current at dispatch — both versions'
        scores are correct GAME scores); a layout-changing swap raises
        typed (``ModelSwapError`` naming the differing leaves) and the loop
        keeps serving the resident model."""
        self.scorer.swap_model(new_model)

    # -- producer side -------------------------------------------------------

    def submit(self, dataset: GameDataset,
               request_id: str | None = None) -> ServeFuture:
        """Enqueue one request; returns its future. Raises
        :class:`ServeTimeout` when the bounded queue stays full past
        ``submit_timeout`` (backpressure surfaces at the caller, typed),
        :class:`ServeError` when the server is not running."""
        if self._thread is None or self._stop.is_set():
            raise ServeError("server is not running (call start())")
        if dataset.num_samples == 0:
            raise ValueError("empty request dataset")
        with self._seq_lock:
            self._seq += 1
            rid = request_id if request_id is not None else f"req-{self._seq}"
        item = _Queued(
            request_id=rid,
            dataset=dataset,
            future=ServeFuture(rid),
            rows=dataset.num_samples,
            t_submit=time.perf_counter(),
        )
        try:
            self._queue.put(item, timeout=self.submit_timeout)
        except queue.Full:
            raise ServeTimeout(
                f"request {rid!r}: queue full "
                f"(depth {self._queue.maxsize}) for "
                f"{self.submit_timeout:.1f}s — the serving loop is not "
                "keeping up"
            ) from None
        serving_counters.record_request()
        serving_counters.set_queue_depth(self._queue.qsize())
        if self._stop.is_set() and not item.future.done():
            # the put raced a concurrent stop(): its drain may already
            # have missed this item, which would otherwise stall the
            # caller into a misattributed ServeTimeout — fail it typed
            # here (first write wins, so a consumer that did serve it in
            # the window keeps its result)
            item.future._fail(ServeError(
                f"request {rid!r}: server stopped before serving it"
            ))
        return item.future

    # -- consumer side -------------------------------------------------------

    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            first = self._carry
            self._carry = None
            if first is None:
                try:
                    first = self._queue.get(timeout=0.05)
                except queue.Empty:
                    continue
            batch = [first]
            rows = first.rows
            # the flush window opens when the batch starts FORMING, not at
            # the first request's submit time: under a burst the submit
            # anchor is already expired at pickup, degenerating every
            # flush to a single request — the window is the knob bounding
            # ADDED latency, so it must actually buy batch company
            deadline = time.perf_counter() + self.max_wait_s
            while rows < self.max_batch_rows and not self._stop.is_set():
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=min(remaining, 0.05))
                except queue.Empty:
                    continue
                if rows + nxt.rows > self.max_batch_rows:
                    # would overflow the batch budget: serve it next round
                    self._carry = nxt
                    break
                batch.append(nxt)
                rows += nxt.rows
            serving_counters.set_queue_depth(self._queue.qsize())
            self._flush(batch, rows)

    def _flush(self, batch: "list[_Queued]", rows: int) -> None:
        with tracing.span("serve/batch", cat="serve",
                          requests=len(batch), rows=rows):
            try:
                merged = (
                    batch[0].dataset if len(batch) == 1
                    else concat_game_datasets([r.dataset for r in batch])
                )
                scores = self.scorer.score(merged)
            except Exception as exc:
                # batch-level failure: classify for the record, then
                # isolate — ONE poisoned request must fail attributed
                # while the rest (and the loop) keep serving (reviewed
                # allowlist entry in dev/lint_parity.py check 5)
                classify_exception(exc)
                self._isolate(batch)
                return
            serving_counters.record_batch()
            lo = 0
            for item in batch:
                item.future._resolve(scores[lo:lo + item.rows])
                lo += item.rows
                serving_counters.record_request_latency_ms(
                    (time.perf_counter() - item.t_submit) * 1e3
                )

    def _isolate(self, batch: "list[_Queued]") -> None:
        """Per-request fallback after a batch failure: each request scores
        alone, so exactly the poisoned ones fail — typed and attributed."""
        for item in batch:
            try:
                scores = self.scorer.score(item.dataset)
            except Exception as exc:
                # the request's own failure, classified and attributed to
                # its id; the loop survives (reviewed allowlist entry in
                # dev/lint_parity.py check 5)
                classify_exception(exc)
                err = RequestError(
                    f"request {item.request_id!r} failed: "
                    f"{type(exc).__name__}: {exc}"
                )
                err.__cause__ = exc
                item.future._fail(err)
                serving_counters.record_request_failure()
                continue
            item.future._resolve(scores)
            serving_counters.record_request_latency_ms(
                (time.perf_counter() - item.t_submit) * 1e3
            )
