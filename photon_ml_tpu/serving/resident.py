"""Resident scorer: one pre-placed model, a bounded set of compiled
micro-batch score programs.

Reference parity: photon-api transformers/GameTransformer.scala:156-203 —
the reference's scoring is a per-partition batch task that rebuilds its
scorer every job. Here the model placement half of that work is hoisted
out of the request path entirely: a :class:`ResidentScorer` builds and
places the GameModel's device params ONCE (FE coefficient vectors, compact
``[E, K]`` RE tables, MF factors — ``DistributedScorer``'s separable
``params_for_layouts`` half) and keeps them resident across calls, the
Snap ML pre-placed-buffer discipline (arXiv:1803.06333). Each request then
pays only dataset assembly + one dispatch of an already-compiled program.

Why shape buckets: XLA compiles one program per input-shape signature, and
on this platform a dispatch costs ~80-110 ms of tunnel latency while a
fresh compile costs far more — an online scorer that compiles per request
size would miss every latency SLO it has. Requests therefore pad into a
SMALL FIXED SET of power-of-two micro-batch shapes (the lane-scheduler
trick reapplied: bounded jit-signature set; pads carry weight 0 /
entity-index −1 / zero feature rows, so they are inert — the framework
padding contract), and sparse entry axes pad to power-of-two lengths the
same way. A request larger than the biggest bucket SPLITS across
micro-batches instead of compiling a new signature.

The whole serving step is ONE traced program end to end (the DrJAX
argument, arXiv:2403.07128): params and the micro-batch both enter the jit
as ARGUMENTS — never closure constants (the measured HTTP-413 landmine;
lint check 9 covers this package) — with the micro-batch buffers DONATED
so steady-state serving reuses device memory instead of allocating per
request. The opt-in bf16 path casts feature blocks AND model params, the
whole path, because a mixed-dtype matmul silently upcasts (the measured
no-op-bf16 landmine).
"""

from __future__ import annotations

import bisect

import numpy as np

from photon_ml_tpu.data.game_data import (
    GameDataset,
    concat_game_datasets,
    pad_game_dataset_to,
    slice_game_dataset,
)
from photon_ml_tpu.models.game import GameModel
from photon_ml_tpu.parallel.scoring import DistributedScorer, _pad_nnz
from photon_ml_tpu.telemetry import program_ledger, serving_counters, tracing
from photon_ml_tpu.telemetry.program_ledger import ledger_jit

#: default micro-batch shape buckets (rows); requests pad to the smallest
#: bucket that fits and split across the largest when they exceed it
DEFAULT_MICROBATCH_SHAPES = (64, 256, 1024)

#: floor for the power-of-two padding of sparse entry axes — tiny requests
#: share one signature instead of minting one per nnz
MIN_NNZ_BUCKET = 64


def _next_pow2(n: int) -> int:
    return 1 << max(0, int(n - 1).bit_length())


class ModelSwapError(ValueError):
    """A hot swap was rejected by the layout fingerprint guard: the new
    model's params layout differs from the resident one's (the message
    names the differing leaves). The resident model keeps serving — a
    layout change needs a fresh scorer (and a warm-up), never an in-place
    swap."""


class ResidentScorer:
    """A GameModel resident on device behind a bounded set of compiled
    micro-batch score programs.

    shapes: the micro-batch shape buckets (positive powers of two,
    ascending); with a mesh each must divide the mesh "data" axis.
    bf16: opt-in whole-path bf16 features+params (NOT bitwise; the default
    f32 path is pinned bitwise against ``DistributedScorer.score_dataset``).
    donate: donate the micro-batch input buffers to the program (None =
    auto: on for real accelerators, off for the CPU backend where XLA
    cannot use them and warns per call).
    """

    def __init__(
        self,
        model: GameModel,
        *,
        shapes=DEFAULT_MICROBATCH_SHAPES,
        mesh=None,
        fe_feature_sharded: "bool | str" = False,
        bf16: bool = False,
        donate: bool | None = None,
    ):
        import jax

        shapes = tuple(int(s) for s in shapes)
        if not shapes:
            raise ValueError("shapes must name at least one micro-batch size")
        for s in shapes:
            if s <= 0 or s & (s - 1):
                raise ValueError(
                    f"micro-batch shape {s} is not a positive power of two — "
                    "the bucket set bounds the compiled-signature count only "
                    "when shapes come from a fixed geometric ladder"
                )
        if sorted(set(shapes)) != list(shapes):
            raise ValueError(f"shapes must be ascending and unique: {shapes}")
        if jax.process_count() > 1:
            raise ValueError(
                "ResidentScorer is the single-process serving path; "
                "multi-process batch scoring goes through "
                "DistributedScorer.score_partitioned"
            )
        self._scorer = DistributedScorer(
            model, mesh, fe_feature_sharded=fe_feature_sharded
        )
        if mesh is not None:
            data_axis = int(mesh.shape["data"])
            for s in shapes:
                if s % data_axis:
                    raise ValueError(
                        f"micro-batch shape {s} does not divide the mesh "
                        f"data axis {data_axis}"
                    )
        self.model = model
        self.shapes = shapes
        self.bf16 = bool(bf16)
        if donate is None:
            donate = jax.default_backend() != "cpu"
        self.donate = bool(donate)
        # Reviewed jit site (lint check 9 allowlist): BOTH operands —
        # the micro-batch data AND the pre-placed model params — enter the
        # program as ARGUMENTS; nothing request- or model-sized is closed
        # over. donate_argnums=(0,) donates only the per-request data
        # buffers; params survive every call (they are the resident state).
        # The program carries the "serve/score" ledger label (ISSUE 13):
        # with a ProgramLedger installed, every serving compile — warm or,
        # pathologically, mid-replay — journals its signature and
        # recompile attribution under that label. The non-donate path
        # therefore owns its program instead of aliasing the batch
        # scorer's (serving compiles must not hide under
        # score/score_dataset); the jit caches only coincided when a
        # micro-batch signature exactly matched a prior full-dataset
        # score, so the bound stays the bucket set either way.
        self._program = ledger_jit(
            self._scorer._score_impl, label="serve/score",
            donate_argnums=(0,) if self.donate else (),
        )
        self._bf16_params_cache: dict = {}
        #: bumped by swap_model: bf16 cache keys carry it, so entries a
        #: racing reader computes from a superseded model are never read
        self._model_version = 0
        self._signatures: set = set()

    # -- program inputs ------------------------------------------------------

    @property
    def signatures(self) -> "frozenset":
        """(bucket, layout, nnz-bucket) signatures scored so far — bounded
        by the configured shape set times the model's (fixed) layout."""
        return frozenset(self._signatures)

    def _bucket_for(self, n: int) -> int:
        i = bisect.bisect_left([s for s in self.shapes], n)
        return self.shapes[min(i, len(self.shapes) - 1)]

    def _cast_bf16(self, tree):
        import jax
        import jax.numpy as jnp

        def cast(leaf):
            if hasattr(leaf, "dtype") and jnp.issubdtype(
                jnp.asarray(leaf).dtype, jnp.floating
            ):
                return jnp.asarray(leaf, jnp.bfloat16)
            return leaf

        return jax.tree_util.tree_map(cast, tree)

    def _params(self, layouts):
        # version read BEFORE the params fetch: a swap committing in
        # between bumps the version, so whatever this thread caches below
        # lands under the superseded key and is never read again (the
        # other order would cache OLD params under the NEW version)
        version = self._model_version
        params = self._scorer.params_for_layouts(layouts)
        if not self.bf16:
            return params
        key = (version, tuple(sorted(layouts.items())))
        cached = self._bf16_params_cache.get(key)
        if cached is None:
            cached = self._bf16_params_cache[key] = self._cast_bf16(params)
        return cached

    def _pad_entry_axes(self, data, xp) -> tuple:
        """Pad every flat entry axis (sparse FE triples, compact-RE entry
        lists) to a power-of-two length so the nnz axis joins the bounded
        signature set; pads are inert (value 0, repeated last row id, the
        compact scratch slot). Returns (data, nnz signature tuple)."""
        mesh = self._scorer.mesh
        data_axis = int(mesh.shape["data"]) if mesh is not None else 1
        nnz_sig = []
        for cid, c in data["coords"].items():
            if "sparse" in c:
                nnz = int(np.shape(c["sparse"]["vals"])[0])
                target = max(_next_pow2(max(nnz, 1)), MIN_NNZ_BUCKET,
                             data_axis)
                c["sparse"] = _pad_nnz(
                    dict(c["sparse"]), data_axis, xp=xp, target=target
                )
                nnz_sig.append((cid, target))
            if "entries" in c:
                nnz = int(np.shape(c["entries"]["vals"])[0])
                target = max(_next_pow2(max(nnz, 1)), MIN_NNZ_BUCKET,
                             data_axis)
                k_scratch = int(
                    self.model.models[cid].coefficients.shape[1]
                )
                c["entries"] = _pad_nnz(
                    dict(c["entries"]), data_axis, xp=xp, target=target,
                    pad_values={"pos": k_scratch},
                )
                nnz_sig.append((cid, target))
        return data, tuple(nnz_sig)

    # -- zero-downtime model refresh ----------------------------------------

    def swap_model(self, new_model: GameModel) -> None:
        """In-place hot swap to a refreshed model while requests keep
        flowing — the serving half of incremental retraining
        (algorithm/refresh.py). Params are jit ARGUMENTS keyed by layout,
        so an EQUAL-layout swap re-uses every compiled score program
        (``xla/serve/score`` compile delta == 0, ledger-pinned by
        tests/test_serving.py); a layout-changing model raises
        :class:`ModelSwapError` naming the differing leaves BEFORE any
        state mutates, and the resident model keeps serving.

        This method is the ONE sanctioned resident-param mutation site in
        the serving package (dev/lint_parity.py check 14): the new params
        are built and placed fully off to the side, then committed by
        reference assignment (atomic under the GIL), so a concurrent
        micro-batch scores either the old or the new model — never a mix.
        """
        try:
            # the layout fingerprint guard lives in the ONE inner API
            # (parallel/scoring.py swap_model_params): validate-then-
            # commit, nothing mutates on rejection. It also rebuilds +
            # re-places the layout-keyed params cache and re-feeds
            # serve/resident_params_bytes (the HBM-forecast input).
            self._scorer.swap_model_params(new_model)
        except ValueError as e:
            serving_counters.record_swap_rejected()
            raise ModelSwapError(
                f"model swap rejected: {e} — build a fresh ResidentScorer "
                "(and warm it) for a layout-changing refresh"
            ) from e
        self.model = new_model
        # version-keyed bf16 cache: a scorer thread racing the swap may
        # still INSERT an entry computed from the old model after this
        # reset — the version bump makes stale entries unreachable
        # instead of served
        self._model_version += 1
        self._bf16_params_cache = {}
        serving_counters.record_model_swap()
        ledger = program_ledger.current_ledger()
        if ledger is not None:
            # no compile fires on an equal-layout swap, so the per-label
            # HBM forecast must be re-fed by hand or it keeps pricing the
            # stale model's resident bytes (ISSUE 13 accounting)
            ledger.refeed_resident_forecast("serve/score")

    # -- scoring -------------------------------------------------------------

    def score(self, dataset: GameDataset) -> np.ndarray:
        """[n] host scores INCLUDING offsets (``score_dataset`` semantics)
        for one request, through the bucketed resident program. Requests
        larger than the biggest bucket split across micro-batches (never a
        fresh compile)."""
        n = dataset.num_samples
        if n == 0:
            return np.zeros((0,), np.float32)
        max_shape = self.shapes[-1]
        if n > max_shape:
            serving_counters.record_bucket_split()
            parts = [
                self._score_bucketed(slice_game_dataset(dataset, lo,
                                                        min(lo + max_shape, n)))
                for lo in range(0, n, max_shape)
            ]
            return np.concatenate(parts)
        return self._score_bucketed(dataset)

    def _score_bucketed(self, dataset: GameDataset) -> np.ndarray:
        import jax.numpy as jnp

        import jax

        n = dataset.num_samples
        bucket = self._bucket_for(n)
        with tracing.span("serve/score", cat="serve", rows=n, bucket=bucket):
            padded, _ = pad_game_dataset_to(dataset, bucket)
            data, layouts = self._scorer._build_data_host(padded, jnp)
            data, nnz_sig = self._pad_entry_axes(data, jnp)
            if self.donate and padded is dataset:
                # pad == 0: the built data aliases the request dataset's
                # own device arrays (jnp.asarray no-ops), and donating
                # them would delete the caller's buffers — a later score
                # of the same dataset (warm-up reuse, per-request
                # isolation retry) would hit 'Array has been deleted'.
                # Padded requests build fresh host arrays, so only this
                # branch needs the defensive copy.
                data = jax.tree_util.tree_map(
                    lambda a: jnp.array(a, copy=True), data
                )
            if self.bf16:
                # feature blocks only: the whole matmul path runs bf16
                # against the bf16 params (a mixed-dtype matmul would
                # silently upcast); offsets/indices stay as built
                data["coords"] = {
                    cid: {
                        k: (self._cast_bf16(v) if k in ("x", "sparse",
                                                        "entries") else v)
                        for k, v in c.items()
                    }
                    for cid, c in data["coords"].items()
                }
            if self._scorer.mesh is not None:
                data = self._scorer._place_data(data)
            params = self._params(layouts)
            sig = (bucket, tuple(sorted(layouts.items())), nnz_sig)
            self._signatures.add(sig)
            if self._scorer.mesh is not None:
                with self._scorer.mesh:
                    out = self._program(data, params)
            else:
                out = self._program(data, params)
            # the compiled-signature gauge is ledger-backed (ISSUE 13):
            # with a ProgramLedger installed the count comes from the
            # "serve/score" program's observed signature registry; the
            # local (bucket, layout, nnz) set is the fallback — and stays
            # the public ``signatures`` property either way
            ledger = program_ledger.current_ledger()
            ledger_sigs = (
                ledger.signature_count("serve/score")
                if ledger is not None else 0
            )
            serving_counters.set_compiled_signatures(
                ledger_sigs or len(self._signatures)
            )
            scores = np.asarray(out)[:n]
            serving_counters.record_scored(rows=n, padded_rows=bucket - n)
        if scores.dtype != np.float32 and self.bf16:
            scores = scores.astype(np.float32)
        return scores

    def warm(self, example: GameDataset) -> int:
        """Compile every bucket signature up front from an example request
        (rows are recycled as needed) so the first live requests never pay
        a compile; returns the number of signatures now resident."""
        n = example.num_samples
        if n == 0:
            raise ValueError("warm() needs a non-empty example dataset")
        for shape in self.shapes:
            take = min(n, shape)
            part = slice_game_dataset(example, 0, take) if take < n else example
            reps = -(-shape // take)
            if reps > 1:
                part = concat_game_datasets([part] * reps)
                part = slice_game_dataset(part, 0, shape)
            self._score_bucketed(part)
        return len(self._signatures)
