"""Driver-level crash-safe recovery: restore-and-resume instead of abort.

No reference analogue as code: the reference driver aborts on any
exception and relies on Spark lineage + coarse per-configuration model
re-use for recovery (GameTrainingDriver.scala:748-815 saves models per
optimization config; there is no mid-sweep resume). Here the training
sweep owns real mid-training checkpoints (io/checkpoint.py), so a
mid-sweep failure that is either

- a :class:`~photon_ml_tpu.io.checkpoint.DivergenceError` (non-finite
  coordinate update) with an intact checkpoint to fall back to, or
- a classified-transient error (dropped tunnel, flaky filesystem —
  resilience/errors.classify_exception)

restarts the attempt instead of aborting: the re-created estimator
resumes from the latest intact checkpoint (run_coordinate_descent's
fast-forward) and the run continues. Restarts are capped by
``max_restarts``; exhaustion re-raises after counting a
``resilience/giveups``. Every restart counts on ``resilience/retries``
and journals a ``resilience_restart`` row; the checkpoint restore itself
counts on ``resilience/checkpoint_restores`` (incremented at the restore
site in algorithm/coordinate_descent.py).
"""

from __future__ import annotations

import logging
from typing import Callable

from photon_ml_tpu.resilience.errors import (
    Transience,
    classify_exception,
    fatal_hint,
    is_preemption,
)
from photon_ml_tpu.telemetry import resilience_counters

logger = logging.getLogger(__name__)


def run_with_recovery(
    fn: Callable[[int], object],
    *,
    max_restarts: int = 2,
    checkpointer=None,
    classify: Callable = classify_exception,
    journal=None,
    description: str = "training",
):
    """Run ``fn(restart_index)`` with capped restore-and-resume restarts.

    fn: one full attempt; receives the 0-based restart index (the driver
        uses it to force ``resume=True`` on restarts even when the user
        passed ``--no-resume`` for the first attempt).
    checkpointer: optional ``io.checkpoint.TrainingCheckpointer``. A
        DivergenceError is only recoverable when a checkpoint step exists
        to restore (re-running a deterministic divergence from scratch
        would fail identically); transient errors restart either way.
    journal: optional ``telemetry.RunJournal`` for ``resilience_restart``
        rows.
    """
    from photon_ml_tpu.io.checkpoint import DivergenceError

    restart = 0
    while True:
        try:
            return fn(restart)
        except Exception as e:  # classified below; broad by design
            transient = classify(e) is Transience.TRANSIENT
            has_checkpoint = (
                checkpointer is not None
                and checkpointer.latest_step() is not None
            )
            divergent = isinstance(e, DivergenceError)
            recoverable = transient or (divergent and has_checkpoint)
            if not recoverable or restart >= max_restarts:
                if journal is not None:
                    # the run's terminal failure row (ISSUE 12): what
                    # dev/doctor.py names when a crashed run's journal —
                    # finalized by the driver's failure path, or the
                    # crash-durable stage of one that never closed — is
                    # read back
                    journal.record(
                        "run_failure",
                        description=description,
                        error=repr(e),
                        transient=transient,
                        divergent=divergent,
                        preemption=is_preemption(e),
                        restarts_used=restart,
                        max_restarts=max_restarts,
                    )
                if recoverable:
                    resilience_counters.record_giveup()
                    logger.error(
                        "%s: restart budget (%d) exhausted; giving up on %r",
                        description, max_restarts, e,
                    )
                elif divergent and not has_checkpoint:
                    logger.error(
                        "%s: diverged with no checkpoint to restore "
                        "(enable --checkpoint-dir for mid-sweep recovery): %r",
                        description, e,
                    )
                else:
                    hint = fatal_hint(e)
                    if hint is not None:
                        logger.error("%s: fatal failure %r. Hint: %s",
                                     description, e, hint)
                raise
            restart += 1
            resilience_counters.record_retry()
            # a device-loss / pool-preemption shape gets its own tally:
            # the counter that says the POOL (not flaky I/O) is exercising
            # the checkpoint cadence
            preempted = is_preemption(e)
            if preempted:
                resilience_counters.record_preemption()
            logger.warning(
                "%s: %s failure (%r) — restart %d/%d%s",
                description,
                "transient" if transient else "divergence",
                e,
                restart,
                max_restarts,
                (
                    f", resuming from checkpoint step "
                    f"{checkpointer.latest_step()}"
                    if has_checkpoint
                    else ", retrying from scratch"
                ),
            )
            if journal is not None:
                journal.record(
                    "resilience_restart",
                    description=description,
                    restart=restart,
                    max_restarts=max_restarts,
                    transient=transient,
                    divergent=divergent,
                    preemption=preempted,
                    resumed_from_step=(
                        checkpointer.latest_step() if has_checkpoint else None
                    ),
                    error=repr(e),
                )
