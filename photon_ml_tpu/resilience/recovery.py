"""Driver-level crash-safe recovery: restore-and-resume instead of abort.

No reference analogue as code: the reference driver aborts on any
exception and relies on Spark lineage + coarse per-configuration model
re-use for recovery (GameTrainingDriver.scala:748-815 saves models per
optimization config; there is no mid-sweep resume). Here the training
sweep owns real mid-training checkpoints (io/checkpoint.py), so a
mid-sweep failure that is either

- a :class:`~photon_ml_tpu.io.checkpoint.DivergenceError` (non-finite
  coordinate update) with an intact checkpoint to fall back to, or
- a classified-transient error (dropped tunnel, flaky filesystem —
  resilience/errors.classify_exception)

restarts the attempt instead of aborting: the re-created estimator
resumes from the latest intact checkpoint (run_coordinate_descent's
fast-forward) and the run continues. Restarts are capped by
``max_restarts``; exhaustion re-raises after counting a
``resilience/giveups``. Every restart counts on ``resilience/retries``
and journals a ``resilience_restart`` row; the checkpoint restore itself
counts on ``resilience/checkpoint_restores`` (incremented at the restore
site in algorithm/coordinate_descent.py).

MULTI-RANK runs attach a ``resilience.coordinated.CoordinatedRecovery``
(ISSUE 15): ``ExchangeTimeout`` and ``PeerAbort`` — always fatal on
their own — become recoverable VIA COORDINATION, every restart is an
all-rank rollback to the last barrier-committed checkpoint, and the
restart budget is the coordinator's SHARED generation count (a flapping
rank burns the JOB's budget, never a per-process one). The give-up
``run_failure`` row then names the originating rank + cause, so the
blamed rank is attributed identically from every rank's journal.
"""

from __future__ import annotations

import logging
from typing import Callable

from photon_ml_tpu.resilience.errors import (
    ExchangeTimeout,
    PeerAbort,
    Transience,
    classify_exception,
    fatal_hint,
    is_preemption,
)
from photon_ml_tpu.telemetry import resilience_counters

logger = logging.getLogger(__name__)


def run_with_recovery(
    fn: Callable[[int], object],
    *,
    max_restarts: int = 2,
    checkpointer=None,
    classify: Callable = classify_exception,
    journal=None,
    description: str = "training",
    coordinator=None,
):
    """Run ``fn(restart_index)`` with capped restore-and-resume restarts.

    fn: one full attempt; receives the 0-based restart index (the driver
        uses it to force ``resume=True`` on restarts even when the user
        passed ``--no-resume`` for the first attempt).
    checkpointer: optional ``io.checkpoint.TrainingCheckpointer``. A
        DivergenceError is only recoverable when a checkpoint step exists
        to restore (re-running a deterministic divergence from scratch
        would fail identically); transient errors restart either way.
    journal: optional ``telemetry.RunJournal`` for ``resilience_restart``
        rows.
    coordinator: optional ``resilience.coordinated.CoordinatedRecovery``
        — multi-rank mode. The coordinator's ``max_restarts`` (the SHARED
        job budget: the restart generation every rank agrees on) replaces
        the per-process ``max_restarts`` argument; ``ExchangeTimeout``
        and ``PeerAbort`` become recoverable; every restart first posts
        an abort marker for this rank's own failures (so peers fail fast
        attributed), then rendezvouses all ranks on the coordinated
        rollback. Detached (None) keeps the pre-existing single-process
        contract bit-for-bit.
    """
    from photon_ml_tpu.io.checkpoint import DivergenceError

    if coordinator is not None:
        max_restarts = coordinator.max_restarts
    restart = 0
    while True:
        try:
            return fn(restart)
        except Exception as e:  # classified below; broad by design
            transient = classify(e) is Transience.TRANSIENT
            has_checkpoint = (
                checkpointer is not None
                and checkpointer.latest_step() is not None
            )
            divergent = isinstance(e, DivergenceError)
            coordination_only = coordinator is not None and isinstance(
                e, (ExchangeTimeout, PeerAbort)
            )
            recoverable = (
                transient
                or (divergent and has_checkpoint)
                or coordination_only
            )
            # origin attribution rides the journal even on paths that never
            # reach the coordinator (e.g. a PeerAbort with no coordinator
            # attached, which stays fatal): the blamed rank must read the
            # same from every journal
            origin_rank = getattr(e, "origin_rank", None)
            origin_cause = getattr(e, "cause", None) if isinstance(
                e, PeerAbort
            ) else None
            decision = None
            if recoverable and coordinator is not None:
                # this rank's OWN failure: attribute it to the peers
                # before restarting (turns their deadline waits into
                # immediate PeerAborts naming this rank). Coordination
                # failures (PeerAbort/ExchangeTimeout) are someone
                # else's — never re-abort on them.
                if not isinstance(e, (PeerAbort, ExchangeTimeout)):
                    coordinator.post_abort(e)
                try:
                    decision = coordinator.coordinated_restart(e)
                except Exception as rendezvous_error:
                    # the rendezvous itself failed (a rank is truly gone,
                    # not restarting): the job dies attributed to the
                    # rendezvous failure, with the original error noted
                    if journal is not None:
                        journal.record(
                            "run_failure",
                            description=description,
                            error=repr(rendezvous_error),
                            original_error=repr(e),
                            transient=False,
                            divergent=divergent,
                            preemption=False,
                            restarts_used=restart,
                            max_restarts=max_restarts,
                            origin_rank=getattr(
                                rendezvous_error, "origin_rank", None
                            ),
                            origin_cause=None,
                        )
                    resilience_counters.record_giveup()
                    logger.error(
                        "%s: coordinated restart rendezvous failed (%r) "
                        "after %r; giving up",
                        description, rendezvous_error, e,
                    )
                    raise
                origin_rank = decision.origin_rank
                origin_cause = decision.origin_cause
            exhausted = (
                decision.exhausted if decision is not None
                else restart >= max_restarts
            )
            if not recoverable or exhausted:
                if journal is not None:
                    # the run's terminal failure row (ISSUE 12): what
                    # dev/doctor.py names when a crashed run's journal —
                    # finalized by the driver's failure path, or the
                    # crash-durable stage of one that never closed — is
                    # read back. With a coordinator the originating rank +
                    # cause ride along (ISSUE 15), so the blamed rank is
                    # attributed identically from every rank's journal.
                    journal.record(
                        "run_failure",
                        description=description,
                        error=repr(e),
                        transient=transient,
                        divergent=divergent,
                        preemption=is_preemption(e),
                        restarts_used=(
                            decision.restarts_used if decision is not None
                            else restart
                        ),
                        max_restarts=max_restarts,
                        origin_rank=origin_rank,
                        origin_cause=origin_cause,
                    )
                if recoverable:
                    resilience_counters.record_giveup()
                    logger.error(
                        "%s: restart budget (%d) exhausted; giving up on %r",
                        description, max_restarts, e,
                    )
                elif divergent and not has_checkpoint:
                    logger.error(
                        "%s: diverged with no checkpoint to restore "
                        "(enable --checkpoint-dir for mid-sweep recovery): %r",
                        description, e,
                    )
                else:
                    hint = fatal_hint(e)
                    if hint is not None:
                        logger.error("%s: fatal failure %r. Hint: %s",
                                     description, e, hint)
                raise
            restart = (
                decision.generation if decision is not None else restart + 1
            )
            resilience_counters.record_retry()
            # a device-loss / pool-preemption shape gets its own tally:
            # the counter that says the POOL (not flaky I/O) is exercising
            # the checkpoint cadence
            preempted = is_preemption(e)
            if preempted:
                resilience_counters.record_preemption()
            logger.warning(
                "%s: %s failure (%r) — restart %d/%d%s",
                description,
                (
                    "transient" if transient
                    else "coordination" if coordination_only
                    else "divergence"
                ),
                e,
                restart,
                max_restarts,
                (
                    f", resuming from checkpoint step "
                    f"{checkpointer.latest_step()}"
                    if has_checkpoint
                    else ", retrying from scratch"
                ),
            )
            if journal is not None:
                journal.record(
                    "resilience_restart",
                    description=description,
                    restart=restart,
                    max_restarts=max_restarts,
                    transient=transient,
                    divergent=divergent,
                    preemption=preempted,
                    resumed_from_step=(
                        decision.step if decision is not None
                        else checkpointer.latest_step()
                        if has_checkpoint else None
                    ),
                    origin_rank=origin_rank,
                    origin_cause=origin_cause,
                    error=repr(e),
                )
