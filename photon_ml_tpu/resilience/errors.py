"""Typed transient-vs-fatal error classification + attributed failure types.

No reference analogue as code: the reference's failure model is Spark's —
lineage recompute re-executes lost partitions and the driver retries failed
tasks (spark-submit/YARN substrate, not a photon-ml source file; SURVEY.md
§5). The TPU-native stack has none of that substrate, so every host-side
boundary (remote-compile/dispatch tunnels, Avro container reads,
coordination-service KV exchanges) needs an explicit answer to "is this
error worth retrying?". This module is that answer — ONE classifier every
retry/recovery site consults, so transient-vs-fatal policy lives in one
reviewed place instead of scattered ``except`` clauses (dev/lint_parity.py
bans broad excepts outside this layer's allowlist for exactly that reason).

Classification rules (in precedence order):

1. Explicit wrappers win: :class:`TransientError` is always transient;
   :class:`ExchangeTimeout` is always fatal (it is already ATTRIBUTED — the
   missing key/rank is named, and waiting the deadline again would just
   double the hang).
2. Known-poison signatures are fatal even when they smell transient: an
   HTTP 413 / "payload too large" from the remote-compile tunnel means a
   jit closed over a large constant (the r2 "compile service flakiness"
   that masqueraded as a dropped connection for a whole round — CLAUDE.md);
   retrying re-sends the same oversized request forever.
3. Connection/timeout exception types and transient OS errnos (EAGAIN,
   EIO, ETIMEDOUT, ECONNRESET, ...) are transient.
4. Message patterns of the distributed runtimes (UNAVAILABLE,
   DEADLINE_EXCEEDED, "socket closed", "connection reset", ...) are
   transient — jaxlib surfaces tunnel/coordination failures as RuntimeError
   subclasses whose TYPE carries no signal.
5. Everything else is fatal (ValueError, programming errors, divergence):
   retrying deterministic failures burns the budget and hides the bug.
"""

from __future__ import annotations

import enum
import errno
import re

#: OS errnos worth retrying: interrupted/expired I/O and dropped network
#: paths (a remote filesystem or the compile tunnel), never logic errors
TRANSIENT_ERRNOS = frozenset(
    {
        errno.EAGAIN,
        errno.EINTR,
        errno.EIO,
        errno.EBUSY,
        errno.ETIMEDOUT,
        errno.ECONNRESET,
        errno.ECONNABORTED,
        errno.ECONNREFUSED,
        errno.ENETRESET,
        errno.ENETUNREACH,
        errno.EHOSTUNREACH,
        errno.EPIPE,
    }
)

#: fatal-despite-the-smell signatures, checked BEFORE the transient
#: patterns. \b413\b is the measured one (word-bounded so ports/byte
#: counts like ":41352" never match): a jit that closed over a large
#: batch serializes it as a CONSTANT into the remote-compile request and
#: the tunnel rejects it — every retry re-sends the same bytes
#: (CLAUDE.md). "out of memory" covers XLA's deterministic device OOM
#: ("RESOURCE_EXHAUSTED: Out of memory while trying to allocate ...") —
#: re-dispatching the identical program OOMs identically.
_FATAL_PATTERNS = re.compile(
    r"\b413\b|payload too large|request entity too large"
    r"|INVALID_ARGUMENT|out of memory",
    re.IGNORECASE,
)

#: gRPC/absl status words and socket-level phrases the distributed
#: runtimes put in RuntimeError messages for genuinely transient failures.
#: RESOURCE_EXHAUSTED stays here for its quota/rate-limit shape — the OOM
#: shape is intercepted by the fatal "out of memory" pattern above.
#: Device-loss / pool-preemption shapes (a preemptible TPU pool reclaiming
#: a worker surfaces as a lost-device XlaRuntimeError or a "Socket
#: closed"-class tunnel drop — the TYPE carries no signal) are transient
#: WITH-RESTART: the work is gone but a restarted attempt on a fresh
#: device resumes from the latest checkpoint (resilience/recovery.py).
_TRANSIENT_PATTERNS = re.compile(
    r"UNAVAILABLE|DEADLINE_EXCEEDED|RESOURCE_EXHAUSTED|ABORTED"
    r"|socket closed|connection reset|connection refused|broken pipe"
    r"|connection closed|temporarily unavailable|too many requests"
    r"|timed? ?out"
    r"|preempt(?:ed|ion)?|device (?:is )?lost|lost device"
    r"|device (?:failure|halted)|worker (?:has )?(?:restarted|terminated)",
    re.IGNORECASE,
)

#: the device-loss subset of the transient shapes: a preemptible pool
#: reclaiming the worker mid-run. Kept separate so drivers can tally
#: ``resilience/preemptions`` distinctly from garden-variety retries —
#: the counter that tells an operator their checkpoint cadence is being
#: exercised by the POOL, not by flaky I/O. A bare "socket closed" is
#: deliberately NOT here: it stays transient (restart-worthy), but on
#: this platform it is also how an oversized remote-compile request
#: surfaces when the 413 is swallowed (CLAUDE.md) — tallying every
#: dropped tunnel as a preemption would send the operator chasing the
#: pool while a deterministic bug repeats.
_PREEMPTION_PATTERNS = re.compile(
    r"preempt(?:ed|ion)?|device (?:is )?lost|lost device"
    r"|device (?:failure|halted)|worker (?:has )?(?:restarted|terminated)",
    re.IGNORECASE,
)

#: remediation hints keyed by fatal signature — logged once at giveup so
#: the next reader does not re-spend a round rediscovering the cause
FATAL_HINTS: tuple[tuple[re.Pattern, str], ...] = (
    (
        re.compile(r"\b413\b|payload too large|request entity too large",
                   re.IGNORECASE),
        "the remote-compile request exceeded the tunnel limit — a jit "
        "likely closed over a large batch; pass batches as jit ARGUMENTS "
        "(CLAUDE.md 'Never close a jax.jit over a large batch')",
    ),
    (
        re.compile(r"out of memory", re.IGNORECASE),
        "device OOM is deterministic — retrying re-allocates identically; "
        "shrink the batch, use bf16 feature blocks, or shard further",
    ),
)


class Transience(enum.Enum):
    """The classifier's verdict: retry-worthy or not."""

    TRANSIENT = "transient"
    FATAL = "fatal"


class TransientError(RuntimeError):
    """Explicitly-transient failure: always retried within budget.

    Raise (or wrap a caught error in) this at call sites that KNOW the
    failure is worth retrying regardless of the generic rules."""


class ExchangeTimeout(TimeoutError):
    """A MetadataExchange read/barrier missed its deadline — attributed.

    Carries the exchange tag, the key that never appeared, and the rank(s)
    expected to publish it, so a wedged multi-host run fails with "rank 2
    never published partitioned_read/train" instead of an anonymous hang
    (the failure mode ISSUE 3 exists to kill). Classified FATAL: the
    deadline already waited; what is needed is the named rank's logs, not
    another identical wait. One exception to "fatal ends the job": a run
    with a ``resilience.coordinated.CoordinatedRecovery`` attached treats
    it (like :class:`PeerAbort`) as recoverable-VIA-COORDINATION — the
    coordinator rendezvouses every rank on an all-rank rollback instead of
    retrying the wait (ISSUE 15); without a coordinator the original
    contract stands.
    """

    def __init__(
        self,
        tag: str,
        *,
        missing_ranks: "tuple[int, ...] | list[int]" = (),
        key: str | None = None,
        rank: int | None = None,
        timeout: float | None = None,
        detail: str = "",
    ):
        self.tag = tag
        self.missing_ranks = tuple(int(r) for r in missing_ranks)
        self.key = key
        self.rank = rank
        self.timeout = timeout
        parts = [f"exchange {tag!r}"]
        if key is not None:
            parts.append(f"key {key!r} was never published")
        if self.missing_ranks:
            parts.append(
                "rank(s) %s did not participate"
                % ",".join(map(str, self.missing_ranks))
            )
        if rank is not None:
            parts.append(f"(observed on rank {rank})")
        if timeout is not None:
            parts.append(f"after {timeout:g}s")
        if detail:
            parts.append(f"[{detail}]")
        super().__init__(" ".join(parts))


class PeerAbort(RuntimeError):
    """ANOTHER rank aborted the attempt — attributed to the culprit.

    Raised by a generation-fenced exchange wait when a peer rank posts an
    abort marker (its own failure classified transient/preemption) instead
    of publishing its key: the healthy ranks fail FAST with the culprit
    rank and cause named, rather than burning the full exchange deadline
    on a rank that already knows it is restarting. Classified FATAL for
    the same reason as :class:`ExchangeTimeout` — already attributed, and
    blindly re-waiting would desynchronize the SPMD call sequence — but
    recoverable VIA COORDINATION: ``run_with_recovery(coordinator=...)``
    turns it into an all-rank rollback to the last barrier-committed
    checkpoint (resilience/coordinated.py).
    """

    def __init__(
        self,
        tag: str,
        *,
        origin_rank: "int | None" = None,
        cause: str = "",
        generation: int | None = None,
        rank: int | None = None,
    ):
        self.tag = tag
        self.origin_rank = origin_rank
        self.cause = cause
        self.generation = generation
        self.rank = rank
        parts = [f"exchange {tag!r} aborted"]
        if origin_rank is not None:
            parts.append(f"by rank {origin_rank}")
        else:
            parts.append("by an unattributed peer (corrupt abort marker?)")
        if generation is not None:
            parts.append(f"in generation {generation}")
        if cause:
            parts.append(f"cause: {cause}")
        if rank is not None:
            parts.append(f"(observed on rank {rank})")
        super().__init__(" ".join(parts))


def classify_exception(exc: BaseException) -> Transience:
    """The ONE transient-vs-fatal rule (precedence in the module docstring)."""
    if isinstance(exc, TransientError):
        return Transience.TRANSIENT
    if isinstance(exc, (ExchangeTimeout, PeerAbort)):
        # already-attributed coordination failures: the cause STRING may
        # smell transient ("preempted"), but re-waiting/retrying locally
        # would desync the SPMD sequence — only the coordinator path
        # (resilience/coordinated.py) may recover these
        return Transience.FATAL
    message = f"{type(exc).__name__}: {exc}"
    if _FATAL_PATTERNS.search(message):
        return Transience.FATAL
    if isinstance(
        exc, (ConnectionError, TimeoutError, InterruptedError)
    ):
        return Transience.TRANSIENT
    if isinstance(exc, OSError) and exc.errno in TRANSIENT_ERRNOS:
        return Transience.TRANSIENT
    if _TRANSIENT_PATTERNS.search(message):
        return Transience.TRANSIENT
    return Transience.FATAL


def is_transient(exc: BaseException) -> bool:
    return classify_exception(exc) is Transience.TRANSIENT


def is_preemption(exc: BaseException) -> bool:
    """True for transient failures whose shape is a device loss / pool
    preemption (lost-device XlaRuntimeError, "Socket closed"-class tunnel
    drop) rather than ordinary flaky I/O. Always a SUBSET of transient:
    a fatal-classified error (e.g. an OOM that happens to mention a
    device) is never counted as a preemption."""
    if classify_exception(exc) is not Transience.TRANSIENT:
        return False
    message = f"{type(exc).__name__}: {exc}"
    return bool(_PREEMPTION_PATTERNS.search(message))


def fatal_hint(exc: BaseException) -> str | None:
    """A remediation hint for known-fatal signatures, or None."""
    message = f"{type(exc).__name__}: {exc}"
    for pattern, hint in FATAL_HINTS:
        if pattern.search(message):
            return hint
    return None
