"""Fault-tolerance layer: error classification, retry, deadlines, recovery.

Reference parity: the reference's fault tolerance is Spark's substrate —
RDD lineage recompute + task retries, owned by spark-submit/YARN rather
than any photon-ml source file (SURVEY.md §5). This package is the
explicit TPU-native replacement, wired through every host-side boundary;
see each submodule's docstring for its slice.
"""

from photon_ml_tpu.resilience.coordinated import (
    CoordinatedRecovery,
    RestartDecision,
)
from photon_ml_tpu.resilience.errors import (
    FATAL_HINTS,
    TRANSIENT_ERRNOS,
    ExchangeTimeout,
    PeerAbort,
    Transience,
    TransientError,
    classify_exception,
    fatal_hint,
    is_preemption,
    is_transient,
)
from photon_ml_tpu.resilience.policy import (
    RetryPolicy,
    default_dispatch_policy,
    default_io_policy,
    default_kv_policy,
)
from photon_ml_tpu.resilience.recovery import run_with_recovery

__all__ = [
    "FATAL_HINTS",
    "TRANSIENT_ERRNOS",
    "CoordinatedRecovery",
    "ExchangeTimeout",
    "PeerAbort",
    "RestartDecision",
    "Transience",
    "TransientError",
    "classify_exception",
    "fatal_hint",
    "is_preemption",
    "is_transient",
    "RetryPolicy",
    "default_dispatch_policy",
    "default_io_policy",
    "default_kv_policy",
    "run_with_recovery",
]
