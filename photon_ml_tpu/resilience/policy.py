"""Bounded retry with exponential backoff and deterministic jitter.

No reference analogue as code: the reference delegates retry to Spark's
task scheduler (spark.task.maxFailures re-runs a lost partition's task;
no photon-ml source file implements it — SURVEY.md §5). Here the
equivalent is an explicit, typed :class:`RetryPolicy` wrapped around the
host-side boundaries the drivers own: remote-compile/dispatch call sites,
Avro container reads, and coordination-service KV operations
(parallel/multihost.DistributedKVExchange).

Design points:

- **Bounded**: ``max_attempts`` total calls; exhaustion re-raises the last
  error after counting a ``resilience/giveups``.
- **Classified**: only errors the shared classifier
  (resilience/errors.classify_exception) deems transient are retried —
  a ValueError or an HTTP-413 "flaky tunnel" burns zero retries.
- **Deterministic jitter**: backoff is ``base * multiplier**attempt``
  capped at ``max_delay``, stretched by a jitter fraction derived from a
  HASH of (policy name, call key, attempt) — reproducible run to run
  (no RNG state, no wall-clock dependence) yet decorrelated across ranks
  and call sites, which is what jitter exists for.
- **Observable**: every retry counts on ``resilience/retries`` and logs
  the classified error; giveups log the remediation hint for known-fatal
  signatures (errors.fatal_hint).

NOT for collectives: retrying one rank of an exchange/allgather while the
others do not desynchronizes the SPMD call sequence. Collective call
sites get deadlines (errors.ExchangeTimeout) instead; retry belongs
inside the transport's point-to-point operations (multihost._kv_* ) or
around whole single-process operations.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import time
from typing import Callable

from photon_ml_tpu.resilience.errors import (
    Transience,
    classify_exception,
    fatal_hint,
)
from photon_ml_tpu.telemetry import resilience_counters

logger = logging.getLogger(__name__)


def _jitter_fraction(name: str, key: str, attempt: int) -> float:
    """[0, 1) fraction from a stable hash — deterministic jitter."""
    digest = hashlib.blake2b(
        f"{name}/{key}/{attempt}".encode(), digest_size=4
    ).digest()
    return int.from_bytes(digest, "little") / 2**32


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """``policy.call(fn, ...)`` — run ``fn`` with classified bounded retry.

    ``sleep`` is injectable so chaos tests pay zero wall-clock; everything
    else is data. Instances are immutable and shareable.
    """

    max_attempts: int = 3
    base_delay: float = 0.2
    max_delay: float = 30.0
    multiplier: float = 2.0
    #: extra delay of up to this fraction of the backoff, hash-derived
    jitter: float = 0.25
    name: str = "retry"
    classify: Callable[[BaseException], Transience] = classify_exception
    sleep: Callable[[float], None] = time.sleep

    def delay(self, attempt: int, key: str = "") -> float:
        """Backoff before attempt ``attempt + 1`` (attempt is 0-based)."""
        base = min(self.base_delay * self.multiplier**attempt, self.max_delay)
        return base * (1.0 + self.jitter * _jitter_fraction(self.name, key, attempt))

    def call(self, fn: Callable, *args, description: str = "", **kwargs):
        """Invoke ``fn(*args, **kwargs)``, retrying classified-transient
        failures up to ``max_attempts`` total attempts."""
        key = description or getattr(fn, "__name__", "call")
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except Exception as e:  # the classifier decides; see module doc
                if self.classify(e) is not Transience.TRANSIENT:
                    hint = fatal_hint(e)
                    if hint is not None:
                        logger.error(
                            "%s: %s failed with a known-fatal signature "
                            "(%r) — not retrying. Hint: %s",
                            self.name, key, e, hint,
                        )
                    raise
                attempt += 1
                if attempt >= self.max_attempts:
                    resilience_counters.record_giveup()
                    logger.error(
                        "%s: %s failed transiently %d/%d times; giving up "
                        "(last error: %r)",
                        self.name, key, attempt, self.max_attempts, e,
                    )
                    raise
                pause = self.delay(attempt - 1, key)
                resilience_counters.record_retry()
                logger.warning(
                    "%s: transient failure in %s (attempt %d/%d): %r — "
                    "retrying in %.2fs",
                    self.name, key, attempt, self.max_attempts, e, pause,
                )
                self.sleep(pause)


def default_io_policy() -> RetryPolicy:
    """Host I/O boundary (Avro container reads, checkpoint/journal files):
    a few quick attempts — local/remote filesystems either heal in seconds
    or not at all."""
    return RetryPolicy(max_attempts=3, base_delay=0.2, max_delay=5.0,
                       name="io-retry")


def default_dispatch_policy() -> RetryPolicy:
    """Remote-compile/dispatch boundary (the tunneled TPU): dispatch rides
    an HTTP relay with tens-of-ms jitter and occasional dropped
    connections; give it more room than local I/O."""
    return RetryPolicy(max_attempts=4, base_delay=1.0, max_delay=60.0,
                       name="dispatch-retry")


def default_kv_policy() -> RetryPolicy:
    """Coordination-service KV boundary: point-to-point set/get against
    the jax.distributed coordinator (deadlines are the transport's own
    job — see multihost.DistributedKVExchange)."""
    return RetryPolicy(max_attempts=3, base_delay=0.5, max_delay=10.0,
                       name="kv-retry")
