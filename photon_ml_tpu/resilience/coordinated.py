"""Coordinated multi-rank recovery: generation fencing, peer-abort
attribution, all-rank rollback to the last barrier-committed checkpoint.

No reference analogue as code: the reference survives executor loss
through Spark's substrate — the driver re-runs lost tasks against lineage
(SURVEY.md §5; spark-submit/YARN, not a photon-ml source file). The SPMD
rebuild has no driver: every rank runs the same program, so ONE rank's
preemption must become a survivable, rank-attributed event for ALL ranks
(ISSUE 15) — Snap ML (arXiv:1803.06333) treats the cluster as a memory
hierarchy to re-enter, DrJAX (arXiv:2403.07128) makes the program, not
the process, the durable unit. Before this module, a healthy rank's
bounded exchange wait on a preempted peer ended the whole job: its
``ExchangeTimeout`` classifies always-fatal (resilience/errors.py) and
its per-process ``run_with_recovery`` budget could not restart an attempt
whose PEERS were not restarting with it.

:class:`CoordinatedRecovery` layers three pieces over the run's existing
``MetadataExchange`` (host-side KV only — it never adds, skips, or
reorders a DEVICE collective, so healthy-path runs with a coordinator
attached stay bitwise-identical to detached runs):

1. **Generation fencing** — the coordinator moves the exchange into a
   restart-generation keyspace (``MetadataExchange.set_generation``):
   every key and barrier id carries the generation, and the per-instance
   call sequence resets when the generation bumps, so a restarted
   attempt's ranks resynchronize at seq 0 and a dead attempt's stale keys
   can never satisfy a new attempt's get (pre-ISSUE-15, the
   process-global KV sequence desynced across restarts — ranks died at
   different points of the SPMD call sequence).
2. **Peer-abort markers** — a rank whose failure classifies
   transient/preemption best-effort-writes a rank- and cause-attributed
   abort marker before restarting; peers blocked in any fenced wait fail
   fast with a typed ``resilience.errors.PeerAbort`` naming the culprit
   instead of burning the full deadline. Markers are written ONLY on the
   failure path; a healthy run performs zero additional exchange ops.
3. **Coordinated rollback** — every rank's recovery path calls
   :meth:`CoordinatedRecovery.coordinated_restart`: the generation bumps,
   all ranks rendezvous on a new-generation restart exchange, rank 0
   resolves the newest intact BARRIER-COMMITTED checkpoint
   (``TrainingCheckpointer.newest_loadable_step`` — ``commit_checkpoint``
   guarantees such a step exists only for sweeps EVERY rank completed)
   and publishes ``(step, generation, restarts_used)``; every rank
   verifies its local view matches and resumes from that step. The
   restart budget is the GENERATION — shared by construction, so a
   flapping rank exhausts the JOB's budget, never an asymmetric
   per-process one.

``run_with_recovery(coordinator=...)`` (resilience/recovery.py) is the
driver-facing entry: with a coordinator attached, ``ExchangeTimeout`` and
``PeerAbort`` become recoverable-via-coordination; without one, the
pre-existing always-fatal contract is untouched.
"""

from __future__ import annotations

import dataclasses
import logging

from photon_ml_tpu.resilience.errors import (
    ExchangeTimeout,
    PeerAbort,
    is_preemption,
)
from photon_ml_tpu.telemetry import resilience_counters

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class RestartDecision:
    """The all-rank agreement one coordinated restart produces.

    generation:    the NEW restart generation every rank adopted (== the
                   job's restarts used so far — the shared budget).
    step:          the barrier-committed checkpoint step rank 0 resolved
                   and published (0 = no checkpoint: restart from
                   scratch).
    restarts_used: == generation; spelled out for journals.
    exhausted:     generation exceeded the job budget — every rank gives
                   up, attributed identically.
    origin_rank:   the rank whose failure started this restart (None when
                   the marker was corrupt/absent — e.g. a hard-killed
                   rank that never wrote one).
    origin_cause:  the originating failure, as the culprit described it.
    """

    generation: int
    step: int
    restarts_used: int
    exhausted: bool
    origin_rank: "int | None"
    origin_cause: str


class CoordinatedRecovery:
    """Per-rank coordinator over the run's ``MetadataExchange``.

    Construct ONE per rank over the SAME exchange instance the run's
    partitioned I/O and checkpoint commits ride (SPMD discipline: every
    rank constructs it at the same point). Construction fences the
    exchange into generation 0 — a pure key-namespace change; the
    exchange op sequence of a healthy run is identical to a detached
    run's.

    checkpointer: the run's shared-directory checkpointer (rank 0 resolves
    the rollback step from it; other ranks verify against the published
    step). None = rollback restarts from scratch (step 0).
    journal: optional per-rank ``telemetry.RunJournal`` — ``peer_abort``
    and ``coordinated_restart`` rows carry the attribution every rank's
    journal must agree on.
    """

    #: exchange tags of the restart protocol (generation-fenced like all
    #: fenced tags, so a dead attempt's rendezvous can never be consumed
    #: by a newer one)
    RESTART_TAG = "coordinated/restart"
    ROLLBACK_TAG = "coordinated/rollback"

    def __init__(
        self,
        exchange,
        *,
        max_restarts: int = 2,
        checkpointer=None,
        journal=None,
        description: str = "training",
    ):
        self.exchange = exchange
        self.max_restarts = int(max_restarts)
        self.checkpointer = checkpointer
        self.journal = journal
        self.description = description
        #: the last decision's checkpoint step — drivers may thread it
        #: into ``train_partitioned(resume_step=...)`` /
        #: ``StreamingGameProgram.train(resume_step=...)`` to pin the
        #: restore to the PUBLISHED step rather than "newest local"
        self.resume_step: "int | None" = None
        exchange.set_generation(0)

    def rebind(self, checkpointer) -> None:
        """Point the coordinator at a NEW unit of work's checkpointer
        (e.g. the next grid config, whose checkpoint directory is its
        own) and clear the published resume step — a step published for
        the PREVIOUS unit's rollback must never pin a later unit's
        restore (it may not even exist in the new directory)."""
        self.checkpointer = checkpointer
        self.resume_step = None

    @property
    def rank(self) -> int:
        return self.exchange.rank

    @property
    def generation(self) -> int:
        return int(self.exchange.generation or 0)

    # -- failure path ---------------------------------------------------------

    def post_abort(self, exc: BaseException) -> None:
        """Best-effort: attribute this rank's recoverable failure to its
        peers before restarting (the marker is what turns their full-
        deadline ``ExchangeTimeout`` into an immediate ``PeerAbort``
        naming this rank). Never raises — the culprit restarts either
        way; peers fall back to their deadlines."""
        info = {
            "rank": self.rank,
            "cause": repr(exc)[:500],
            "kind": (
                "preemption" if is_preemption(exc) else type(exc).__name__
            ),
            "generation": self.generation,
        }
        try:
            self.exchange.post_abort(info)
        except (RuntimeError, OSError) as e:
            logger.warning("abort-marker write failed (best-effort): %s", e)
        if self.journal is not None:
            self.journal.record(
                "abort_written",
                rank=self.rank,
                cause=info["cause"],
                failure_kind=info["kind"],
                generation=info["generation"],
            )

    def _origin(self, cause: BaseException) -> "tuple[int | None, str]":
        """(origin_rank, origin_cause) as THIS rank observed it: a
        PeerAbort carries the culprit; a marker left on the board names
        it; otherwise this rank is itself the origin."""
        if isinstance(cause, PeerAbort):
            return cause.origin_rank, cause.cause or repr(cause)
        marker = None
        try:
            marker = self.exchange.pending_abort()
        except (RuntimeError, OSError):  # marker read is best-effort too
            marker = None
        if marker is not None:
            origin = marker.get("rank")
            return (
                None if origin is None else int(origin),
                str(marker.get("cause", "")),
            )
        if isinstance(cause, ExchangeTimeout):
            # no marker: the peer died without writing one (hard kill) —
            # the timeout's own attribution (missing ranks) is the best
            # available
            missing = getattr(cause, "missing_ranks", ())
            return (missing[0] if missing else None), repr(cause)
        return self.rank, repr(cause)

    def coordinated_restart(self, cause: BaseException) -> RestartDecision:
        """The all-rank restart protocol — EVERY rank's recovery path
        calls this (the rendezvous is exchange-collective); returns the
        published :class:`RestartDecision`. Raises ``ExchangeTimeout``
        when a rank never reaches the rendezvous (it is truly gone, not
        restarting — the job then fails attributed, as before)."""
        origin_rank, origin_cause = self._origin(cause)
        if isinstance(cause, PeerAbort):
            resilience_counters.record_peer_abort()
            if self.journal is not None:
                self.journal.record(
                    "peer_abort",
                    rank=self.rank,
                    origin_rank=origin_rank,
                    origin_cause=origin_cause,
                    generation=self.generation,
                    tag=getattr(cause, "tag", None),
                )
        generation = self.generation + 1
        self.exchange.set_generation(generation)
        # rendezvous: every restarting rank checks in with its local view
        # of the origin; the JOB-level attribution prefers a rank that
        # blames ITSELF (the actual culprit's own report) over hearsay
        views = self.exchange.allgather(
            self.RESTART_TAG,
            {"rank": self.rank, "origin_rank": origin_rank,
             "origin_cause": origin_cause},
        )
        for v in views:
            if v.get("origin_rank") is not None and (
                v.get("origin_rank") == v.get("rank")
            ):
                origin_rank = int(v["origin_rank"])
                origin_cause = str(v.get("origin_cause", origin_cause))
                break
        else:
            named = [v for v in views if v.get("origin_rank") is not None]
            if named:
                origin_rank = int(named[0]["origin_rank"])
                origin_cause = str(named[0].get("origin_cause",
                                                origin_cause))
        exhausted = generation > self.max_restarts
        step = 0
        if not exhausted:
            # rank 0 resolves the newest intact barrier-committed step and
            # publishes; every rank restores THAT step (commit_checkpoint
            # guarantees it exists only for sweeps every rank completed)
            local = (
                self.checkpointer.newest_loadable_step()
                if self.checkpointer is not None else None
            )
            published = self.exchange.allgather(
                self.ROLLBACK_TAG,
                {"step": local} if self.rank == 0 else None,
            )[0]
            step = int(published.get("step") or 0)
            if (
                self.rank != 0
                and self.checkpointer is not None
                and (local or 0) != step
            ):
                raise ValueError(
                    f"coordinated rollback: rank {self.rank} resolves "
                    f"checkpoint step {local or 0} but rank 0 published "
                    f"step {step} — the ranks disagree on the shared "
                    "checkpoint directory's contents; every rank must "
                    "mount the SAME barrier-committed checkpoint "
                    "directory"
                )
            self.resume_step = step
            resilience_counters.record_coordinated_restart()
        if self.journal is not None:
            self.journal.record(
                "coordinated_restart",
                rank=self.rank,
                generation=generation,
                restarts_used=generation,
                max_restarts=self.max_restarts,
                step=step,
                exhausted=exhausted,
                origin_rank=origin_rank,
                origin_cause=origin_cause,
            )
        logger.warning(
            "coordinated restart: rank %d enters generation %d "
            "(origin rank %s: %s)%s",
            self.rank, generation, origin_rank, origin_cause,
            (
                " — JOB restart budget exhausted" if exhausted
                else f", rolling back to checkpoint step {step}"
            ),
        )
        return RestartDecision(
            generation=generation,
            step=step,
            restarts_used=generation,
            exhausted=exhausted,
            origin_rank=origin_rank,
            origin_cause=origin_cause,
        )
