"""Matrix-factorization coordinate: alternating vmapped latent-factor solves.

The reference promises an MF coordinate (README.md:92-95,
LatentFactorAvro.avsc) but never implemented it; this module supplies the
missing capability as a first-class GAME coordinate so MF factors train on
coordinate-descent residuals alongside fixed/random effects
(algorithm/CoordinateDescent parity: photon-lib algorithm/Coordinate.scala).

Training is alternating minimization. With column factors held fixed, the
objective restricted to one row-entity r is an ordinary GLM over its
samples whose "feature vector" for sample i is ``col_factors[col_idx_i]``
— exactly the local subproblem shape of a random-effect entity. So each
half-step gathers the fixed side's factors as features and reuses the
vmapped per-entity solver (`coordinates._solve_bucket_entities`) over
size-bucketed padded blocks. The gather happens *inside* jit, so a bucket's
HLO is (embedding-lookup → vmapped LBFGS) fused by XLA, and each half-step
scatters straight back into the [E, k] factor table.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from functools import partial

from photon_ml_tpu.algorithm.coordinates import (
    Coordinate,
    CoordinateOptimizationConfig,
    _bucket_offsets,
    _make_objective,
    _solve_bucket_entities,
    _solve_config,
)
from photon_ml_tpu.data.game_data import (
    GameDataset,
    group_entities_into_buckets,
    pack_bucket_lanes,
)
from photon_ml_tpu.models.matrix_factorization import (
    MatrixFactorizationModel,
    init_factors,
)
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim.optimizer import OptimizerConfig
from photon_ml_tpu.telemetry.program_ledger import ledger_jit
from photon_ml_tpu.types import TaskType

Array = jax.Array


@dataclasses.dataclass
class MFSideBucket:
    """One size-bucket of per-entity sample groups for one MF side.

    Unlike EntityBucket there is no static feature block — features are the
    *other* side's factor rows, gathered at solve time (they change every
    half-step).

    labels/weights: [e, cap] (weight 0 marks padding)
    entity_rows:    [e]      row in this side's entity vocab
    sample_rows:    [e, cap] global sample row per slot, -1 pad
    """

    labels: Array
    weights: Array
    entity_rows: Array
    sample_rows: Array

    @property
    def num_entities(self) -> int:
        return int(self.entity_rows.shape[0])


@dataclasses.dataclass
class MFDataset:
    """Bucketed per-entity views of both MF sides."""

    row_effect_type: str
    col_effect_type: str
    row_buckets: list[MFSideBucket]
    col_buckets: list[MFSideBucket]
    num_row_entities: int
    num_col_entities: int

    def trained_masks(self) -> tuple[np.ndarray, np.ndarray]:
        """Boolean [R] / [C] masks of entities that appear in any bucket.
        Entities outside (vocab members with zero samples) are never
        trained and must score 0, matching random-effect semantics."""
        row = np.zeros(self.num_row_entities, dtype=bool)
        for b in self.row_buckets:
            row[np.asarray(b.entity_rows)] = True
        col = np.zeros(self.num_col_entities, dtype=bool)
        for b in self.col_buckets:
            col[np.asarray(b.entity_rows)] = True
        return row, col


def _build_side_buckets(
    entity_idx: np.ndarray,
    other_idx: np.ndarray,
    labels: np.ndarray,
    weights: np.ndarray,
    unique_ids: np.ndarray,
    *,
    bucket_sizes,
    active_data_upper_bound: int | None,
    seed: int,
) -> list[MFSideBucket]:
    """Group samples by this side's entity (shared bucketing with
    build_random_effect_dataset; reservoir caps keyed on stable sample ids).
    Samples whose other-side entity is unseen cannot contribute a
    factor-feature, so they are excluded BEFORE grouping — otherwise they
    would crowd usable samples out of the reservoir cap."""
    effective_idx = np.where(other_idx >= 0, entity_idx, -1)
    per_bucket = group_entities_into_buckets(
        effective_idx,
        unique_ids,
        bucket_sizes=bucket_sizes,
        active_data_upper_bound=active_data_upper_bound,
        seed=seed,
    )
    buckets: list[MFSideBucket] = []
    for cap, members in per_bucket.items():
        if not members:
            continue
        e = len(members)
        be, rows_concat, lane, slot = pack_bucket_lanes(members)
        bl = np.zeros((e, cap), dtype=labels.dtype)
        bw = np.zeros((e, cap), dtype=weights.dtype)
        bs = np.full((e, cap), -1, dtype=np.int32)
        bl[lane, slot] = labels[rows_concat]
        bw[lane, slot] = weights[rows_concat] * (other_idx[rows_concat] >= 0)
        bs[lane, slot] = rows_concat
        buckets.append(
            MFSideBucket(
                labels=jnp.asarray(bl),
                weights=jnp.asarray(bw),
                entity_rows=jnp.asarray(be),
                sample_rows=jnp.asarray(bs),
            )
        )
    return buckets


def build_mf_dataset(
    dataset: GameDataset,
    row_effect_type: str,
    col_effect_type: str,
    *,
    bucket_sizes=(8, 32, 128, 512, 2048),
    active_data_upper_bound: int | None = None,
    seed: int = 0,
) -> MFDataset:
    labels = dataset.host_array("labels")
    weights = dataset.host_array("weights")
    unique_ids = np.asarray(dataset.unique_ids)
    row_idx = dataset.host_array(f"entity_idx/{row_effect_type}")
    col_idx = dataset.host_array(f"entity_idx/{col_effect_type}")
    return MFDataset(
        row_effect_type=row_effect_type,
        col_effect_type=col_effect_type,
        row_buckets=_build_side_buckets(
            row_idx, col_idx, labels, weights, unique_ids,
            bucket_sizes=bucket_sizes,
            active_data_upper_bound=active_data_upper_bound, seed=seed,
        ),
        col_buckets=_build_side_buckets(
            col_idx, row_idx, labels, weights, unique_ids,
            bucket_sizes=bucket_sizes,
            active_data_upper_bound=active_data_upper_bound, seed=seed,
        ),
        num_row_entities=len(dataset.entity_vocabs[row_effect_type]),
        num_col_entities=len(dataset.entity_vocabs[col_effect_type]),
    )


def solve_mf_side_bucket(
    objective: GLMObjective,
    opt: OptimizerConfig,
    labels: Array,        # [e, cap]
    weights: Array,       # [e, cap]
    entity_rows: Array,   # [e]
    sample_rows: Array,   # [e, cap]
    other_idx_full: Array,  # [n] the fixed side's per-sample entity index
    other_factors: Array,   # [E_other, k] the fixed side's factor table
    full_offsets: Array,    # [n] base + residual offsets
    table: Array,           # [E_this, k] this side's factor table
) -> Array:
    """One alternating half-step over one bucket: gather the fixed side's
    factors as features, vmap-solve every entity, scatter back.

    Pure/traceable: reused by the single-chip jit wrapper below and by the
    mesh-sharded fused GAME step (parallel/distributed.py), where the
    entity axis shards over the mesh's "data" axis."""
    safe_rows = jnp.maximum(sample_rows, 0)
    oidx = other_idx_full[safe_rows]                       # [e, cap]
    feats = other_factors[jnp.maximum(oidx, 0)]            # [e, cap, k]
    pad = sample_rows < 0
    feats = jnp.where(pad[..., None] | (oidx < 0)[..., None], 0.0, feats)
    offsets = _bucket_offsets(sample_rows, full_offsets)
    solved, _trace = _solve_bucket_entities(
        objective, opt, feats, labels, weights, offsets, table[entity_rows]
    )
    return table.at[entity_rows].set(solved)


@partial(ledger_jit, label="coord/mf_side_solve", static_argnums=(0, 1))
def _jitted_mf_side_solve(
    objective: GLMObjective,
    opt: OptimizerConfig,
    labels: Array,
    weights: Array,
    entity_rows: Array,
    sample_rows: Array,
    other_idx_full: Array,
    other_factors: Array,
    full_offsets: Array,
    table: Array,
) -> Array:
    return solve_mf_side_bucket(
        objective, opt, labels, weights, entity_rows, sample_rows,
        other_idx_full, other_factors, full_offsets, table,
    )


@dataclasses.dataclass
class MatrixFactorizationCoordinate(Coordinate):
    """Trains (row_factors, col_factors) on the residual offsets.

    ``num_alternations`` inner row/col sweeps per coordinate update; the
    outer coordinate-descent loop supplies further alternations, so small
    values (1-2) suffice.
    """

    coordinate_id: str
    dataset: GameDataset
    mf_dataset: MFDataset
    task: TaskType
    config: CoordinateOptimizationConfig
    num_latent_factors: int
    num_alternations: int = 2
    seed: int = 0

    def initial_model(self) -> MatrixFactorizationModel:
        mf = self.mf_dataset
        row, col = init_factors(
            mf.num_row_entities, mf.num_col_entities, self.num_latent_factors,
            seed=self.seed, dtype=self.dataset.labels.dtype,
        )
        # Vocab entities with no training samples keep zero factors (they are
        # never solved, so a random init would leak noise into their scores).
        row_mask, col_mask = mf.trained_masks()
        row = jnp.where(jnp.asarray(row_mask)[:, None], row, 0.0)
        col = jnp.where(jnp.asarray(col_mask)[:, None], col, 0.0)
        return MatrixFactorizationModel(
            row_factors=row,
            col_factors=col,
            row_effect_type=mf.row_effect_type,
            col_effect_type=mf.col_effect_type,
            row_keys=self.dataset.entity_vocabs[mf.row_effect_type],
            col_keys=self.dataset.entity_vocabs[mf.col_effect_type],
            task=self.task,
        )

    def update_model(
        self, model: MatrixFactorizationModel, extra_offsets: Array | None = None
    ):
        if self.config.l1_weight > 0.0:
            raise ValueError(
                "L1 regularization is not supported on latent factors "
                "(use l2_weight; the reference's MF design is L2-only)"
            )
        objective = _make_objective(self.task, self.config, None)
        # alternating factor solves are small-k dense vmapped problems:
        # AUTO resolves to the batched-Newton solver (optim/newton.py)
        opt = _solve_config(self.config, loss=objective.loss, small_dense=True)
        full_offsets = self.dataset.offsets
        if extra_offsets is not None:
            full_offsets = full_offsets + extra_offsets

        mf = self.mf_dataset
        row_idx = self.dataset.entity_idx[mf.row_effect_type]
        col_idx = self.dataset.entity_idx[mf.col_effect_type]
        rows, cols = model.row_factors, model.col_factors
        for _ in range(self.num_alternations):
            for b in mf.row_buckets:
                rows = _jitted_mf_side_solve(
                    objective, opt, b.labels, b.weights, b.entity_rows,
                    b.sample_rows, col_idx, cols, full_offsets, rows,
                )
            for b in mf.col_buckets:
                cols = _jitted_mf_side_solve(
                    objective, opt, b.labels, b.weights, b.entity_rows,
                    b.sample_rows, row_idx, rows, full_offsets, cols,
                )
        return model.with_factors(rows, cols), None

    def score(self, model: MatrixFactorizationModel) -> Array:
        return model.score_dataset(self.dataset)
