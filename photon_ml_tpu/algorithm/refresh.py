"""Incremental GAME retrain: re-solve only what changed, carry the rest.

Reference parity: partial retraining via locked coordinates
(photon-lib algorithm/CoordinateDescent.scala:44-49 — a locked coordinate
contributes scores and never retrains) and warm-start between
configurations (GameEstimator.scala:352-366). The reference's granularity
stops at the COORDINATE; this module pushes it to the ENTITY: a daily
refresh re-solves only the random-effect entities whose data changed or
whose gradient at the resident solution exceeds tolerance, against frozen
residuals from the resident model's scores, warm-started from the resident
coefficients — so a refresh costs ~the changed entities' solve time, not a
full GAME fit (the Snap ML keep-resident-state-hot discipline,
arXiv:1803.06333).

Mechanics:

- **Selection** (:func:`select_refresh_entities`): entities DECLARED
  changed (``RefreshPolicy.changed_entities`` — the ingest layer knows who
  got new rows) union entities whose per-entity solve-space gradient norm
  at the resident coefficients exceeds ``gradient_tolerance`` (one vmapped
  gradient pass per bucket — catches undeclared drift; an entity whose
  data is unchanged sits at rounding-scale gradient because the resident
  solve left it there).
- **Solve**: the lane scheduler's active-set freezing promoted to an
  externally-chosen set (``LaneScheduler.freeze_rows``): unselected lanes
  are frozen and skipped by compaction, selected lanes re-solve with the
  full iteration budget warm-started from their resident rows, and
  untouched table rows carry over BITWISE (the compacted scatter never
  writes them).
- **Frozen residuals**: each coordinate re-solves against the partial
  score of the RESIDENT model (full score minus its own contribution) —
  exactly the residual-offset mechanism of the CD loop
  (CoordinateDescent.scala:198-255), evaluated once at the resident state.
- **Resume**: a checkpointer commits after every coordinate through the
  one gated write site (``io.checkpoint.commit_checkpoint``, lint check
  10); a preempted refresh fast-forwards past completed coordinates and
  finishes bitwise-identical to an uninterrupted run. Restores are
  fingerprint-guarded: a checkpoint written under a different
  layout/λ-grid fails fast naming the differing fields.

Strictly opt-in: nothing here runs unless the driver passes
``--incremental-refresh`` (or a caller invokes ``GameEstimator.refresh``);
the full-fit path is untouched.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Mapping, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.coordinates import (
    Coordinate,
    FixedEffectCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.models.game import GameModel
from photon_ml_tpu.telemetry import refresh_counters, tracing

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class RefreshPolicy:
    """What a refresh re-solves.

    gradient_tolerance: re-solve entities whose solve-space gradient norm
        at the resident solution exceeds this (None disables screening —
        only declared entities re-solve).
    changed_entities: RE type -> entity keys that saw new data since the
        resident fit (the ingest layer's knowledge; may be empty — the
        gradient screen catches changed entities too, since new rows move
        the gradient off rounding scale).
    refresh_fixed_effects: also re-solve fixed-effect coordinates
        (warm-started from the resident coefficients, against the refreshed
        residuals). Off by default: the FE is the global slow-moving part
        of the model and the expensive solve a daily refresh exists to
        skip.
    """

    gradient_tolerance: float | None = 1e-4
    changed_entities: Mapping[str, Sequence] = dataclasses.field(
        default_factory=dict
    )
    refresh_fixed_effects: bool = False


@dataclasses.dataclass
class RefreshResult:
    """One incremental refresh's outcome + its selection evidence."""

    model: GameModel
    coordinate_stats: dict
    lanes_total: int = 0
    lanes_solved: int = 0
    lanes_changed: int = 0
    lanes_gradient: int = 0


class RefreshFingerprintError(ValueError):
    """A refresh (or its checkpoint) was attempted against a resident
    model trained under a different layout/λ-grid — raised fast, with the
    differing fields named (io.checkpoint.fingerprint_mismatch format)."""


def _shard_dim(shard) -> int:
    return int(getattr(shard, "feature_dim", None) or np.shape(shard)[1])


def _vocab_digest(keys) -> str:
    """Content digest of an entity vocab: same-SIZE membership drift (one
    entity churned out, one churned in) still re-sorts every later row, so
    the fingerprint must pin the vocab's CONTENT, not just its length.
    Keys normalize through str so a '<U3' dataset vocab and an int model
    vocab with equal keys digest equal."""
    import hashlib

    h = hashlib.sha1()
    for k in np.asarray(keys).tolist():
        h.update(str(k).encode())
        h.update(b"\x00")
    return h.hexdigest()[:12]


def expected_fingerprint(dataset, coordinate_configs, sequence,
                         reg_weights: Mapping[str, float] | None = None) -> dict:
    """This run's side of the refresh agreement: per-coordinate kind,
    feature-shard identity and width, entity-vocab size, and λ — computed
    from the CURRENT configs + data, compared field-by-field against
    :func:`model_fingerprint` of the resident model."""
    from photon_ml_tpu.estimators import (
        FixedEffectCoordinateConfig,
        MatrixFactorizationCoordinateConfig,
        RandomEffectCoordinateConfig,
    )

    fp: dict = {"sequence": ",".join(sequence)}
    for cid in sequence:
        cfg = coordinate_configs[cid]
        if isinstance(cfg, FixedEffectCoordinateConfig):
            fp[f"{cid}/kind"] = "fixed"
            fp[f"{cid}/shard"] = cfg.feature_shard_id
            fp[f"{cid}/dim"] = _shard_dim(
                dataset.feature_shards[cfg.feature_shard_id]
            )
        elif isinstance(cfg, RandomEffectCoordinateConfig):
            fp[f"{cid}/kind"] = "random"
            fp[f"{cid}/shard"] = cfg.feature_shard_id
            fp[f"{cid}/re_type"] = cfg.random_effect_type
            fp[f"{cid}/dim"] = _shard_dim(
                dataset.feature_shards[cfg.feature_shard_id]
            )
            fp[f"{cid}/entities"] = len(
                dataset.entity_vocabs[cfg.random_effect_type]
            )
            fp[f"{cid}/vocab"] = _vocab_digest(
                dataset.entity_vocabs[cfg.random_effect_type]
            )
        elif isinstance(cfg, MatrixFactorizationCoordinateConfig):
            fp[f"{cid}/kind"] = "matrix_factorization"
            fp[f"{cid}/re_type"] = (
                f"{cfg.row_effect_type}x{cfg.col_effect_type}"
            )
        if reg_weights is not None and cid in reg_weights:
            fp[f"{cid}/lambda"] = float(reg_weights[cid])
    return fp


def model_fingerprint(model: GameModel, sequence=None,
                      reg_weights: Mapping[str, float] | None = None) -> dict:
    """The resident model's side of the refresh agreement (same keys as
    :func:`expected_fingerprint`); ``reg_weights`` comes from the saved
    model's metadata (optimizationConfigurations.regWeights) when known."""
    from photon_ml_tpu.models.game import FixedEffectModel, RandomEffectModel
    from photon_ml_tpu.models.matrix_factorization import (
        MatrixFactorizationModel,
    )

    sequence = list(sequence if sequence is not None else model.models)
    fp: dict = {"sequence": ",".join(sequence)}
    for cid in sequence:
        m = model.models.get(cid)
        if m is None:
            continue  # the missing key itself surfaces in the diff
        if isinstance(m, FixedEffectModel):
            fp[f"{cid}/kind"] = "fixed"
            fp[f"{cid}/shard"] = m.feature_shard_id
            fp[f"{cid}/dim"] = int(
                np.shape(m.glm.coefficients.means)[0]
            )
        elif isinstance(m, RandomEffectModel):
            fp[f"{cid}/kind"] = "random"
            fp[f"{cid}/shard"] = m.feature_shard_id
            fp[f"{cid}/re_type"] = m.random_effect_type
            fp[f"{cid}/dim"] = int(
                m.feature_dim if m.is_compact
                else np.shape(m.coefficients)[1]
            )
            fp[f"{cid}/entities"] = int(np.shape(m.coefficients)[0])
            fp[f"{cid}/vocab"] = _vocab_digest(m.entity_keys)
        elif isinstance(m, MatrixFactorizationModel):
            fp[f"{cid}/kind"] = "matrix_factorization"
            fp[f"{cid}/re_type"] = (
                f"{m.row_effect_type}x{m.col_effect_type}"
            )
        if reg_weights is not None and cid in reg_weights:
            fp[f"{cid}/lambda"] = float(reg_weights[cid])
    return fp


def check_refresh_fingerprint(resident_fp: dict, expected_fp: dict) -> None:
    """Fail fast — naming the differing fields — when the resident model
    was trained under a different layout/λ-grid than this refresh run."""
    from photon_ml_tpu.io.checkpoint import fingerprint_mismatch

    mismatch = fingerprint_mismatch(resident_fp, expected_fp)
    if mismatch is not None:
        raise RefreshFingerprintError(
            "resident model is incompatible with this refresh "
            f"configuration ({mismatch}); refresh with the layout/λ-grid "
            "it was trained under, or run a full fit"
        )


def select_refresh_entities(
    coord: RandomEffectCoordinate,
    model,
    extra_offsets,
    policy: RefreshPolicy,
) -> tuple[np.ndarray, dict]:
    """(bool [num_entities] selection, {"changed": n, "gradient": n}):
    declared-changed entities union gradient-screened entities (see the
    module docstring)."""
    re_type = coord.re_dataset.random_effect_type
    num = int(coord.re_dataset.num_entities)
    changed = np.zeros(num, dtype=bool)
    keys = policy.changed_entities.get(re_type)
    if keys is not None and len(keys):
        vocab = np.asarray(coord.dataset.entity_vocabs[re_type])
        keys_arr = np.asarray(list(keys))
        if vocab.dtype.kind in "iu" and keys_arr.dtype.kind in "US":
            # CLI-declared keys are strings; an integer vocab compares
            # after a loud numeric parse (never a silent no-match)
            keys_arr = keys_arr.astype(vocab.dtype)
        elif vocab.dtype.kind in "US" and keys_arr.dtype.kind in "iu":
            keys_arr = keys_arr.astype(vocab.dtype)
        changed = np.isin(vocab, keys_arr)
        missing = np.unique(keys_arr[~np.isin(keys_arr, vocab)])
        if len(missing):
            # a typo'd or NEW entity has no table row to re-solve —
            # vocab growth needs a full fit (ROADMAP rider); loud, never
            # a silent no-match
            logger.warning(
                "refresh policy declares %d changed %r entit%s not in the "
                "resident vocab (%s): nothing re-solves for them — a NEW "
                "entity needs a full fit, a typo needs fixing",
                len(missing), re_type,
                "y" if len(missing) == 1 else "ies",
                ", ".join(repr(str(k)) for k in missing[:5])
                + (", ..." if len(missing) > 5 else ""),
            )
    graded = np.zeros(num, dtype=bool)
    if policy.gradient_tolerance is not None:
        norms = coord.refresh_gradient_norms(model, extra_offsets)
        # NaN = entity in no bucket: nothing to re-solve, never selected
        graded = np.nan_to_num(norms, nan=0.0) > policy.gradient_tolerance
    return changed | graded, {
        "changed": int(changed.sum()),
        "gradient": int(graded.sum()),
    }


def run_incremental_refresh(
    coordinates: Mapping[str, Coordinate],
    sequence: Sequence[str],
    resident_model: GameModel,
    policy: RefreshPolicy,
    *,
    checkpointer=None,
    resume: bool = True,
    check_finite: bool = True,
    telemetry=None,
    fingerprint: dict | None = None,
) -> RefreshResult:
    """One incremental refresh pass over ``sequence`` (see module
    docstring). ``fingerprint`` (optional) rides every checkpoint commit
    and guards resume: a mid-refresh checkpoint written under a different
    agreement fails fast naming the differing fields."""
    from photon_ml_tpu.io.checkpoint import (
        DivergenceError,
        commit_checkpoint,
        fingerprint_mismatch,
        game_model_from_arrays,
        game_model_to_arrays,
    )
    from photon_ml_tpu.telemetry import resilience_counters

    sequence = list(sequence)
    models: dict = {}
    for cid in sequence:
        if cid not in resident_model.models:
            raise RefreshFingerprintError(
                f"resident model has no coordinate '{cid}' — refresh runs "
                "under the layout the model was trained with (coordinates: "
                f"{list(resident_model.models)})"
            )
        models[cid] = resident_model.get(cid)

    if policy.changed_entities:
        consumed = {
            coordinates[cid].re_dataset.random_effect_type
            for cid in sequence
            if isinstance(coordinates[cid], RandomEffectCoordinate)
        }
        unconsumed = sorted(set(policy.changed_entities) - consumed)
        if unconsumed:
            # a typo'd reType — or an MF effect type — would otherwise
            # no-op silently while the summary reads "refreshed"
            logger.warning(
                "refresh policy declares changed entities for effect "
                "type(s) %s, but no refreshable random-effect coordinate "
                "consumes them — fixed-effect and MF coordinates carry "
                "over (entity-granular MF refresh is a ROADMAP rider)",
                unconsumed,
            )

    coordinate_stats: dict = {}
    totals = {"lanes_total": 0, "lanes_solved": 0, "lanes_changed": 0,
              "lanes_gradient": 0}
    start_pos = 0
    if checkpointer is not None and resume:
        ckpt = checkpointer.restore()
        if ckpt is not None:
            if ckpt.meta.get("kind") != "incremental_refresh":
                raise ValueError(
                    f"checkpoint at {checkpointer.directory} is not an "
                    f"incremental-refresh checkpoint "
                    f"(kind={ckpt.meta.get('kind')!r}); use a fresh "
                    "checkpoint directory"
                )
            saved = ckpt.meta.get("refresh", {})
            if list(saved.get("sequence", [])) != sequence:
                raise ValueError(
                    "refresh checkpoint is incompatible with this run: it "
                    f"covers coordinates {saved.get('sequence')} but the "
                    f"update sequence is {sequence}; pass resume=False or "
                    "a fresh checkpoint directory"
                )
            if fingerprint is not None:
                mismatch = fingerprint_mismatch(
                    saved.get("fingerprint"), fingerprint
                )
                if mismatch is not None:
                    raise RefreshFingerprintError(
                        f"refresh checkpoint at {checkpointer.directory} "
                        f"was written under a different agreement "
                        f"({mismatch}); resume with the original "
                        "layout/λ-grid, or use a fresh checkpoint directory"
                    )
            restored = game_model_from_arrays(ckpt.arrays, ckpt.meta["model"])
            models.update(restored.models)
            coordinate_stats = dict(saved.get("stats", {}))
            totals.update(saved.get("totals", {}))
            start_pos = int(saved.get("position", 0))
            resilience_counters.record_checkpoint_restore()
            if start_pos >= len(sequence):
                # a COMPLETED refresh checkpoint (e.g. yesterday's run in
                # the same directory): every coordinate fast-forwards and
                # the CHECKPOINTED model comes back untouched — correct
                # for an idempotent re-run, wrong for new data. Loud, so
                # a daily-refresh operator reaching for fresh data knows
                # to pass resume=False or a fresh checkpoint directory.
                logger.warning(
                    "refresh checkpoint at %s already covers the whole "
                    "update sequence — returning the checkpointed model "
                    "WITHOUT re-reading today's data; pass resume=False "
                    "(--no-resume) or a fresh checkpoint directory to "
                    "refresh against new data",
                    checkpointer.directory,
                )
            logger.info(
                "Resuming incremental refresh from coordinate %d/%d",
                start_pos, len(sequence),
            )

    scores = {cid: coordinates[cid].score(models[cid]) for cid in sequence}

    def full_score():
        it = iter(scores.values())
        total = next(it).copy()
        for s in it:
            total = total + s
        return total

    def commit(position: int) -> None:
        if checkpointer is None:
            return
        arrays, model_meta = game_model_to_arrays(
            GameModel(models=dict(models))
        )
        meta = {
            "kind": "incremental_refresh",
            "model": model_meta,
            "refresh": {
                "fingerprint": fingerprint,
                "position": position,
                "sequence": sequence,
                "stats": coordinate_stats,
                "totals": totals,
            },
        }
        # the ONE gated write site (lint check 10); refresh is
        # single-process, so the rank gate is a pass-through
        commit_checkpoint(checkpointer, position, arrays, meta)

    for position, cid in enumerate(sequence):
        if position < start_pos:
            continue  # completed before the restored checkpoint
        coord = coordinates[cid]
        is_re = isinstance(coord, RandomEffectCoordinate)
        with tracing.span("refresh/coordinate", cat="refresh",
                          coordinate=cid, position=position):
            if not is_re:
                if (
                    policy.refresh_fixed_effects
                    and isinstance(coord, FixedEffectCoordinate)
                ):
                    partial = full_score() - scores[cid]
                    model_new, _info = coord.update_model(models[cid], partial)
                    models[cid] = model_new
                    scores[cid] = coord.score(model_new)
                    coordinate_stats[cid] = {"refreshed": True, "kind": "fe"}
                else:
                    # fixed effects / MF / locked coordinates carry over
                    # untouched (their scores still anchor the residuals)
                    refresh_counters.record_carried_coordinate()
                    coordinate_stats[cid] = {"refreshed": False}
                    commit(position + 1)
                    continue
            else:
                partial = full_score() - scores[cid]
                selection, sel_stats = select_refresh_entities(
                    coord, models[cid], partial, policy
                )
                coord.set_refresh_selection(selection)
                try:
                    model_new, _info = coord.update_model(models[cid], partial)
                finally:
                    coord.set_refresh_selection(None)
                models[cid] = model_new
                scores[cid] = coord.score(model_new)
                sched = coord.last_refresh_stats
                stats = {
                    "refreshed": True,
                    "kind": "re",
                    "lanes_total": int(sched.lanes_total),
                    "lanes_solved": int(sched.lanes_probed),
                    "lanes_changed": sel_stats["changed"],
                    "lanes_gradient": sel_stats["gradient"],
                }
                coordinate_stats[cid] = stats
                totals["lanes_total"] += stats["lanes_total"]
                totals["lanes_solved"] += stats["lanes_solved"]
                totals["lanes_changed"] += stats["lanes_changed"]
                totals["lanes_gradient"] += stats["lanes_gradient"]
                refresh_counters.record_selection(
                    lanes_total=stats["lanes_total"],
                    lanes_solved=stats["lanes_solved"],
                    lanes_changed=stats["lanes_changed"],
                    lanes_gradient=stats["lanes_gradient"],
                )
            if check_finite:
                # reduce on device: only a scalar crosses to the host
                if not bool(jnp.isfinite(jnp.asarray(scores[cid])).all()):
                    raise DivergenceError(
                        f"coordinate '{cid}' produced non-finite scores "
                        "during incremental refresh"
                        + (
                            f"; last good checkpoint: step "
                            f"{checkpointer.latest_step()} in "
                            f"{checkpointer.directory}"
                            if checkpointer is not None else ""
                        )
                    )
            if telemetry is not None:
                telemetry.heartbeat(
                    "game_refresh", position=position + 1,
                    num_coordinates=len(sequence),
                )
            commit(position + 1)

    return RefreshResult(
        model=GameModel(models=dict(models)),
        coordinate_stats=coordinate_stats,
        lanes_total=totals["lanes_total"],
        lanes_solved=totals["lanes_solved"],
        lanes_changed=totals["lanes_changed"],
        lanes_gradient=totals["lanes_gradient"],
    )
