"""Training coordinates: fixed-effect and random-effect updates.

Reference parity: photon-api algorithm/FixedEffectCoordinate.scala:91-165
(broadcast model, treeAggregate-driven optimize, score = map dot-product),
algorithm/RandomEffectCoordinate.scala:104-153 (per-entity local solves),
locked-model coordinates (FixedEffectModelCoordinate,
RandomEffectModelCoordinate), algorithm/CoordinateFactory.scala:50-111.

TPU-native:
- Fixed effect: one jitted solve over the sample-sharded batch; gradients
  all-reduce over the mesh "data" axis automatically under jit (this is
  where Spark treeAggregate went).
- Random effect: ``vmap(minimize_*)`` over each entity bucket — thousands of
  independent solvers advancing in lock-step on the MXU instead of
  thousands of RDD records each running breeze. Warm start flows in as the
  per-entity coefficient rows; results scatter back into the [E, d] table.
- Residual offsets arrive via ``extra_offsets`` (the partial-score
  mechanism of CoordinateDescent, reference Coordinate.scala:60-63).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.batch import LabeledPointBatch
from photon_ml_tpu.data.sparse_batch import SparseLabeledPointBatch
from photon_ml_tpu.sampling import down_sampler_for_task
from photon_ml_tpu.data.game_data import GameDataset, RandomEffectDataset
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import (
    DatumScoringModel,
    FixedEffectModel,
    RandomEffectModel,
    score_random_effect,
)
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.normalization import NormalizationContext, no_normalization
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.sparse_objective import SparseGLMObjective
from photon_ml_tpu.ops.variance import (
    FULL_VARIANCE_MAX_DIM,
    coefficient_variances,
    diag_inverse_from_hessian,
    full_inverse_from_hessian,
    inverse_of_diagonal,
    resolve_variance_mode,
    resolve_variance_mode_for,
    validate_variance_mode,
)
from photon_ml_tpu.optim.common import LaneTrace, LaneTraces
from photon_ml_tpu.telemetry.program_ledger import ledger_jit
from photon_ml_tpu.optim.optimizer import (
    OptimizerConfig,
    OptimizerType,
    resolve_auto_optimizer,
    solve,
)
from photon_ml_tpu.projector.projectors import ProjectorType
from photon_ml_tpu.types import TaskType

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class CoordinateOptimizationConfig:
    """Per-coordinate optimization settings (reference
    GLMOptimizationConfiguration: optimizer + reg weights + variance flag)."""

    optimizer: OptimizerConfig
    l2_weight: float = 0.0
    l1_weight: float = 0.0
    compute_variance: bool = False
    variance_mode: str = "auto"  # "auto" | "full" (diag(H⁻¹)) | "diagonal"
    down_sampling_rate: float = 1.0

    def __post_init__(self):
        validate_variance_mode(self.variance_mode)

    @property
    def uses_owlqn(self) -> bool:
        return self.l1_weight > 0.0 or self.optimizer.optimizer_type == OptimizerType.OWLQN


class Coordinate:
    """One block of the coordinate-descent update (reference Coordinate[D])."""

    coordinate_id: str

    def update_model(self, model: DatumScoringModel, extra_offsets: Array):
        """Train this coordinate with residual offsets; returns (model, info)."""
        raise NotImplementedError

    def score(self, model: DatumScoringModel) -> Array:
        raise NotImplementedError

    def initial_model(self) -> DatumScoringModel:
        raise NotImplementedError


def _make_objective(task: TaskType, cfg: CoordinateOptimizationConfig,
                    normalization: NormalizationContext | None,
                    sparse: bool = False,
                    use_pallas: bool | None = False) -> GLMObjective | SparseGLMObjective:
    """use_pallas MUST stay False for any objective whose solve is vmapped
    (per-entity RE/MF buckets, λ-grid lanes): `lax.while_loop` bodies trace
    with UNBATCHED tracers, so runtime batch-tracer detection cannot see the
    vmap — a Pallas call baked into the loop body then gets batched into a
    serial per-lane loop (~lanes× slower; the r4 bench regression). Only
    un-vmapped solve paths (the FE coordinate) pass None (= auto/on-TPU)."""
    if sparse:
        return SparseGLMObjective(
            loss_for_task(task),
            l2_weight=cfg.l2_weight,
            normalization=normalization,
        )
    return GLMObjective(
        loss_for_task(task),
        l2_weight=cfg.l2_weight,
        normalization=normalization,
        use_pallas=use_pallas,
    )


def _solve_config(
    cfg: CoordinateOptimizationConfig,
    *,
    loss=None,
    small_dense: bool = False,
) -> OptimizerConfig:
    """Concrete solver config for one coordinate solve: resolves AUTO
    (NEWTON on eligible small-d dense vmapped solves — RE/MF buckets —
    LBFGS elsewhere; optim/optimizer.resolve_auto_optimizer) and then
    applies the elastic-net OWLQN flip, which overrides any resolution
    exactly as it overrides an explicit LBFGS."""
    opt = resolve_auto_optimizer(
        cfg.optimizer, loss=loss, small_dense=small_dense
    )
    if cfg.uses_owlqn:
        opt = dataclasses.replace(
            opt, optimizer_type=OptimizerType.OWLQN, l1_weight=cfg.l1_weight
        )
    return opt


@dataclasses.dataclass
class FixedEffectCoordinate(Coordinate):
    """Trains one GLM on a feature shard over the full (sharded) sample axis.

    Models are held in *original* feature space: training converts the warm
    start into normalized space, solves there, and converts back
    (NormalizationContext.to_model_space), so scoring and persistence never
    need the normalization context (reference saves original-space models
    too, NormalizationContext.modelToOriginalSpace).
    """

    coordinate_id: str
    dataset: GameDataset
    feature_shard_id: str
    task: TaskType
    config: CoordinateOptimizationConfig
    normalization: NormalizationContext | None = None
    intercept_index: int | None = None
    #: single-pass kernel on this (un-vmapped, dense) solve: None = TPU
    #: auto, True = force (interpret off-TPU), False = off
    use_pallas: bool | None = None
    _update_count: int = dataclasses.field(default=0, init=False, repr=False)

    def initial_model(self) -> FixedEffectModel:
        shard = self.dataset.feature_shards[self.feature_shard_id]
        from photon_ml_tpu.data.batch import solve_dtype_of

        return FixedEffectModel(
            glm=GeneralizedLinearModel(
                Coefficients.zeros(
                    shard.shape[1], dtype=solve_dtype_of(shard.dtype)
                ),
                self.task,
            ),
            feature_shard_id=self.feature_shard_id,
        )

    def update_model(self, model: FixedEffectModel, extra_offsets: Array | None = None):
        batch = self.dataset.fixed_effect_batch(self.feature_shard_id, extra_offsets)
        if self.config.down_sampling_rate < 1.0:
            # Training-only thinning via weight zeroing (reference
            # DistributedOptimizationProblem.runWithSampling:145-160); scoring
            # below still covers every sample. The seed rotates per update so
            # excluded rows differ across coordinate-descent iterations, like
            # the reference's per-update random seed — but deterministically.
            sampler = down_sampler_for_task(self.task, self.config.down_sampling_rate)
            new_w = sampler.down_sample_weights(
                np.asarray(self.dataset.labels),
                np.asarray(self.dataset.weights),
                self.dataset.unique_ids,
                seed=self._update_count,
            )
            self._update_count += 1
            batch = batch.replace(weights=jnp.asarray(new_w, dtype=batch.weights.dtype))
        # default use_pallas=None (auto): the FE solve is the one UN-vmapped
        # dense hot loop, where the single-pass Pallas kernel measures ~2x
        # the autodiff path on TPU (BASELINE.md r4 study; harmless no-op for
        # sparse batches, whose objective has no kernel)
        objective = _make_objective(
            self.task, self.config, self.normalization,
            sparse=isinstance(batch, SparseLabeledPointBatch),
            use_pallas=self.use_pallas,
        )
        if self.config.compute_variance:
            # fail a full-variance-on-sparse config BEFORE the (possibly
            # giant-d, hours-long) solve, not after
            resolve_variance_mode_for(
                objective, self.config.variance_mode, batch.dim
            )
        norm = objective.normalization
        w0 = norm.from_model_space(model.glm.coefficients.means, self.intercept_index)
        result = _jitted_fe_solve(
            objective, _solve_config(self.config, loss=objective.loss),
            batch, w0,
        )
        means = norm.to_model_space(result.coefficients, self.intercept_index)
        variances = None
        if self.config.compute_variance:
            variances = norm.variances_to_model_space(
                coefficient_variances(
                    objective, result.coefficients, batch,
                    mode=self.config.variance_mode,
                )
            )
        glm = GeneralizedLinearModel(
            Coefficients(means=means, variances=variances), self.task
        )
        return FixedEffectModel(glm=glm, feature_shard_id=self.feature_shard_id), result

    def score(self, model: FixedEffectModel) -> Array:
        return model.score_dataset(self.dataset)


@partial(ledger_jit, label="coord/fe_solve", static_argnums=(0, 1))
def _jitted_fe_solve(objective: GLMObjective, opt: OptimizerConfig,
                     batch: LabeledPointBatch, w0: Array):
    return solve(opt, objective.bind(batch), w0)


@dataclasses.dataclass
class RandomEffectCoordinate(Coordinate):
    """Per-entity solves over bucketed padded blocks, vmapped."""

    coordinate_id: str
    dataset: GameDataset
    re_dataset: RandomEffectDataset
    task: TaskType
    config: CoordinateOptimizationConfig
    normalization: NormalizationContext | None = None
    intercept_index: int | None = None
    #: probe/rescue lane-scheduler state (algorithm/lane_scheduler.py),
    #: created on first scheduled update when the coordinate's
    #: OptimizerConfig carries a LaneSchedulerConfig; persists across CD
    #: iterations (host bucket caches + cross-sweep active sets)
    _scheduler: object = dataclasses.field(default=None, init=False, repr=False)
    #: (iteration, num_iterations) from the CD loop — the active set needs
    #: to know the final sweep (it runs everyone). Standalone update_model
    #: calls leave it None, which means "treat as final": never skip.
    _sweep_context: tuple = dataclasses.field(default=None, init=False, repr=False)
    #: bool [num_entities] refresh selection (algorithm/refresh.py): when
    #: set, update_model re-solves ONLY the selected entities' lanes
    #: (compacted; warm-started from the incoming table) and the rest carry
    #: over BITWISE. None (default) is the unchanged full solve — the
    #: refresh path is strictly opt-in.
    _refresh_selection: object = dataclasses.field(default=None, init=False, repr=False)
    #: SchedulerStats of the last refresh-selected solve (telemetry)
    last_refresh_stats: object = dataclasses.field(default=None, init=False, repr=False)

    def set_sweep(self, iteration: int, num_iterations: int) -> None:
        """Cross-sweep context hook, called by run_coordinate_descent before
        each update (CoordinateDescent.scala:198-255's per-iteration loop is
        where the reference knows the sweep index too)."""
        self._sweep_context = (iteration, num_iterations)

    def set_refresh_selection(self, selected: "np.ndarray | None") -> None:
        """Install (or clear, with None) the refresh policy's entity
        selection for the next ``update_model`` — the partial-retraining
        counterpart of the reference's locked coordinates
        (CoordinateDescent.scala:44-49), at ENTITY granularity instead of
        coordinate granularity (algorithm/refresh.py)."""
        if selected is None:
            self._refresh_selection = None
            return
        selected = np.ascontiguousarray(selected, dtype=bool)
        if selected.shape != (self.re_dataset.num_entities,):
            raise ValueError(
                f"refresh selection covers {selected.shape} but coordinate "
                f"'{self.coordinate_id}' has "
                f"{self.re_dataset.num_entities} entities"
            )
        self._refresh_selection = selected

    def initial_model(self) -> RandomEffectModel:
        from photon_ml_tpu.data.batch import solve_dtype_of

        re = self.re_dataset
        dtype = solve_dtype_of(
            self.dataset.feature_shards[re.feature_shard_id].dtype
        )
        return RandomEffectModel(
            # compact (sparse-shard) coordinates hold [E, K] tables over each
            # entity's active columns; dense hold [E, dim]
            coefficients=jnp.zeros(
                (re.num_entities, re.table_width), dtype=dtype
            ),
            entity_keys=self.dataset.entity_vocabs[re.random_effect_type],
            random_effect_type=re.random_effect_type,
            feature_shard_id=re.feature_shard_id,
            task=self.task,
            active_cols=re.active_cols,
            feature_dim=re.dim if re.is_compact else None,
        )

    def _prepare_solve(self, model: RandomEffectModel, extra_offsets: Array | None):
        """Shared solve prologue for ``update_model`` and
        ``refresh_gradient_norms``: validates the projector/normalization
        composition and converts the model into solve space. Returns
        (objective, projector, full_offsets, norm, compact_cols, table)."""
        projector = self.re_dataset.projector_type
        if (
            projector == ProjectorType.RANDOM
            and self.normalization is not None
            and not self.re_dataset.pre_normalized
        ):
            # normalization must be applied BEFORE the sketch (exact),
            # which happens at dataset build; a post-hoc context cannot be
            # folded through P (the reference's projected-context approach,
            # ProjectionMatrixBroadcast.projectNormalizationContext, does
            # not commute with per-feature scaling and is not reproduced)
            raise ValueError(
                "RANDOM-projected coordinate with normalization: the "
                "RandomEffectDataset must be built with the same "
                "normalization (build_random_effect_dataset(normalization=...)) "
                "so features are normalized before sketching"
            )
        # RANDOM-projected variances are PROPAGATED properly below:
        # var(w) = diag(P H_k⁻¹ Pᵀ). (The reference back-projects means but
        # passes the projected-space variance vector through unchanged —
        # ProjectionMatrixBroadcast.scala:76 — which we refuse to reproduce;
        # this is the mathematically consistent improvement.)
        if (
            self.re_dataset.is_compact
            and self.normalization is not None
            and self.normalization.shifts is not None
        ):
            raise ValueError(
                "compact (sparse-shard) random-effect coordinates support "
                "SCALE-only normalization; mean shifts (STANDARDIZATION) "
                "would densify the feature space"
            )
        if (
            projector == ProjectorType.INDEX_MAP
            and self.normalization is not None
            and not self.re_dataset.pre_normalized
        ):
            raise ValueError(
                "INDEX_MAP coordinate with normalization: the "
                "RandomEffectDataset must be built with the same "
                "normalization (build_random_effect_dataset(normalization=...)) "
                "so entity blocks are pre-normalized"
            )
        if self.re_dataset.pre_normalized and self.normalization is None:
            raise ValueError(
                "this RandomEffectDataset was built pre-normalized but the "
                "coordinate has no normalization context — its solved "
                "tables would be emitted as model-space coefficients while "
                "actually living in normalized space"
            )
        # pre-normalized projected blocks already hold x' = (x-shift)*factor
        # (INDEX_MAP: per-entity gathered columns; RANDOM: normalized before
        # sketching), so the SOLVE runs on a plain objective; table/model
        # conversions and variance post-processing still use the context
        solve_norm = (
            None if projector in (ProjectorType.INDEX_MAP, ProjectorType.RANDOM)
            else self.normalization
        )
        objective = _make_objective(self.task, self.config, solve_norm)
        full_offsets = self.dataset.offsets
        if extra_offsets is not None:
            full_offsets = full_offsets + extra_offsets
        norm = (
            self.normalization if self.normalization is not None
            else no_normalization()
        )
        compact_cols = (
            jnp.asarray(self.re_dataset.active_cols)
            if self.re_dataset.is_compact else None
        )
        if compact_cols is not None:
            # compact tables convert per entity through gathered factors
            table = norm.from_model_space_compact(
                model.coefficients, compact_cols
            )
        else:
            table = norm.from_model_space(model.coefficients, self.intercept_index)
        return objective, projector, full_offsets, norm, compact_cols, table

    def update_model(self, model: RandomEffectModel, extra_offsets: Array | None = None):
        objective, projector, full_offsets, norm, compact_cols, table = (
            self._prepare_solve(model, extra_offsets)
        )
        # AUTO resolves to NEWTON here: the per-entity bucket solve is
        # exactly the small-d dense vmapped shape the batched-Newton
        # solver was measured on (BASELINE.md r5)
        opt = _solve_config(self.config, loss=objective.loss, small_dense=True)

        traces: list[LaneTrace] = []
        refresh_sel = self._refresh_selection
        if refresh_sel is not None:
            table, traces = self._solve_refresh(
                objective, opt, projector, full_offsets, table, refresh_sel
            )
        elif opt.scheduler is not None:
            table, traces = self._solve_scheduled(
                objective, opt, projector, full_offsets, table
            )
        elif projector == ProjectorType.INDEX_MAP:
            # extra scratch column absorbs the padding scatter/gather slots
            table_ext = jnp.concatenate(
                [table, jnp.zeros((table.shape[0], 1), table.dtype)], axis=1
            )
            for bucket in self.re_dataset.buckets:
                table_ext, trace = _jitted_re_bucket_solve_indexmap(
                    objective, opt,
                    bucket.features, bucket.labels, bucket.weights,
                    bucket.sample_rows, bucket.entity_rows, bucket.col_index,
                    full_offsets, table_ext,
                )
                traces.append(trace)
            table = table_ext[:, :-1]
        elif projector == ProjectorType.RANDOM:
            matrix = jnp.asarray(self.re_dataset.projection.matrix, dtype=table.dtype)
            for bucket in self.re_dataset.buckets:
                table, trace = _jitted_re_bucket_solve_random(
                    objective, opt,
                    bucket.features, bucket.labels, bucket.weights,
                    bucket.sample_rows, bucket.entity_rows,
                    matrix, full_offsets, table,
                )
                traces.append(trace)
        else:
            for bucket in self.re_dataset.buckets:
                table, trace = _jitted_re_bucket_solve(
                    objective, opt,
                    bucket.features, bucket.labels, bucket.weights,
                    bucket.sample_rows, bucket.entity_rows,
                    full_offsets, table,
                )
                traces.append(trace)
        variances = None
        if self.config.compute_variance:
            # per-entity diag(H⁻¹): one batched Cholesky per bucket
            # (reference SingleNodeOptimizationProblem.computeVariances:58-69
            # runs this per RDD record; here the entity axis is vmapped).
            # Mode resolution budgets for the whole [e, d, d] Hessian stack
            # of the largest bucket, not one Hessian. Entities in no bucket
            # (below active_data_lower_bound / vocab-only) keep NaN — "no
            # variance computed" — and the model writer drops their
            # variances field rather than persisting a false 0.
            max_bucket = max(
                (b.entity_rows.shape[0] for b in self.re_dataset.buckets),
                default=1,
            )
            if projector == ProjectorType.RANDOM:
                # propagate through the sketch: var(w) = diag(P H_k⁻¹ Pᵀ)
                resolved = random_variance_mode(
                    self.config.variance_mode,
                    self.re_dataset.dim,
                    int(self.re_dataset.projection.matrix.shape[1]),
                    max_bucket,
                )
                kernel = (
                    _jitted_re_bucket_variances_random if resolved == "full"
                    else _jitted_re_bucket_variances_random_diagonal
                )
                matrix = jnp.asarray(
                    self.re_dataset.projection.matrix, dtype=table.dtype
                )
                var_table = jnp.full_like(table, jnp.nan)
                for bucket in self.re_dataset.buckets:
                    var_table = kernel(
                        objective,
                        bucket.features, bucket.labels, bucket.weights,
                        bucket.sample_rows, bucket.entity_rows,
                        matrix, full_offsets, table, var_table,
                    )
            elif projector == ProjectorType.INDEX_MAP:
                # solve-space diag(H⁻¹) over each entity's active columns,
                # scattered back through the same index maps as the means —
                # the reference's IndexMapProjectorRDD.scala:103 contract.
                # Inactive columns keep NaN ("no variance computed": the
                # reference's projected model simply has no entry there).
                width = max(
                    (int(b.features.shape[2]) for b in self.re_dataset.buckets),
                    default=1,
                )
                resolved = resolve_variance_mode(
                    self.config.variance_mode, width, num_problems=max_bucket
                )
                kernel = (
                    _jitted_re_bucket_variances_indexmap
                    if resolved == "full"
                    else _jitted_re_bucket_variances_indexmap_diagonal
                )
                table_ext = jnp.concatenate(
                    [table, jnp.zeros((table.shape[0], 1), table.dtype)],
                    axis=1,
                )
                var_ext = jnp.full_like(table_ext, jnp.nan)
                for bucket in self.re_dataset.buckets:
                    var_ext = kernel(
                        objective,
                        bucket.features, bucket.labels, bucket.weights,
                        bucket.sample_rows, bucket.entity_rows,
                        bucket.col_index, full_offsets, table_ext, var_ext,
                    )
                var_table = var_ext[:, :-1]
            else:
                resolved = resolve_variance_mode(
                    self.config.variance_mode, self.re_dataset.dim,
                    num_problems=max_bucket,
                )
                kernel = (
                    _jitted_re_bucket_variances if resolved == "full"
                    else _jitted_re_bucket_variances_diagonal
                )
                var_table = jnp.full_like(table, jnp.nan)
                for bucket in self.re_dataset.buckets:
                    var_table = kernel(
                        objective,
                        bucket.features, bucket.labels, bucket.weights,
                        bucket.sample_rows, bucket.entity_rows,
                        full_offsets, table, var_table,
                    )
            variances = (
                norm.variances_to_model_space_compact(var_table, compact_cols)
                if compact_cols is not None
                else norm.variances_to_model_space(var_table)
            )
        table = (
            norm.to_model_space_compact(table, compact_cols)
            if compact_cols is not None
            else norm.to_model_space(table, self.intercept_index)
        )
        if refresh_sel is not None:
            # untouched entities carry over BITWISE — the compacted solve
            # never scatters into their rows, and this restore also erases
            # any normalization from/to-model-space round-off on them
            sel = jnp.asarray(refresh_sel)[:, None]
            table = jnp.where(
                sel, table, jnp.asarray(model.coefficients, dtype=table.dtype)
            )
            # variances follow the same carry-over rule: unselected
            # entities KEEP the resident variances; selected entities get
            # the freshly computed ones, or NaN ("no variance computed" —
            # the model writer drops NaN) when this refresh did not run
            # the variance pass. A refresh must never silently drop the
            # resident model's variances or overwrite carried entities'
            # variances under the new residuals.
            if variances is not None or model.variances is not None:
                nans = jnp.full(table.shape, jnp.nan, table.dtype)
                variances = jnp.where(
                    sel,
                    nans if variances is None
                    else jnp.asarray(variances, dtype=table.dtype),
                    nans if model.variances is None
                    else jnp.asarray(model.variances, dtype=table.dtype),
                )
        # info = the per-bucket lane traces: the coordinate-descent loop
        # hands them to telemetry (convergence-reason tallies over every
        # vmapped entity lane). LaneTraces keeps the device arrays unmerged —
        # no eager concatenate dispatches — so an update with no telemetry
        # attached pays nothing; consumers merge host-side.
        info = LaneTraces(traces) if traces else None
        return dataclasses.replace(
            model, coefficients=table, variances=variances
        ), info

    def score(self, model: RandomEffectModel) -> Array:
        return model.score_dataset(self.dataset)

    def _scheduler_blocks(self, projector) -> list:
        """Bucket field dicts in the shape the lane scheduler consumes."""
        return [
            {
                "features": b.features,
                "labels": b.labels,
                "weights": b.weights,
                "sample_rows": b.sample_rows,
                "entity_rows": b.entity_rows,
                **({"col_index": b.col_index}
                   if projector == ProjectorType.INDEX_MAP else {}),
            }
            for b in self.re_dataset.buckets
        ]

    def _projection_matrix(self, projector, dtype):
        return (
            jnp.asarray(self.re_dataset.projection.matrix, dtype=dtype)
            if projector == ProjectorType.RANDOM else None
        )

    def _solve_scheduled(self, objective, opt, projector, full_offsets, table):
        """Probe/rescue (+ cross-sweep active-set) solve of every bucket via
        algorithm/lane_scheduler.py; returns (table, host-numpy traces)."""
        # lazy import: lane_scheduler builds on this module's bucket solvers
        from photon_ml_tpu.algorithm.lane_scheduler import LaneScheduler

        if self._scheduler is None or self._scheduler.config != opt.scheduler:
            self._scheduler = LaneScheduler(opt.scheduler)
        iteration, num_iterations = self._sweep_context or (0, 1)
        table, traces, _stats = self._scheduler.solve(
            objective, opt, self._scheduler_blocks(projector), full_offsets,
            table,
            projector=projector,
            matrix=self._projection_matrix(projector, table.dtype),
            final_sweep=iteration >= num_iterations - 1,
        )
        return table, traces

    def _solve_refresh(self, objective, opt, projector, full_offsets, table,
                       selected: np.ndarray):
        """Refresh-policy solve (algorithm/refresh.py): the lane scheduler's
        active-set freezing promoted to an EXTERNALLY chosen set — compact
        and re-solve only the selected entities' lanes with the full
        iteration budget, warm-started from the resident table rows;
        unselected rows are never scattered into. A fresh scheduler per
        call: a refresh selection does not outlive its update."""
        from photon_ml_tpu.algorithm.lane_scheduler import LaneScheduler
        from photon_ml_tpu.optim.optimizer import LaneSchedulerConfig

        base = dataclasses.replace(opt, scheduler=None)
        # probe budget == the whole budget: one compacted solve of the
        # selected lanes, no rescue phase
        # the probe IS the whole solve here (no rescue phase), so the
        # "probe flags rarely fire without a live function stop" warning
        # does not apply
        scheduler = LaneScheduler(
            LaneSchedulerConfig(probe_iterations=base.max_iterations),
            warn_no_live_stop=False,
        )
        scheduler.freeze_rows(~selected)
        table, traces, stats = scheduler.solve(
            objective, base, self._scheduler_blocks(projector), full_offsets,
            table,
            projector=projector,
            matrix=self._projection_matrix(projector, table.dtype),
            final_sweep=False,
        )
        self.last_refresh_stats = stats
        return table, traces

    def refresh_gradient_norms(
        self, model: RandomEffectModel, extra_offsets: Array | None = None
    ) -> np.ndarray:
        """[num_entities] solve-space gradient norms of ``model`` at its own
        coefficients — the refresh policy's screening signal
        (algorithm/refresh.py): an entity whose data changed since the
        resident solve leaves a gradient well above rounding scale, while a
        converged untouched entity sits at it. One vmapped gradient pass
        per bucket (no solver state); entities in no bucket return NaN
        (nothing to re-solve)."""
        objective, projector, full_offsets, _norm, _cols, table = (
            self._prepare_solve(model, extra_offsets)
        )
        num_rows = int(table.shape[0])
        out = np.full(num_rows, np.nan)
        matrix = self._projection_matrix(projector, table.dtype)
        if projector == ProjectorType.INDEX_MAP:
            table_ext = jnp.concatenate(
                [table, jnp.zeros((num_rows, 1), table.dtype)], axis=1
            )
        for b in self.re_dataset.buckets:
            if projector == ProjectorType.INDEX_MAP:
                norms = _jitted_re_bucket_grad_norms_indexmap(
                    objective, b.features, b.labels, b.weights,
                    b.sample_rows, b.entity_rows, b.col_index,
                    full_offsets, table_ext,
                )
            elif projector == ProjectorType.RANDOM:
                norms = _jitted_re_bucket_grad_norms_random(
                    objective, b.features, b.labels, b.weights,
                    b.sample_rows, b.entity_rows, matrix,
                    full_offsets, table,
                )
            else:
                norms = _jitted_re_bucket_grad_norms(
                    objective, b.features, b.labels, b.weights,
                    b.sample_rows, b.entity_rows, full_offsets, table,
                )
            rows = np.asarray(b.entity_rows)
            valid = (rows >= 0) & (rows < num_rows)
            out[rows[valid]] = np.asarray(norms)[valid]
        return out


def _bucket_offsets(sample_rows: Array, full_offsets: Array) -> Array:
    safe = jnp.maximum(sample_rows, 0)
    return jnp.where(sample_rows >= 0, full_offsets[safe], 0.0)


def _solve_bucket_entities(
    objective: GLMObjective,
    opt: OptimizerConfig,
    features: Array,  # [e, cap, k]
    labels: Array,  # [e, cap]
    weights: Array,  # [e, cap]
    offsets: Array,  # [e, cap]
    w0s: Array,  # [e, k]
) -> tuple[Array, LaneTrace]:
    """vmapped per-entity solves: ([e, k] solved coefficients, [e] trace).

    The trace carries each lane's final iteration count / convergence reason
    / value — tiny extra outputs XLA computes anyway; consumers that only
    want the table drop it (DCE removes the cost)."""

    def solve_one(f, l, o, w, w0):
        batch = LabeledPointBatch(features=f, labels=l, offsets=o, weights=w)
        result = solve(opt, objective.bind(batch), w0)
        trace = LaneTrace(
            iterations=result.iterations,
            reason=result.reason,
            value=result.value,
            gradient_norm=result.gradient_norm,
            valid=jnp.asarray(True),
        )
        return result.coefficients, trace

    return jax.vmap(solve_one)(features, labels, offsets, weights, w0s)


def _mask_padding_lanes(trace: LaneTrace, entity_rows: Array, num_rows: int) -> LaneTrace:
    """Mark padding lanes invalid: OOB-sentinel entity rows (gathers clamp,
    scatters drop) solve all-zero-weight batches whose iteration counts and
    reasons must not pollute convergence tallies."""
    return trace.replace(valid=(entity_rows >= 0) & (entity_rows < num_rows))


def solve_entity_bucket(
    objective: GLMObjective,
    opt: OptimizerConfig,
    features: Array,  # [e, cap, d]
    labels: Array,  # [e, cap]
    weights: Array,  # [e, cap]
    sample_rows: Array,  # [e, cap]
    entity_rows: Array,  # [e]
    full_offsets: Array,  # [n]
    table: Array,  # [E, d]
) -> Array:
    """Solve every entity in a bucket and scatter results into the table.

    Pure/traceable: reused by the single-chip jit wrapper below and by the
    mesh-sharded full-GAME train step (parallel/distributed.py), where the
    entity axis shards over the mesh's "data" axis.
    """
    table, _trace = solve_entity_bucket_traced(
        objective, opt, features, labels, weights, sample_rows, entity_rows,
        full_offsets, table,
    )
    return table


def solve_entity_bucket_traced(
    objective: GLMObjective,
    opt: OptimizerConfig,
    features: Array,
    labels: Array,
    weights: Array,
    sample_rows: Array,
    entity_rows: Array,
    full_offsets: Array,
    table: Array,
) -> tuple[Array, LaneTrace]:
    """:func:`solve_entity_bucket` + per-lane convergence trace (padding
    lanes masked invalid). The fused mesh path keeps using the untraced
    variant; the CD path returns the trace to telemetry."""
    offsets = _bucket_offsets(sample_rows, full_offsets)
    solved, trace = _solve_bucket_entities(
        objective, opt, features, labels, weights, offsets, table[entity_rows]
    )
    trace = _mask_padding_lanes(trace, entity_rows, table.shape[0])
    return table.at[entity_rows].set(solved), trace


@partial(ledger_jit, label="coord/re_bucket_solve", static_argnums=(0, 1))
def _jitted_re_bucket_solve(
    objective: GLMObjective,
    opt: OptimizerConfig,
    features: Array,
    labels: Array,
    weights: Array,
    sample_rows: Array,
    entity_rows: Array,
    full_offsets: Array,
    table: Array,
):
    return solve_entity_bucket_traced(
        objective, opt, features, labels, weights, sample_rows, entity_rows,
        full_offsets, table,
    )


def _bucket_grad_norms(objective, features, labels, weights, offsets, w0s):
    """[e] gradient norms at each lane's warm start — the vmapped single
    pass behind ``RandomEffectCoordinate.refresh_gradient_norms``."""

    def one(f, l, o, wt, w):
        batch = LabeledPointBatch(features=f, labels=l, offsets=o, weights=wt)
        return jnp.linalg.norm(objective.gradient(w, batch))

    return jax.vmap(one)(features, labels, offsets, weights, w0s)


@partial(ledger_jit, label="refresh/grad_norms", static_argnums=(0,))
def _jitted_re_bucket_grad_norms(
    objective: GLMObjective,
    features: Array,
    labels: Array,
    weights: Array,
    sample_rows: Array,
    entity_rows: Array,
    full_offsets: Array,
    table: Array,
):
    offsets = _bucket_offsets(sample_rows, full_offsets)
    return _bucket_grad_norms(
        objective, features, labels, weights, offsets, table[entity_rows]
    )


@partial(ledger_jit, label="refresh/grad_norms_indexmap", static_argnums=(0,))
def _jitted_re_bucket_grad_norms_indexmap(
    objective: GLMObjective,
    features: Array,
    labels: Array,
    weights: Array,
    sample_rows: Array,
    entity_rows: Array,
    col_index: Array,
    full_offsets: Array,
    table_ext: Array,
):
    offsets = _bucket_offsets(sample_rows, full_offsets)
    w0s = table_ext[entity_rows[:, None], col_index]
    return _bucket_grad_norms(
        objective, features, labels, weights, offsets, w0s
    )


@partial(ledger_jit, label="refresh/grad_norms_random", static_argnums=(0,))
def _jitted_re_bucket_grad_norms_random(
    objective: GLMObjective,
    features: Array,
    labels: Array,
    weights: Array,
    sample_rows: Array,
    entity_rows: Array,
    matrix: Array,
    full_offsets: Array,
    table: Array,
):
    offsets = _bucket_offsets(sample_rows, full_offsets)
    return _bucket_grad_norms(
        objective, features, labels, weights, offsets,
        table[entity_rows] @ matrix,
    )


@partial(ledger_jit, label="coord/re_bucket_variances", static_argnums=(0,))
def _jitted_re_bucket_variances(
    objective: GLMObjective,
    features: Array,  # [e, cap, d]
    labels: Array,
    weights: Array,
    sample_rows: Array,
    entity_rows: Array,
    full_offsets: Array,
    table: Array,  # [E, d] solved coefficients (normalized space)
    var_table: Array,  # [E, d] accumulator
):
    """Per-entity diag(H⁻¹) at the solved coefficients, scattered into
    var_table with the same index semantics as solve_entity_bucket."""
    offsets = _bucket_offsets(sample_rows, full_offsets)

    def one(f, l, o, wt, w):
        batch = LabeledPointBatch(features=f, labels=l, offsets=o, weights=wt)
        return diag_inverse_from_hessian(objective.hessian_matrix(w, batch))

    vs = jax.vmap(one)(features, labels, offsets, weights, table[entity_rows])
    return var_table.at[entity_rows].set(vs)


@partial(ledger_jit, label="coord/re_bucket_variances_diagonal", static_argnums=(0,))
def _jitted_re_bucket_variances_diagonal(
    objective: GLMObjective,
    features: Array,
    labels: Array,
    weights: Array,
    sample_rows: Array,
    entity_rows: Array,
    full_offsets: Array,
    table: Array,
    var_table: Array,
):
    """Diagonal-approximation twin of :func:`_jitted_re_bucket_variances` —
    1/diag(H) per entity without materializing the [e, d, d] Hessian stack."""
    offsets = _bucket_offsets(sample_rows, full_offsets)

    def one(f, l, o, wt, w):
        batch = LabeledPointBatch(features=f, labels=l, offsets=o, weights=wt)
        return inverse_of_diagonal(objective.hessian_diagonal(w, batch))

    vs = jax.vmap(one)(features, labels, offsets, weights, table[entity_rows])
    return var_table.at[entity_rows].set(vs)


@partial(ledger_jit, label="coord/re_bucket_variances_indexmap", static_argnums=(0,))
def _jitted_re_bucket_variances_indexmap(
    objective: GLMObjective,
    features: Array,  # [e, cap, k] index-projected (possibly pre-normalized)
    labels: Array,
    weights: Array,
    sample_rows: Array,
    entity_rows: Array,
    col_index: Array,  # [e, k], pad slots hold the scratch column
    full_offsets: Array,
    table_ext: Array,  # [E, d+1] solved coefficients + scratch
    var_ext: Array,  # [E, d+1] accumulator (NaN = not computed)
):
    """Per-entity diag(H⁻¹) in the PROJECTED space (H over the entity's
    active columns only), scattered back through the entity's index map —
    variances travel with the means exactly as in the reference
    (IndexMapProjectorRDD.scala:103)."""
    offsets = _bucket_offsets(sample_rows, full_offsets)
    w0s = table_ext[entity_rows[:, None], col_index]

    def one(f, l, o, wt, w):
        batch = LabeledPointBatch(features=f, labels=l, offsets=o, weights=wt)
        return diag_inverse_from_hessian(objective.hessian_matrix(w, batch))

    vs = jax.vmap(one)(features, labels, offsets, weights, w0s)
    return var_ext.at[entity_rows[:, None], col_index].set(vs)


@partial(ledger_jit, label="coord/re_bucket_variances_indexmap_diagonal", static_argnums=(0,))
def _jitted_re_bucket_variances_indexmap_diagonal(
    objective: GLMObjective,
    features: Array,
    labels: Array,
    weights: Array,
    sample_rows: Array,
    entity_rows: Array,
    col_index: Array,
    full_offsets: Array,
    table_ext: Array,
    var_ext: Array,
):
    """Diagonal-approximation twin of
    :func:`_jitted_re_bucket_variances_indexmap`."""
    offsets = _bucket_offsets(sample_rows, full_offsets)
    w0s = table_ext[entity_rows[:, None], col_index]

    def one(f, l, o, wt, w):
        batch = LabeledPointBatch(features=f, labels=l, offsets=o, weights=wt)
        return inverse_of_diagonal(objective.hessian_diagonal(w, batch))

    vs = jax.vmap(one)(features, labels, offsets, weights, w0s)
    return var_ext.at[entity_rows[:, None], col_index].set(vs)


def solve_entity_bucket_indexmap(
    objective: GLMObjective,
    opt: OptimizerConfig,
    features: Array,  # [e, cap, k]
    labels: Array,
    weights: Array,
    sample_rows: Array,
    entity_rows: Array,
    col_index: Array,  # [e, k], padding slots hold d (the scratch column)
    full_offsets: Array,
    table_ext: Array,  # [E, d+1]
) -> Array:
    """Index-map-projected bucket solve: gather each entity's active columns
    as its warm start, solve in the projected space, scatter back. Padding
    slots read/write the scratch column, which is re-zeroed afterwards.

    Pure/traceable (reference IndexMapProjectorRDD.scala:218-257 semantics):
    used by the single-chip jit wrapper below and by the mesh-sharded
    fused step (parallel/distributed.py), where the entity axis shards
    over "data"."""
    table_ext, _trace = solve_entity_bucket_indexmap_traced(
        objective, opt, features, labels, weights, sample_rows, entity_rows,
        col_index, full_offsets, table_ext,
    )
    return table_ext


def solve_entity_bucket_indexmap_traced(
    objective: GLMObjective,
    opt: OptimizerConfig,
    features: Array,
    labels: Array,
    weights: Array,
    sample_rows: Array,
    entity_rows: Array,
    col_index: Array,
    full_offsets: Array,
    table_ext: Array,
) -> tuple[Array, LaneTrace]:
    """:func:`solve_entity_bucket_indexmap` + per-lane convergence trace."""
    offsets = _bucket_offsets(sample_rows, full_offsets)
    w0s = table_ext[entity_rows[:, None], col_index]
    solved, trace = _solve_bucket_entities(
        objective, opt, features, labels, weights, offsets, w0s
    )
    trace = _mask_padding_lanes(trace, entity_rows, table_ext.shape[0])
    table_ext = table_ext.at[entity_rows[:, None], col_index].set(solved)
    return table_ext.at[:, -1].set(0.0), trace


@partial(ledger_jit, label="coord/re_bucket_variances_random", static_argnums=(0,))
def _jitted_re_bucket_variances_random(
    objective: GLMObjective,
    features: Array,  # [e, cap, k] (already projected)
    labels: Array,
    weights: Array,
    sample_rows: Array,
    entity_rows: Array,
    matrix: Array,  # [d, k]
    full_offsets: Array,
    table: Array,  # [E, d] solved ORIGINAL-space coefficients
    var_table: Array,  # [E, d] accumulator (NaN = not computed)
):
    """Original-space variances of a RANDOM-projected solve: the estimator
    is w = P w_k, so Cov(w) = P Cov(w_k) Pᵀ and
    var(w) = diag(P H_k⁻¹ Pᵀ) = rowsum((P @ H_k⁻¹) ∘ P).

    This is an IMPROVEMENT over the reference, which back-projects the
    means but passes the PROJECTED-space variance vector through unchanged
    (ProjectionMatrixBroadcast.scala:76) — a length-k vector attached to a
    length-d model. Standalone entry points reject that; this kernel does
    the propagation properly."""
    offsets = _bucket_offsets(sample_rows, full_offsets)
    wks = _recover_sketch_coefficients(table[entity_rows], matrix)

    def one(f, l, o, wt, wk):
        batch = LabeledPointBatch(features=f, labels=l, offsets=o, weights=wt)
        h_inv = full_inverse_from_hessian(objective.hessian_matrix(wk, batch))
        return jnp.einsum("dk,kl,dl->d", matrix, h_inv, matrix)

    vs = jax.vmap(one)(features, labels, offsets, weights, wks)
    return var_table.at[entity_rows].set(vs)


def random_variance_mode(mode: str, d: int, k: int, num_problems: int) -> str:
    """AUTO gate for the RANDOM-projection variance kernels: the full
    propagation materializes a [d, k] (P @ H_k⁻¹) intermediate PER VMAPPED
    ENTITY — num_problems·d·k floats, unbounded in d (the axis the sketch
    exists to shrink) — so the budget must cover that stack, not just the
    e·k² Hessians."""
    resolved = resolve_variance_mode(mode, k, num_problems=num_problems)
    if (
        mode == "auto"
        and resolved == "full"
        and num_problems * d * k > FULL_VARIANCE_MAX_DIM * FULL_VARIANCE_MAX_DIM
    ):
        return "diagonal"
    return resolved


def _recover_sketch_coefficients(rows: Array, matrix: Array) -> Array:
    """EXACT solve-space coefficients from back-projected table rows.

    Table rows hold w = P w_k exactly (set by ``solved @ P.T``), so
    w_k = (PᵀP)⁻¹ Pᵀ w — a shared [k, k] Gram solve. The cheaper adjoint
    Pᵀw = (PᵀP) w_k is fine as a solver WARM START but deviates from w_k by
    ~sqrt(k/d) relative error, which would bias any coefficient-dependent
    Hessian (logistic/Poisson) evaluated there.
    """
    gram = matrix.T @ matrix  # [k, k]
    return jnp.linalg.solve(gram, (rows @ matrix).T).T


@partial(ledger_jit, label="coord/re_bucket_variances_random_diagonal", static_argnums=(0,))
def _jitted_re_bucket_variances_random_diagonal(
    objective: GLMObjective,
    features: Array,
    labels: Array,
    weights: Array,
    sample_rows: Array,
    entity_rows: Array,
    matrix: Array,
    full_offsets: Array,
    table: Array,
    var_table: Array,
):
    """Diagonal-approximation twin: var(w) ≈ (P∘P) @ 1/diag(H_k)."""
    offsets = _bucket_offsets(sample_rows, full_offsets)
    wks = _recover_sketch_coefficients(table[entity_rows], matrix)
    p2 = matrix * matrix

    def one(f, l, o, wt, wk):
        batch = LabeledPointBatch(features=f, labels=l, offsets=o, weights=wt)
        return p2 @ inverse_of_diagonal(objective.hessian_diagonal(wk, batch))

    vs = jax.vmap(one)(features, labels, offsets, weights, wks)
    return var_table.at[entity_rows].set(vs)


def solve_entity_bucket_random(
    objective: GLMObjective,
    opt: OptimizerConfig,
    features: Array,  # [e, cap, k] (already projected)
    labels: Array,
    weights: Array,
    sample_rows: Array,
    entity_rows: Array,
    matrix: Array,  # [d, k]
    full_offsets: Array,
    table: Array,  # [E, d]
) -> Array:
    """Random-projected bucket solve: warm start Pᵀw (the adjoint projection,
    ≈ the projected coefficients since E[PᵀP]=I), back-project P w_k.
    Pure/traceable, shared with the fused step like its index-map twin."""
    table, _trace = solve_entity_bucket_random_traced(
        objective, opt, features, labels, weights, sample_rows, entity_rows,
        matrix, full_offsets, table,
    )
    return table


def solve_entity_bucket_random_traced(
    objective: GLMObjective,
    opt: OptimizerConfig,
    features: Array,
    labels: Array,
    weights: Array,
    sample_rows: Array,
    entity_rows: Array,
    matrix: Array,
    full_offsets: Array,
    table: Array,
) -> tuple[Array, LaneTrace]:
    """:func:`solve_entity_bucket_random` + per-lane convergence trace."""
    offsets = _bucket_offsets(sample_rows, full_offsets)
    w0s = table[entity_rows] @ matrix
    solved, trace = _solve_bucket_entities(
        objective, opt, features, labels, weights, offsets, w0s
    )
    trace = _mask_padding_lanes(trace, entity_rows, table.shape[0])
    return table.at[entity_rows].set(solved @ matrix.T), trace


@partial(ledger_jit, label="coord/re_bucket_solve_indexmap", static_argnums=(0, 1))
def _jitted_re_bucket_solve_indexmap(
    objective: GLMObjective,
    opt: OptimizerConfig,
    features: Array,
    labels: Array,
    weights: Array,
    sample_rows: Array,
    entity_rows: Array,
    col_index: Array,
    full_offsets: Array,
    table_ext: Array,
):
    return solve_entity_bucket_indexmap_traced(
        objective, opt, features, labels, weights, sample_rows, entity_rows,
        col_index, full_offsets, table_ext,
    )


@partial(ledger_jit, label="coord/re_bucket_solve_random", static_argnums=(0, 1))
def _jitted_re_bucket_solve_random(
    objective: GLMObjective,
    opt: OptimizerConfig,
    features: Array,
    labels: Array,
    weights: Array,
    sample_rows: Array,
    entity_rows: Array,
    matrix: Array,
    full_offsets: Array,
    table: Array,
):
    return solve_entity_bucket_random_traced(
        objective, opt, features, labels, weights, sample_rows, entity_rows,
        matrix, full_offsets, table,
    )


@dataclasses.dataclass
class ModelCoordinate(Coordinate):
    """A locked coordinate: contributes scores, never retrains (reference
    FixedEffectModelCoordinate / RandomEffectModelCoordinate, used by partial
    retraining, CoordinateDescent.scala:44-49)."""

    coordinate_id: str
    dataset: GameDataset
    model: DatumScoringModel

    def initial_model(self) -> DatumScoringModel:
        return self.model

    def update_model(self, model: DatumScoringModel, extra_offsets: Array | None = None):
        return model, None

    def score(self, model: DatumScoringModel) -> Array:
        return model.score_dataset(self.dataset)
