"""Block coordinate descent over GAME coordinates with residual offsets.

Reference parity: photon-lib algorithm/CoordinateDescent.scala — the GAME
training loop. Per (iteration, coordinate): compute the partial score
(full training score minus this coordinate's own score), re-offset the
coordinate's dataset, retrain, refresh the full score
(CoordinateDescent.scala:198-255); track the best model by the first
validation evaluator over full update sequences (:183-192, :323-356); locked
coordinates never retrain (partial retraining, :44-49).

TPU-native: scores are [n] device arrays; the residual update is one
elementwise subtract (replacing the reference's DataScores RDD ± algebra and
its persist/unpersist choreography — device memory management is XLA's job).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Mapping, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.coordinates import Coordinate
from photon_ml_tpu.evaluation.evaluators import EvaluationData, Evaluator
from photon_ml_tpu.models.game import DatumScoringModel, GameModel

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class CoordinateDescentResult:
    model: GameModel
    best_model: GameModel
    best_metric: float
    metric_history: list[dict[str, float]]


def run_coordinate_descent(
    coordinates: Mapping[str, Coordinate],
    update_sequence: Sequence[str],
    num_iterations: int,
    *,
    initial_models: Mapping[str, DatumScoringModel] | None = None,
    locked_coordinates: frozenset[str] | set[str] = frozenset(),
    training_evaluator: Evaluator | None = None,
    training_data: EvaluationData | None = None,
    validation_evaluators: Sequence[Evaluator] = (),
    validation_scorer=None,
    validation_data: EvaluationData | None = None,
    checkpointer=None,
    checkpoint_every: int = 1,
    resume: bool = True,
    check_finite: bool = True,
    telemetry=None,
) -> CoordinateDescentResult:
    """Run block coordinate descent.

    validation_scorer: callable(GameModel) -> np.ndarray of validation scores
    (the transformer path); the FIRST validation evaluator selects the best
    model across update sequences, as in the reference (:183-192).

    checkpointer: optional ``io.checkpoint.TrainingCheckpointer``. When set,
    full CD progress (current models, best model, metric history) is saved
    every ``checkpoint_every`` coordinate updates (and at the final update);
    with ``resume=True`` a later call restores the latest checkpoint and
    fast-forwards past completed updates. This is a capability the reference
    lacks (SURVEY.md §5 — Spark lineage only).

    check_finite: raise ``io.checkpoint.DivergenceError`` the moment a
    coordinate update produces non-finite scores, instead of training on.

    telemetry: optional ``telemetry.SolverTelemetry``. Every coordinate
    update reports its solver info — a SolverResult (fixed effect) or
    per-entity LaneTraces (vmapped random-effect buckets) — as journal
    convergence rows / OptimizationLogEvents keyed by (coordinate,
    outer iteration), the parity hook for the reference's per-coordinate
    OptimizationStatesTracker reporting (CoordinateDescent.scala:198-255).
    """
    from photon_ml_tpu.io.checkpoint import (
        DivergenceError,
        commit_checkpoint,
        pack_cd_state,
        unpack_cd_state,
    )

    models: dict[str, DatumScoringModel] = {}
    scores: dict[str, jnp.ndarray] = {}

    best_model: GameModel | None = None
    best_metric = float("nan")
    history: list[dict[str, float]] = []
    start_slot = 0  # global update counter: iteration * len(seq) + position

    restored = None
    if checkpointer is not None and resume:
        ckpt = checkpointer.restore()
        if ckpt is not None:
            saved_order = ckpt.meta.get("model", {}).get("order")
            # exact ordered match: the fast-forward below maps the checkpoint
            # step onto (iteration, position) slots of THIS sequence, so a
            # reordering would skip the wrong coordinates
            if saved_order is not None and list(saved_order) != list(update_sequence):
                raise ValueError(
                    "checkpoint is incompatible with this run: it holds "
                    f"coordinates {saved_order} but the update sequence is "
                    f"{list(update_sequence)}; pass resume=False or a fresh "
                    "checkpoint directory"
                )
            restored_model, best_model, best_metric, history = unpack_cd_state(ckpt)
            restored = restored_model.models
            start_slot = int(ckpt.step)
            # journaled restore evidence (resilience/checkpoint_restores):
            # both user-driven resume and driver-level crash recovery
            # (resilience/recovery.py) pass through here
            from photon_ml_tpu.telemetry import resilience_counters

            resilience_counters.record_checkpoint_restore()
            logger.info(
                "Resuming coordinate descent from checkpoint step %d", start_slot
            )

    for cid in update_sequence:
        coord = coordinates[cid]
        if restored is not None and cid in restored:
            models[cid] = restored[cid]
        elif initial_models and cid in initial_models:
            models[cid] = initial_models[cid]
        else:
            models[cid] = coord.initial_model()
        scores[cid] = coord.score(models[cid])

    def full_score():
        it = iter(scores.values())
        total = next(it).copy()
        for s in it:
            total = total + s
        return total

    n_seq = len(update_sequence)
    # the final slot that actually performs an update (locked coordinates
    # never reach the save site) — the guaranteed-checkpoint point
    unlocked = [i for i, c in enumerate(update_sequence) if c not in locked_coordinates]
    final_update_slot = (
        (num_iterations - 1) * n_seq + max(unlocked) if unlocked else -1
    )
    for iteration in range(num_iterations):
        for position, cid in enumerate(update_sequence):
            slot = iteration * n_seq + position
            coord = coordinates[cid]
            if cid in locked_coordinates:
                continue
            if slot < start_slot:
                continue  # already completed before the restored checkpoint
            if hasattr(coord, "set_sweep"):
                # cross-sweep active sets (algorithm/lane_scheduler.py):
                # a lane-scheduled random-effect coordinate may freeze
                # converged entities and skip them in later sweeps' solves
                # (they are still rescored below) — it needs to know the
                # final sweep, which always runs everyone
                coord.set_sweep(iteration, num_iterations)
            # partial score = everything except this coordinate
            partial = full_score() - scores[cid]
            model, _info = coord.update_model(models[cid], partial)
            models[cid] = model
            scores[cid] = coord.score(model)
            finite = True
            if check_finite:
                # reduce on device: only a scalar crosses to the host
                finite = bool(jnp.isfinite(jnp.asarray(scores[cid])).all())
                if finite and _info is not None and hasattr(_info, "value"):
                    # a failed solve can leave finite warm-start coefficients
                    # but a non-finite objective (e.g. NaN labels) — catch
                    # too. Scalar solver results only: vmapped RE lane
                    # traces (LaneTraces) are telemetry-only and rely on
                    # the device-side score check above, as before.
                    finite = bool(np.isfinite(float(_info.value)))
            if not finite:
                raise DivergenceError(
                    f"coordinate '{cid}' produced non-finite scores at CD "
                    f"iteration {iteration}"
                    + (
                        f"; last good checkpoint: step {checkpointer.latest_step()}"
                        f" in {checkpointer.directory}"
                        if checkpointer is not None
                        else ""
                    )
                )

            metrics: dict[str, float] = {}
            if training_evaluator is not None and training_data is not None:
                # Scores must include the base offsets: the optimizer minimizes
                # the loss of margins *with* offsets (warm-start residuals).
                total = np.asarray(full_score()) + training_data.offsets
                metrics[f"train:{training_evaluator.name}"] = training_evaluator.evaluate(
                    total, training_data
                )

            game_model = GameModel(models=dict(models))
            if validation_evaluators and validation_scorer is not None and validation_data is not None:
                val_scores = np.asarray(validation_scorer(game_model))
                for i, ev in enumerate(validation_evaluators):
                    v = ev.evaluate(val_scores, validation_data)
                    metrics[f"validate:{ev.name}"] = v
                    if i == 0 and (best_model is None or ev.better_than(v, best_metric)):
                        best_model, best_metric = game_model, v
            if metrics:
                logger.info("CD iter %d coord %s: %s", iteration, cid, metrics)
                history.append({"iteration": iteration, "coordinate": cid, **metrics})
            if telemetry is not None:
                telemetry.record_coordinate(
                    cid, iteration, _info, metrics=metrics or None
                )

            if checkpointer is not None and (
                (slot + 1) % max(1, checkpoint_every) == 0
                or slot == final_update_slot
            ):
                arrays, meta = pack_cd_state(
                    GameModel(models=dict(models)), best_model, best_metric, history
                )
                # the ONE gated write site (lint check 10); the host-loop
                # CD path is single-process, so the gate is a pass-through
                commit_checkpoint(checkpointer, slot + 1, arrays, meta)

        if telemetry is not None:
            # liveness heartbeat (ISSUE 12): sweep cursor + registry deltas
            # into the crash-durable journal stage; observes only
            telemetry.heartbeat(
                "game_cd", sweep=iteration + 1, num_sweeps=num_iterations
            )

    final = GameModel(models=dict(models))
    if best_model is None:
        best_model = final
    return CoordinateDescentResult(
        model=final,
        best_model=best_model,
        best_metric=best_metric,
        metric_history=history,
    )
