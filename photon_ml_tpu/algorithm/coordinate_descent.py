"""Block coordinate descent over GAME coordinates with residual offsets.

Reference parity: photon-lib algorithm/CoordinateDescent.scala — the GAME
training loop. Per (iteration, coordinate): compute the partial score
(full training score minus this coordinate's own score), re-offset the
coordinate's dataset, retrain, refresh the full score
(CoordinateDescent.scala:198-255); track the best model by the first
validation evaluator over full update sequences (:183-192, :323-356); locked
coordinates never retrain (partial retraining, :44-49).

TPU-native: scores are [n] device arrays; the residual update is one
elementwise subtract (replacing the reference's DataScores RDD ± algebra and
its persist/unpersist choreography — device memory management is XLA's job).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Mapping, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.coordinates import Coordinate
from photon_ml_tpu.evaluation.evaluators import EvaluationData, Evaluator
from photon_ml_tpu.models.game import DatumScoringModel, GameModel

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class CoordinateDescentResult:
    model: GameModel
    best_model: GameModel
    best_metric: float
    metric_history: list[dict[str, float]]


def run_coordinate_descent(
    coordinates: Mapping[str, Coordinate],
    update_sequence: Sequence[str],
    num_iterations: int,
    *,
    initial_models: Mapping[str, DatumScoringModel] | None = None,
    locked_coordinates: frozenset[str] | set[str] = frozenset(),
    training_evaluator: Evaluator | None = None,
    training_data: EvaluationData | None = None,
    validation_evaluators: Sequence[Evaluator] = (),
    validation_scorer=None,
    validation_data: EvaluationData | None = None,
) -> CoordinateDescentResult:
    """Run block coordinate descent.

    validation_scorer: callable(GameModel) -> np.ndarray of validation scores
    (the transformer path); the FIRST validation evaluator selects the best
    model across update sequences, as in the reference (:183-192).
    """
    models: dict[str, DatumScoringModel] = {}
    scores: dict[str, jnp.ndarray] = {}
    for cid in update_sequence:
        coord = coordinates[cid]
        if initial_models and cid in initial_models:
            models[cid] = initial_models[cid]
        else:
            models[cid] = coord.initial_model()
        scores[cid] = coord.score(models[cid])

    def full_score():
        it = iter(scores.values())
        total = next(it).copy()
        for s in it:
            total = total + s
        return total

    best_model: GameModel | None = None
    best_metric = float("nan")
    history: list[dict[str, float]] = []

    for iteration in range(num_iterations):
        for cid in update_sequence:
            coord = coordinates[cid]
            if cid in locked_coordinates:
                continue
            # partial score = everything except this coordinate
            partial = full_score() - scores[cid]
            model, _info = coord.update_model(models[cid], partial)
            models[cid] = model
            scores[cid] = coord.score(model)

            metrics: dict[str, float] = {}
            if training_evaluator is not None and training_data is not None:
                # Scores must include the base offsets: the optimizer minimizes
                # the loss of margins *with* offsets (warm-start residuals).
                total = np.asarray(full_score()) + training_data.offsets
                metrics[f"train:{training_evaluator.name}"] = training_evaluator.evaluate(
                    total, training_data
                )

            game_model = GameModel(models=dict(models))
            if validation_evaluators and validation_scorer is not None and validation_data is not None:
                val_scores = np.asarray(validation_scorer(game_model))
                for i, ev in enumerate(validation_evaluators):
                    v = ev.evaluate(val_scores, validation_data)
                    metrics[f"validate:{ev.name}"] = v
                    if i == 0 and (best_model is None or ev.better_than(v, best_metric)):
                        best_model, best_metric = game_model, v
            if metrics:
                logger.info("CD iter %d coord %s: %s", iteration, cid, metrics)
                history.append({"iteration": iteration, "coordinate": cid, **metrics})

    final = GameModel(models=dict(models))
    if best_model is None:
        best_model = final
    return CoordinateDescentResult(
        model=final,
        best_model=best_model,
        best_metric=best_metric,
        metric_history=history,
    )
