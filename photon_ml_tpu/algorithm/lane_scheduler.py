"""Converged-lane scheduling for vmapped random-effect solves.

Reference parity: photon-api algorithm/RandomEffectCoordinate.scala:104-153
— the reference's per-entity local solves are INDEPENDENT Spark tasks, so
each entity pays only its own iteration count and stragglers are scheduled
around by the task scheduler. The TPU port vmaps those solves, which makes
every lane advance in lock-step to the WORST lane: with the 1e-7 relative
tolerances that never fire in f32 for warm-started small solves, every lane
pays ``max_iter`` (the ~87% RE-solve share of the fused GAME sweep,
BASELINE.md r5 decomposition). This module restores the reference's
work-follows-convergence property without giving up the vmap:

1. **Probe** — run every bucket's vmapped solve for a short probe budget
   (``LaneSchedulerConfig.probe_iterations``) and read each lane's
   convergence reason from the existing ``LaneTrace`` scalars (tiny
   device-to-host reads).
2. **Rescue** — host-compact the lanes still at MAX_ITERATIONS across
   same-(capacity, feature-width) buckets (vectorized numpy,
   ``data.game_data.compact_lane_blocks``) into power-of-two-padded rescue
   blocks — bounded jit signatures, cached across sweeps — and re-run them
   with the remaining ``max_iterations - probe_iterations`` budget, warm-
   started from their probe rows; results scatter back into the [E, d]
   coefficient table inside the same jit.
3. **Cross-sweep active sets** (opt-in via the freeze tolerances) — entities
   whose per-sweep coefficient delta and final gradient norm fall below
   threshold are frozen: skipped by later sweeps' solves (still rescored by
   the coordinate's scoring path); the final sweep always runs everyone.

The scheduling literature motivates both moves: Snap ML (arxiv 1803.06333)
derives its hierarchy wins from matching work to the per-subproblem
convergence distribution, and distributed coordinate descent (arxiv
1611.02101) observes most coordinates converge within a handful of inner
iterations after the first outer pass.

Strictly opt-in: ``OptimizerConfig.scheduler=None`` keeps the unscheduled
single-jit path bitwise-identical (tests/test_lane_scheduler.py pins it).
Scheduled solves trade the one-jit sweep for a few extra dispatches and
small host reads per bucket — worth it exactly when the saved lane
iterations dwarf the ~100 ms tunnel dispatch (compare the same-run
``fused_game_sweep_scheduled_ms`` vs ``fused_game_sweep_ms`` bench rows,
never cross-run absolutes).

use_pallas MUST stay False in every objective this module receives — the
solves are vmapped, and a baked-in pallas_call would batch into a serial
per-lane loop (dev/lint_parity.py check 6 enforces this statically).
"""

from __future__ import annotations

import dataclasses
import logging
from functools import partial
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.coordinates import (
    _bucket_offsets,
    _mask_padding_lanes,
    _solve_bucket_entities,
)
from photon_ml_tpu.data.game_data import compact_lane_blocks
from photon_ml_tpu.optim.common import ConvergenceReason, LaneTrace
from photon_ml_tpu.optim.optimizer import LaneSchedulerConfig, OptimizerConfig
from photon_ml_tpu.projector.projectors import ProjectorType

Array = jax.Array

logger = logging.getLogger(__name__)

#: entity_rows value for compacted padding lanes: out of range for any
#: coefficient table (the mesh-padding convention of shard_inputs), so
#: gathers clamp and scatters drop
SENTINEL_ROW = np.iinfo(np.int32).max

#: rescue blocks are padded to at least this many lanes, bounding the
#: number of distinct jit signatures at log2(E) per (cap, d) group
MIN_RESCUE_LANES = 8

#: registry namespace of the scheduler counters (reset per driver run next
#: to solver/*; journaled via the drivers' registry snapshot on success AND
#: failure paths)
SCHEDULER_METRIC_PREFIX = "scheduler/"


def _pow2_lanes(m: int) -> int:
    return 1 << (max(m, MIN_RESCUE_LANES) - 1).bit_length()


@dataclasses.dataclass
class SchedulerStats:
    """Per-sweep scheduling outcome of one coordinate's bucket set."""

    lanes_total: int = 0  # valid (non-padding) lanes across all buckets
    lanes_probed: int = 0  # lanes actually solved this sweep
    lanes_rescued: int = 0  # probed lanes re-run with the remaining budget
    lanes_frozen_skipped: int = 0  # lanes skipped by the active set
    lanes_newly_frozen: int = 0
    rescue_blocks: int = 0

    def merge(self, other: "SchedulerStats") -> None:
        for f in dataclasses.fields(self):
            setattr(self, f.name, getattr(self, f.name) + getattr(other, f.name))


# -- jitted block solvers ----------------------------------------------------
# One per projector, mirroring algorithm/coordinates.py's *_traced solvers
# with two tiny extra outputs per lane (coefficient delta and norm — the
# active-set freeze inputs). (objective, opt) are static; shapes key the jit
# cache, so power-of-two rescue padding bounds compilation.


@partial(jax.jit, static_argnums=(0, 1))
def _block_solve_identity(
    objective, opt: OptimizerConfig,
    features: Array, labels: Array, weights: Array,
    sample_rows: Array, entity_rows: Array,
    full_offsets: Array, table: Array,
):
    offsets = _bucket_offsets(sample_rows, full_offsets)
    w0s = table[entity_rows]  # OOB sentinel lanes clamp to the last row
    solved, trace = _solve_bucket_entities(
        objective, opt, features, labels, weights, offsets, w0s
    )
    trace = _mask_padding_lanes(trace, entity_rows, table.shape[0])
    delta = jnp.linalg.norm(solved - w0s, axis=-1)
    wnorm = jnp.linalg.norm(solved, axis=-1)
    return table.at[entity_rows].set(solved), trace, delta, wnorm


@partial(jax.jit, static_argnums=(0, 1))
def _block_solve_indexmap(
    objective, opt: OptimizerConfig,
    features: Array, labels: Array, weights: Array,
    sample_rows: Array, entity_rows: Array, col_index: Array,
    full_offsets: Array, table_ext: Array,
):
    offsets = _bucket_offsets(sample_rows, full_offsets)
    w0s = table_ext[entity_rows[:, None], col_index]
    solved, trace = _solve_bucket_entities(
        objective, opt, features, labels, weights, offsets, w0s
    )
    trace = _mask_padding_lanes(trace, entity_rows, table_ext.shape[0])
    delta = jnp.linalg.norm(solved - w0s, axis=-1)
    wnorm = jnp.linalg.norm(solved, axis=-1)
    table_ext = table_ext.at[entity_rows[:, None], col_index].set(solved)
    return table_ext.at[:, -1].set(0.0), trace, delta, wnorm


@partial(jax.jit, static_argnums=(0, 1))
def _block_solve_random(
    objective, opt: OptimizerConfig,
    features: Array, labels: Array, weights: Array,
    sample_rows: Array, entity_rows: Array, matrix: Array,
    full_offsets: Array, table: Array,
):
    offsets = _bucket_offsets(sample_rows, full_offsets)
    w0s = table[entity_rows] @ matrix
    solved, trace = _solve_bucket_entities(
        objective, opt, features, labels, weights, offsets, w0s
    )
    trace = _mask_padding_lanes(trace, entity_rows, table.shape[0])
    delta = jnp.linalg.norm(solved - w0s, axis=-1)
    wnorm = jnp.linalg.norm(solved, axis=-1)
    return table.at[entity_rows].set(solved @ matrix.T), trace, delta, wnorm


@jax.jit
def _extend_scratch(table: Array) -> Array:
    """[E, d] -> [E, d+1]: the INDEX_MAP scratch column that absorbs padding
    gather/scatter slots (algorithm/coordinates.py convention)."""
    return jnp.concatenate(
        [table, jnp.zeros((table.shape[0], 1), table.dtype)], axis=1
    )


@jax.jit
def _strip_scratch(table_ext: Array) -> Array:
    return table_ext[:, :-1]


class LaneScheduler:
    """Per-coordinate probe/rescue state, persisted across sweeps.

    Holds the host copies of the bucket structure (read once — buckets are
    immutable across sweeps; only the table and offsets change), the frozen
    active-set mask, and the carried per-lane (value, gradient-norm) scalars
    that frozen lanes report to telemetry. Create one per random-effect
    coordinate and reuse it for every sweep; a fresh instance per call works
    but re-reads the bucket arrays to the host each time.
    """

    def __init__(self, config: LaneSchedulerConfig, registry=None):
        self.config = config
        self._registry = registry
        self._host_blocks: list[dict[str, np.ndarray]] | None = None
        #: bool [table rows]; grows monotonically until the final sweep
        self.frozen_rows: np.ndarray | None = None
        #: per-block (value, gradient_norm) carried for lanes a later sweep
        #: skips (frozen lanes still appear in lane traces, with iterations 0)
        self._carry: list[tuple[np.ndarray, np.ndarray]] | None = None
        self.total_stats = SchedulerStats()
        self.last_stats: SchedulerStats | None = None
        self._warned_no_live_stop = False
        self._num_rows: int | None = None

    def registry(self):
        if self._registry is None:
            from photon_ml_tpu.telemetry.registry import default_registry

            self._registry = default_registry()
        return self._registry

    def _host_cache(self, blocks: Sequence[Mapping[str, Array]]):
        if self._host_blocks is None:
            # one device-to-host read per field per bucket, amortized over
            # every later sweep (single-process only: a multi-process
            # sharded bucket is not addressable — callers gate on that)
            self._host_blocks = [
                {k: np.asarray(v) for k, v in b.items()} for b in blocks
            ]
        if len(self._host_blocks) != len(blocks):
            raise ValueError(
                "LaneScheduler is per-coordinate state: it was built over "
                f"{len(self._host_blocks)} buckets but is now asked to "
                f"schedule {len(blocks)} — create one scheduler per "
                "random-effect coordinate"
            )
        return self._host_blocks

    # -- the scheduled solve -------------------------------------------------

    def solve(
        self,
        objective,
        opt: OptimizerConfig,
        blocks: Sequence[Mapping[str, Array]],
        full_offsets: Array,
        table: Array,
        *,
        projector: ProjectorType = ProjectorType.IDENTITY,
        matrix: Array | None = None,
        final_sweep: bool = True,
    ) -> tuple[Array, list[LaneTrace], SchedulerStats]:
        """Probe + rescue (+ active-set skip) over one coordinate's buckets.

        blocks: bucket field dicts (features/labels/weights/sample_rows/
            entity_rows[/col_index]) — the shapes the unscheduled solvers
            consume. ``table`` is the RAW [E, d] coefficient table for every
            projector (the INDEX_MAP scratch column is handled internally).
        Returns (updated table, per-bucket numpy LaneTraces, stats). A
        frozen (skipped) lane reports iterations=0 with its carried value/
        gradient norm and reason FUNCTION_VALUES_WITHIN_TOLERANCE — the
        freeze criterion is a function-decrease statement.
        """
        cfg = self.config
        stats = SchedulerStats()
        if not blocks:
            self.last_stats = stats
            return table, [], stats
        from photon_ml_tpu.optim.optimizer import OptimizerType

        if (
            opt.rel_function_tolerance is None
            and opt.optimizer_type in (OptimizerType.LBFGS, OptimizerType.OWLQN)
            and not self._warned_no_live_stop
        ):
            # without a live function-decrease stop, warm-started LBFGS/OWLQN
            # lanes rarely flag converged after the probe (the CLAUDE.md
            # tolerance landmine): every lane gets rescued every sweep and
            # the scheduler only ADDS dispatch/compaction cost
            self._warned_no_live_stop = True
            logger.warning(
                "lane scheduler active with optimizer_type=%s but no "
                "rel_function_tolerance: probe convergence flags rarely fire "
                "at the plain tolerance for warm starts, so most lanes will "
                "be rescued anyway — set rel_function_tolerance (e.g. 1e-6) "
                "to get the probe/rescue win",
                opt.optimizer_type.name,
            )

        indexmap = projector == ProjectorType.INDEX_MAP
        if indexmap:
            table = _extend_scratch(table)
        num_rows = int(table.shape[0])
        # per-coordinate contract, checked even on no-compaction sweeps:
        # frozen_rows/_carry sized for another coordinate's table would
        # silently skip the wrong entities instead of raising
        if self._num_rows is None:
            self._num_rows = num_rows
        elif self._num_rows != num_rows:
            raise ValueError(
                "LaneScheduler is per-coordinate state: it was built over a "
                f"{self._num_rows}-row coefficient table but is now asked to "
                f"schedule a {num_rows}-row one — create one scheduler per "
                "random-effect coordinate"
            )

        probe_iters = max(1, min(cfg.probe_iterations, opt.max_iterations))
        rescue_budget = opt.max_iterations - probe_iters
        base_opt = dataclasses.replace(opt, scheduler=None)
        probe_opt = dataclasses.replace(base_opt, max_iterations=probe_iters)
        rescue_opt = (
            dataclasses.replace(base_opt, max_iterations=rescue_budget)
            if rescue_budget > 0 else None
        )

        def run_block(b: Mapping[str, Array], o: OptimizerConfig, tab: Array):
            if indexmap:
                return _block_solve_indexmap(
                    objective, o, b["features"], b["labels"], b["weights"],
                    b["sample_rows"], b["entity_rows"], b["col_index"],
                    full_offsets, tab,
                )
            if projector == ProjectorType.RANDOM:
                return _block_solve_random(
                    objective, o, b["features"], b["labels"], b["weights"],
                    b["sample_rows"], b["entity_rows"], matrix,
                    full_offsets, tab,
                )
            return _block_solve_identity(
                objective, o, b["features"], b["labels"], b["weights"],
                b["sample_rows"], b["entity_rows"], full_offsets, tab,
            )

        freezing = cfg.freezes
        frozen = self.frozen_rows
        if freezing and frozen is None:
            frozen = np.zeros(num_rows, dtype=bool)

        # host lane bookkeeping (entity_rows only — cheap; the full host
        # bucket cache is built lazily, first time compaction is needed)
        rows_h = [np.asarray(b["entity_rows"]).astype(np.int64) for b in blocks]
        valid_h = [(r >= 0) & (r < num_rows) for r in rows_h]
        if freezing and not final_sweep and frozen.any():
            skip_h = [
                v & frozen[np.clip(r, 0, num_rows - 1)]
                for r, v in zip(rows_h, valid_h)
            ]
        else:
            skip_h = [np.zeros(len(r), dtype=bool) for r in rows_h]
        solve_h = [v & ~s for v, s in zip(valid_h, skip_h)]
        stats.lanes_total = int(sum(v.sum() for v in valid_h))
        stats.lanes_frozen_skipped = int(sum(s.sum() for s in skip_h))

        # per-block output arrays; frozen lanes keep carried scalars
        e_sizes = [len(r) for r in rows_h]
        iters_out = [np.zeros(e, np.int64) for e in e_sizes]
        reason_out = [
            np.full(e, int(ConvergenceReason.FUNCTION_VALUES_WITHIN_TOLERANCE),
                    np.int64)
            for e in e_sizes
        ]
        value_out = [np.zeros(e, np.float64) for e in e_sizes]
        gnorm_out = [np.zeros(e, np.float64) for e in e_sizes]
        delta_out = [np.zeros(e, np.float64) for e in e_sizes]
        wnorm_out = [np.zeros(e, np.float64) for e in e_sizes]
        if self._carry is not None:
            for i, (cv, cg) in enumerate(self._carry):
                value_out[i][:] = cv
                gnorm_out[i][:] = cg

        def scatter_back(trace, delta, wnorm, blk, lane):
            """Write one solved block's per-lane scalars back into the
            per-original-bucket output arrays; (blk, lane) name the source
            of each REAL lane (compacted-block padding lanes are beyond
            len(lane) and never land here). Iterations and deltas ADD
            (probe + rescue accumulate); the rest overwrite."""
            it = np.asarray(trace.iterations)
            rs = np.asarray(trace.reason)
            vl = np.asarray(trace.value)
            gn = np.asarray(trace.gradient_norm)
            dl = np.asarray(delta)
            wn = np.asarray(wnorm)
            m = len(lane)
            for i in range(len(blocks)):
                mask = blk[:m] == i
                if not mask.any():
                    continue
                li = lane[:m][mask]
                iters_out[i][li] += it[:m][mask]
                reason_out[i][li] = rs[:m][mask]
                value_out[i][li] = vl[:m][mask]
                gnorm_out[i][li] = gn[:m][mask]
                delta_out[i][li] += dl[:m][mask]
                wnorm_out[i][li] = wn[:m][mask]

        # -- probe phase ----------------------------------------------------
        any_skip = any(s.any() for s in skip_h)
        if not any_skip:
            # full buckets, original shapes — the same signatures the
            # unscheduled path compiles
            for i, b in enumerate(blocks):
                table, trace, delta, wnorm = run_block(b, probe_opt, table)
                blk = np.full(e_sizes[i], i, np.int32)
                lane = np.arange(e_sizes[i], dtype=np.int64)
                real = solve_h[i]
                scatter_back(
                    _np_trace_subset(trace, real), _np_subset(delta, real),
                    _np_subset(wnorm, real), blk[real], lane[real],
                )
            stats.lanes_probed = int(sum(s.sum() for s in solve_h))
        else:
            # active-set compaction: only unfrozen lanes probe
            host = self._host_cache(blocks)
            groups = _group_by_shape(host, solve_h)
            for picks in groups:
                fields, src_blk, src_lane = compact_lane_blocks(
                    host, picks,
                    pad_to=_pow2_lanes(sum(len(l) for _, l in picks)),
                    sentinel_row=SENTINEL_ROW,
                )
                table, trace, delta, wnorm = run_block(
                    _device_block(fields), probe_opt, table
                )
                scatter_back(trace, delta, wnorm, src_blk, src_lane)
                stats.lanes_probed += len(src_lane)

        # -- rescue phase ---------------------------------------------------
        rescue_h = [
            s & (r_out == int(ConvergenceReason.MAX_ITERATIONS))
            for s, r_out in zip(solve_h, reason_out)
        ]
        n_rescue = int(sum(r.sum() for r in rescue_h))
        if rescue_opt is not None and n_rescue:
            host = self._host_cache(blocks)
            groups = _group_by_shape(host, rescue_h)
            for picks in groups:
                fields, src_blk, src_lane = compact_lane_blocks(
                    host, picks,
                    pad_to=_pow2_lanes(sum(len(l) for _, l in picks)),
                    sentinel_row=SENTINEL_ROW,
                )
                table, trace, delta, wnorm = run_block(
                    _device_block(fields), rescue_opt, table
                )
                scatter_back(trace, delta, wnorm, src_blk, src_lane)
                stats.rescue_blocks += 1
            stats.lanes_rescued = n_rescue

        # -- active-set update ----------------------------------------------
        if freezing and not final_sweep:
            ftol = cfg.freeze_coefficient_tolerance
            gtol = cfg.freeze_gradient_tolerance
            for i in range(len(blocks)):
                sel = solve_h[i]
                quiet = (
                    sel
                    & (delta_out[i] <= ftol * (1.0 + wnorm_out[i]))
                    & (gnorm_out[i] <= gtol)
                )
                if quiet.any():
                    frozen[rows_h[i][quiet]] = True
                    stats.lanes_newly_frozen += int(quiet.sum())
            self.frozen_rows = frozen
        if final_sweep:
            # the active set does not outlive its training run
            self.frozen_rows = None

        self._carry = [
            (value_out[i].copy(), gnorm_out[i].copy())
            for i in range(len(blocks))
        ]

        traces = [
            LaneTrace(
                iterations=iters_out[i],
                reason=reason_out[i],
                value=value_out[i],
                gradient_norm=gnorm_out[i],
                valid=valid_h[i],
                # provenance: these lanes are observed into the
                # solver/lane_iters histogram below — telemetry consumers
                # (SolverTelemetry.record_lanes) must not count them again
                scheduled=True,
            )
            for i in range(len(blocks))
        ]
        self._record(stats, traces)
        self.last_stats = stats
        self.total_stats.merge(stats)
        if indexmap:
            table = _strip_scratch(table)
        return table, traces, stats

    def _record(self, stats: SchedulerStats, traces: Sequence[LaneTrace]):
        """Feed the scheduler counters and the solver/lane_iters histogram
        (telemetry/registry.py conventions; journaled by the drivers'
        registry snapshot on success and failure paths)."""
        reg = self.registry()
        p = SCHEDULER_METRIC_PREFIX
        reg.counter(p + "sweeps").inc()
        reg.counter(p + "lanes_probed").inc(stats.lanes_probed)
        reg.counter(p + "lanes_rescued").inc(stats.lanes_rescued)
        reg.counter(p + "lanes_frozen_skipped").inc(stats.lanes_frozen_skipped)
        reg.counter(p + "rescue_blocks").inc(stats.rescue_blocks)
        if self.frozen_rows is not None:
            reg.gauge(p + "frozen_rows").set(int(self.frozen_rows.sum()))
        # the canonical per-lane iteration histogram (record_lanes skips
        # scheduler-produced traces, so lanes land here exactly once)
        from photon_ml_tpu.telemetry.solver_trace import LANE_ITERS_METRIC

        hist = reg.histogram(LANE_ITERS_METRIC)
        for t in traces:
            hist.observe_many(
                np.asarray(t.iterations)[np.asarray(t.valid)].tolist()
            )


def _np_subset(arr, mask: np.ndarray) -> np.ndarray:
    return np.asarray(arr)[mask]


def _np_trace_subset(trace: LaneTrace, mask: np.ndarray) -> LaneTrace:
    return LaneTrace(
        iterations=_np_subset(trace.iterations, mask),
        reason=_np_subset(trace.reason, mask),
        value=_np_subset(trace.value, mask),
        gradient_norm=_np_subset(trace.gradient_norm, mask),
        valid=_np_subset(trace.valid, mask),
    )


def _device_block(fields: dict[str, np.ndarray]) -> dict[str, Array]:
    return {k: jnp.asarray(v) for k, v in fields.items()}


def _group_by_shape(
    host_blocks: Sequence[Mapping[str, np.ndarray]],
    lane_masks: Sequence[np.ndarray],
) -> list[list[tuple[int, np.ndarray]]]:
    """Group selected (block, lanes) picks by (capacity, feature width) so
    each compacted block mixes only shape-compatible lanes."""
    groups: dict[tuple[int, int], list[tuple[int, np.ndarray]]] = {}
    for i, mask in enumerate(lane_masks):
        lanes = np.flatnonzero(mask)
        if not len(lanes):
            continue
        f = host_blocks[i]["features"]
        groups.setdefault((f.shape[1], f.shape[2]), []).append((i, lanes))
    return list(groups.values())
