"""Converged-lane scheduling for vmapped random-effect solves.

Reference parity: photon-api algorithm/RandomEffectCoordinate.scala:104-153
— the reference's per-entity local solves are INDEPENDENT Spark tasks, so
each entity pays only its own iteration count and stragglers are scheduled
around by the task scheduler. The TPU port vmaps those solves, which makes
every lane advance in lock-step to the WORST lane: with the 1e-7 relative
tolerances that never fire in f32 for warm-started small solves, every lane
pays ``max_iter`` (the ~87% RE-solve share of the fused GAME sweep,
BASELINE.md r5 decomposition). This module restores the reference's
work-follows-convergence property without giving up the vmap:

1. **Probe** — run every bucket's vmapped solve for a short probe budget
   (``LaneSchedulerConfig.probe_iterations``) and read each lane's
   convergence reason from the existing ``LaneTrace`` scalars (tiny
   device-to-host reads).
2. **Rescue** — host-compact the lanes still at MAX_ITERATIONS across
   same-(capacity, feature-width) buckets (vectorized numpy,
   ``data.game_data.compact_lane_blocks``) into power-of-two-padded rescue
   blocks — bounded jit signatures, cached across sweeps — and re-run them
   with the remaining ``max_iterations - probe_iterations`` budget, warm-
   started from their probe rows; results scatter back into the [E, d]
   coefficient table inside the same jit.
3. **Cross-sweep active sets** (opt-in via the freeze tolerances) — entities
   whose per-sweep coefficient delta and final gradient norm fall below
   threshold are frozen: skipped by later sweeps' solves (still rescored by
   the coordinate's scoring path); the final sweep always runs everyone.

The scheduling literature motivates both moves: Snap ML (arxiv 1803.06333)
derives its hierarchy wins from matching work to the per-subproblem
convergence distribution, and distributed coordinate descent (arxiv
1611.02101) observes most coordinates converge within a handful of inner
iterations after the first outer pass.

Strictly opt-in: ``OptimizerConfig.scheduler=None`` keeps the unscheduled
single-jit path bitwise-identical (tests/test_lane_scheduler.py pins it).
Scheduled solves trade the one-jit sweep for a few extra dispatches and
small host reads per bucket — worth it exactly when the saved lane
iterations dwarf the ~100 ms tunnel dispatch (compare the same-run
``fused_game_sweep_scheduled_ms`` vs ``fused_game_sweep_ms`` bench rows,
never cross-run absolutes).

use_pallas MUST stay False in every objective this module receives — the
solves are vmapped, and a baked-in pallas_call would batch into a serial
per-lane loop (dev/lint_parity.py check 6 enforces this statically).
"""

from __future__ import annotations

import dataclasses
import logging
from functools import partial
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.coordinates import (
    _bucket_offsets,
    _mask_padding_lanes,
    _solve_bucket_entities,
)
from photon_ml_tpu.data.game_data import compact_lane_blocks
from photon_ml_tpu.optim.common import ConvergenceReason, LaneTrace
from photon_ml_tpu.optim.optimizer import LaneSchedulerConfig, OptimizerConfig
from photon_ml_tpu.projector.projectors import ProjectorType
from photon_ml_tpu.telemetry import tracing
from photon_ml_tpu.telemetry.program_ledger import ledger_jit

Array = jax.Array

logger = logging.getLogger(__name__)

#: entity_rows value for compacted padding lanes: out of range for any
#: coefficient table (the mesh-padding convention of shard_inputs), so
#: gathers clamp and scatters drop
SENTINEL_ROW = np.iinfo(np.int32).max

#: rescue blocks are padded to at least this many lanes, bounding the
#: number of distinct jit signatures at log2(E) per (cap, d) group
MIN_RESCUE_LANES = 8

#: registry namespace of the scheduler counters (reset per driver run next
#: to solver/*; journaled via the drivers' registry snapshot on success AND
#: failure paths)
SCHEDULER_METRIC_PREFIX = "scheduler/"


def _pow2_lanes(m: int) -> int:
    return 1 << (max(m, MIN_RESCUE_LANES) - 1).bit_length()


@dataclasses.dataclass
class SchedulerStats:
    """Per-sweep scheduling outcome of one coordinate's bucket set."""

    lanes_total: int = 0  # valid (non-padding) lanes across all buckets
    lanes_probed: int = 0  # lanes actually solved this sweep
    lanes_rescued: int = 0  # probed lanes re-run with the remaining budget
    lanes_frozen_skipped: int = 0  # lanes skipped by the active set
    lanes_newly_frozen: int = 0
    rescue_blocks: int = 0

    def merge(self, other: "SchedulerStats") -> None:
        for f in dataclasses.fields(self):
            setattr(self, f.name, getattr(self, f.name) + getattr(other, f.name))


# -- jitted block solvers ----------------------------------------------------
# One per projector, mirroring algorithm/coordinates.py's *_traced solvers
# with two tiny extra outputs per lane (coefficient delta and norm — the
# active-set freeze inputs). (objective, opt) are static; shapes key the jit
# cache, so power-of-two rescue padding bounds compilation.


@partial(ledger_jit, label="scheduler/solve_identity", static_argnums=(0, 1))
def _block_solve_identity(
    objective, opt: OptimizerConfig,
    features: Array, labels: Array, weights: Array,
    sample_rows: Array, entity_rows: Array,
    full_offsets: Array, table: Array,
):
    offsets = _bucket_offsets(sample_rows, full_offsets)
    w0s = table[entity_rows]  # OOB sentinel lanes clamp to the last row
    solved, trace = _solve_bucket_entities(
        objective, opt, features, labels, weights, offsets, w0s
    )
    trace = _mask_padding_lanes(trace, entity_rows, table.shape[0])
    delta = jnp.linalg.norm(solved - w0s, axis=-1)
    wnorm = jnp.linalg.norm(solved, axis=-1)
    return table.at[entity_rows].set(solved), trace, delta, wnorm


@partial(ledger_jit, label="scheduler/solve_indexmap", static_argnums=(0, 1))
def _block_solve_indexmap(
    objective, opt: OptimizerConfig,
    features: Array, labels: Array, weights: Array,
    sample_rows: Array, entity_rows: Array, col_index: Array,
    full_offsets: Array, table_ext: Array,
):
    offsets = _bucket_offsets(sample_rows, full_offsets)
    w0s = table_ext[entity_rows[:, None], col_index]
    solved, trace = _solve_bucket_entities(
        objective, opt, features, labels, weights, offsets, w0s
    )
    trace = _mask_padding_lanes(trace, entity_rows, table_ext.shape[0])
    delta = jnp.linalg.norm(solved - w0s, axis=-1)
    wnorm = jnp.linalg.norm(solved, axis=-1)
    table_ext = table_ext.at[entity_rows[:, None], col_index].set(solved)
    return table_ext.at[:, -1].set(0.0), trace, delta, wnorm


@partial(ledger_jit, label="scheduler/solve_random", static_argnums=(0, 1))
def _block_solve_random(
    objective, opt: OptimizerConfig,
    features: Array, labels: Array, weights: Array,
    sample_rows: Array, entity_rows: Array, matrix: Array,
    full_offsets: Array, table: Array,
):
    offsets = _bucket_offsets(sample_rows, full_offsets)
    w0s = table[entity_rows] @ matrix
    solved, trace = _solve_bucket_entities(
        objective, opt, features, labels, weights, offsets, w0s
    )
    trace = _mask_padding_lanes(trace, entity_rows, table.shape[0])
    delta = jnp.linalg.norm(solved - w0s, axis=-1)
    wnorm = jnp.linalg.norm(solved, axis=-1)
    return table.at[entity_rows].set(solved @ matrix.T), trace, delta, wnorm


@partial(ledger_jit, label="scheduler/extend_scratch")
def _extend_scratch(table: Array) -> Array:
    """[E, d] -> [E, d+1]: the INDEX_MAP scratch column that absorbs padding
    gather/scatter slots (algorithm/coordinates.py convention)."""
    return jnp.concatenate(
        [table, jnp.zeros((table.shape[0], 1), table.dtype)], axis=1
    )


@partial(ledger_jit, label="scheduler/strip_scratch")
def _strip_scratch(table_ext: Array) -> Array:
    return table_ext[:, :-1]


class LaneScheduler:
    """Per-coordinate probe/rescue state, persisted across sweeps.

    Holds the host copies of the bucket structure (read once — buckets are
    immutable across sweeps; only the table and offsets change), the frozen
    active-set mask, and the carried per-lane (value, gradient-norm) scalars
    that frozen lanes report to telemetry. Create one per random-effect
    coordinate and reuse it for every sweep; a fresh instance per call works
    but re-reads the bucket arrays to the host each time.

    ``mesh``: None (default) is the single-process host mode — compaction
    reads whole bucket arrays. Passing the training mesh switches to the
    COLLECTIVE-SAFE SPMD mode (the multi-process path): per-lane flags are
    read through a tiled ``process_allgather`` (a collective every rank
    makes), compaction is RANK-LOCAL over this rank's addressable bucket
    shards only, and the compacted stragglers assemble into one fixed
    ``[num_ranks * R]``-lane rescue block (R a power of two derived from
    the globally-agreed straggler maximum; ranks with fewer stragglers pad
    with sentinel lanes) — the same jit signature on every rank every
    sweep, so SPMD ranks stay in lock-step and ``train_distributed`` no
    longer falls back on multi-process runs.
    """

    def __init__(self, config: LaneSchedulerConfig, registry=None,
                 mesh=None, warn_no_live_stop: bool = True):
        #: set False when the probe IS the whole solve (the refresh policy:
        #: probe budget == max_iterations, no rescue) — the "probe flags
        #: rarely fire without a live function stop" warning only applies
        #: when a rescue phase exists to waste
        self.config = config
        self.mesh = mesh
        self._registry = registry
        self._warned_no_live_stop = not warn_no_live_stop
        self._host_blocks: list[dict[str, np.ndarray]] | None = None
        #: SPMD mode: (rank-local field slices, base row, owner map) per
        #: bucket — built lazily like the host cache
        self._spmd_blocks: list[dict] | None = None
        #: bool [table rows]; grows monotonically until the final sweep
        self.frozen_rows: np.ndarray | None = None
        #: per-block (value, gradient_norm) carried for lanes a later sweep
        #: skips (frozen lanes still appear in lane traces, with iterations 0)
        self._carry: list[tuple[np.ndarray, np.ndarray]] | None = None
        self.total_stats = SchedulerStats()
        self.last_stats: SchedulerStats | None = None
        self._num_rows: int | None = None

    # -- SPMD (collective-safe) helpers --------------------------------------

    def _gather_np(self, x):
        """Host copy of a per-lane device array — or a PYTREE of them,
        gathered in ONE collective (per-call dispatch is ~100 ms on this
        platform; never loop scalars through separate gathers). SPMD mode
        on a multi-process run allgathers (a COLLECTIVE — every rank
        calls it for every solve, by construction of the shared solve()
        flow); otherwise a plain device read."""
        import jax

        if self.mesh is not None and jax.process_count() > 1:
            from jax.experimental import multihost_utils

            x = multihost_utils.process_allgather(x, tiled=True)
        return jax.tree_util.tree_map(np.asarray, x)

    def _spmd_cache(self, blocks: Sequence[Mapping[str, Array]]):
        """Rank-local addressable slices + global owner maps, built once
        (buckets are immutable across sweeps)."""
        if self._spmd_blocks is None:
            self._spmd_blocks = [
                _rank_local_block(b) for b in blocks
            ]
        if len(self._spmd_blocks) != len(blocks):
            raise ValueError(
                "LaneScheduler is per-coordinate state: it was built over "
                f"{len(self._spmd_blocks)} buckets but is now asked to "
                f"schedule {len(blocks)} — create one scheduler per "
                "random-effect coordinate"
            )
        return self._spmd_blocks

    def registry(self):
        if self._registry is None:
            from photon_ml_tpu.telemetry.registry import default_registry

            self._registry = default_registry()
        return self._registry

    def freeze_rows(self, mask: np.ndarray) -> None:
        """Pre-seed the active set: coefficient-table rows True in ``mask``
        are FROZEN — a ``solve(final_sweep=False)`` skips their lanes
        (compacting the rest) and never scatters into their rows, so they
        carry over bitwise. This is the refresh-policy entry point
        (algorithm/refresh.py): "retrain only what changed" is the
        cross-sweep active set handed in from outside instead of grown
        from per-sweep convergence — the freeze tolerances need not be
        configured for a preset to take effect."""
        self.frozen_rows = np.ascontiguousarray(mask, dtype=bool).copy()

    def _host_cache(self, blocks: Sequence[Mapping[str, Array]]):
        if self._host_blocks is None:
            # one device-to-host read per field per bucket, amortized over
            # every later sweep (single-process only: a multi-process
            # sharded bucket is not addressable — callers gate on that)
            self._host_blocks = [
                {k: np.asarray(v) for k, v in b.items()} for b in blocks
            ]
        if len(self._host_blocks) != len(blocks):
            raise ValueError(
                "LaneScheduler is per-coordinate state: it was built over "
                f"{len(self._host_blocks)} buckets but is now asked to "
                f"schedule {len(blocks)} — create one scheduler per "
                "random-effect coordinate"
            )
        return self._host_blocks

    # -- the scheduled solve -------------------------------------------------

    def solve(
        self,
        objective,
        opt: OptimizerConfig,
        blocks: Sequence[Mapping[str, Array]],
        full_offsets: Array,
        table: Array,
        *,
        projector: ProjectorType = ProjectorType.IDENTITY,
        matrix: Array | None = None,
        final_sweep: bool = True,
    ) -> tuple[Array, list[LaneTrace], SchedulerStats]:
        """Probe + rescue (+ active-set skip) over one coordinate's buckets.

        blocks: bucket field dicts (features/labels/weights/sample_rows/
            entity_rows[/col_index]) — the shapes the unscheduled solvers
            consume. ``table`` is the RAW [E, d] coefficient table for every
            projector (the INDEX_MAP scratch column is handled internally).
        Returns (updated table, per-bucket numpy LaneTraces, stats). A
        frozen (skipped) lane reports iterations=0 with its carried value/
        gradient norm and reason FUNCTION_VALUES_WITHIN_TOLERANCE — the
        freeze criterion is a function-decrease statement.
        """
        cfg = self.config
        stats = SchedulerStats()
        if not blocks:
            self.last_stats = stats
            return table, [], stats
        from photon_ml_tpu.optim.optimizer import OptimizerType

        if (
            opt.rel_function_tolerance is None
            and opt.optimizer_type in (OptimizerType.LBFGS, OptimizerType.OWLQN)
            and not self._warned_no_live_stop
        ):
            # without a live function-decrease stop, warm-started LBFGS/OWLQN
            # lanes rarely flag converged after the probe (the CLAUDE.md
            # tolerance landmine): every lane gets rescued every sweep and
            # the scheduler only ADDS dispatch/compaction cost
            self._warned_no_live_stop = True
            logger.warning(
                "lane scheduler active with optimizer_type=%s but no "
                "rel_function_tolerance: probe convergence flags rarely fire "
                "at the plain tolerance for warm starts, so most lanes will "
                "be rescued anyway — set rel_function_tolerance (e.g. 1e-6) "
                "to get the probe/rescue win",
                opt.optimizer_type.name,
            )

        indexmap = projector == ProjectorType.INDEX_MAP
        if indexmap:
            table = _extend_scratch(table)
        num_rows = int(table.shape[0])
        # per-coordinate contract, checked even on no-compaction sweeps:
        # frozen_rows/_carry sized for another coordinate's table would
        # silently skip the wrong entities instead of raising
        if self._num_rows is None:
            self._num_rows = num_rows
        elif self._num_rows != num_rows:
            raise ValueError(
                "LaneScheduler is per-coordinate state: it was built over a "
                f"{self._num_rows}-row coefficient table but is now asked to "
                f"schedule a {num_rows}-row one — create one scheduler per "
                "random-effect coordinate"
            )

        probe_iters = max(1, min(cfg.probe_iterations, opt.max_iterations))
        rescue_budget = opt.max_iterations - probe_iters
        base_opt = dataclasses.replace(opt, scheduler=None)
        probe_opt = dataclasses.replace(base_opt, max_iterations=probe_iters)
        rescue_opt = (
            dataclasses.replace(base_opt, max_iterations=rescue_budget)
            if rescue_budget > 0 else None
        )

        def run_block(b: Mapping[str, Array], o: OptimizerConfig, tab: Array):
            if indexmap:
                return _block_solve_indexmap(
                    objective, o, b["features"], b["labels"], b["weights"],
                    b["sample_rows"], b["entity_rows"], b["col_index"],
                    full_offsets, tab,
                )
            if projector == ProjectorType.RANDOM:
                return _block_solve_random(
                    objective, o, b["features"], b["labels"], b["weights"],
                    b["sample_rows"], b["entity_rows"], matrix,
                    full_offsets, tab,
                )
            return _block_solve_identity(
                objective, o, b["features"], b["labels"], b["weights"],
                b["sample_rows"], b["entity_rows"], full_offsets, tab,
            )

        freezing = cfg.freezes
        frozen = self.frozen_rows
        if freezing and frozen is None:
            frozen = np.zeros(num_rows, dtype=bool)
        # a preset active set (freeze_rows — the refresh policy) skips even
        # when the per-sweep freeze tolerances are off; only the tolerance-
        # driven active-set GROWTH below stays gated on cfg.freezes
        skipping = freezing or frozen is not None
        if frozen is not None and len(frozen) != num_rows:
            raise ValueError(
                f"frozen-row mask covers {len(frozen)} rows but the "
                f"coefficient table has {num_rows} — freeze_rows() masks "
                "must match the coordinate's table"
            )

        # host lane bookkeeping (entity_rows only — cheap; the full host
        # bucket cache is built lazily, first time compaction is needed).
        # SPMD mode allgathers, so every rank sees the same global arrays.
        rows_h = [
            r.astype(np.int64)
            for r in self._gather_np(tuple(b["entity_rows"] for b in blocks))
        ]
        valid_h = [(r >= 0) & (r < num_rows) for r in rows_h]
        if skipping and not final_sweep and frozen.any():
            skip_h = [
                v & frozen[np.clip(r, 0, num_rows - 1)]
                for r, v in zip(rows_h, valid_h)
            ]
        else:
            skip_h = [np.zeros(len(r), dtype=bool) for r in rows_h]
        solve_h = [v & ~s for v, s in zip(valid_h, skip_h)]
        stats.lanes_total = int(sum(v.sum() for v in valid_h))
        stats.lanes_frozen_skipped = int(sum(s.sum() for s in skip_h))

        # per-block output arrays; frozen lanes keep carried scalars
        e_sizes = [len(r) for r in rows_h]
        iters_out = [np.zeros(e, np.int64) for e in e_sizes]
        reason_out = [
            np.full(e, int(ConvergenceReason.FUNCTION_VALUES_WITHIN_TOLERANCE),
                    np.int64)
            for e in e_sizes
        ]
        value_out = [np.zeros(e, np.float64) for e in e_sizes]
        gnorm_out = [np.zeros(e, np.float64) for e in e_sizes]
        delta_out = [np.zeros(e, np.float64) for e in e_sizes]
        wnorm_out = [np.zeros(e, np.float64) for e in e_sizes]
        if self._carry is not None:
            for i, (cv, cg) in enumerate(self._carry):
                value_out[i][:] = cv
                gnorm_out[i][:] = cg

        def scatter_back(trace, delta, wnorm, blk, lane):
            """Write one solved block's per-lane scalars back into the
            per-original-bucket output arrays; (blk, lane) name the source
            of each REAL lane (padding lanes carry blk == -1 and never
            land anywhere). Iterations and deltas ADD (probe + rescue
            accumulate); the rest overwrite. SPMD mode reads the trace
            through the allgather — a collective every rank makes."""
            it, rs, vl, gn, dl, wn = self._gather_np(
                (trace.iterations, trace.reason, trace.value,
                 trace.gradient_norm, delta, wnorm)
            )
            for i in range(len(blocks)):
                mask = blk == i
                if not mask.any():
                    continue
                li = lane[mask]
                iters_out[i][li] += it[mask]
                reason_out[i][li] = rs[mask]
                value_out[i][li] = vl[mask]
                gnorm_out[i][li] = gn[mask]
                delta_out[i][li] += dl[mask]
                wnorm_out[i][li] = wn[mask]

        def run_compacted(lane_masks, o: OptimizerConfig, tab):
            """Solve only the masked lanes, grouped by (cap, d): host-mode
            compaction over whole bucket arrays, or rank-local SPMD
            compaction into fixed [num_ranks * R] blocks (the collective-
            safe path). Returns (table, lanes solved, blocks run)."""
            solved = 0
            n_blocks = 0
            if self.mesh is not None:
                local = self._spmd_cache(blocks)
                # _group_by_shape only reads shapes — fine on device blocks
                for picks in _group_by_shape(blocks, lane_masks):
                    tab, n = self._run_spmd_block(
                        picks, local, o, run_block, tab, scatter_back
                    )
                    solved += n
                    n_blocks += 1
                return tab, solved, n_blocks
            host = self._host_cache(blocks)
            for picks in _group_by_shape(host, lane_masks):
                pad_to = _pow2_lanes(sum(len(l) for _, l in picks))
                with tracing.span("scheduler/compaction", cat="scheduler",
                                  lanes=int(sum(len(l) for _, l in picks))):
                    fields, src_blk, src_lane = compact_lane_blocks(
                        host, picks, pad_to=pad_to, sentinel_row=SENTINEL_ROW,
                    )
                tab, trace, delta, wnorm = run_block(
                    _device_block(fields), o, tab
                )
                scatter_back(trace, delta, wnorm,
                             _pad_minus1(src_blk, pad_to),
                             _pad_zeros(src_lane, pad_to))
                solved += len(src_lane)
                n_blocks += 1
            return tab, solved, n_blocks

        # -- probe phase ----------------------------------------------------
        any_skip = any(s.any() for s in skip_h)
        with tracing.span("scheduler/probe", cat="scheduler",
                          lanes=stats.lanes_total,
                          frozen_skipped=stats.lanes_frozen_skipped):
            if not any_skip:
                # full buckets, original shapes — the same signatures the
                # unscheduled path compiles
                for i, b in enumerate(blocks):
                    table, trace, delta, wnorm = run_block(b, probe_opt, table)
                    blk = np.where(solve_h[i], i, -1).astype(np.int32)
                    lane = np.arange(e_sizes[i], dtype=np.int64)
                    scatter_back(trace, delta, wnorm, blk, lane)
                stats.lanes_probed = int(sum(s.sum() for s in solve_h))
            else:
                # active-set compaction: only unfrozen lanes probe
                table, probed, _ = run_compacted(solve_h, probe_opt, table)
                stats.lanes_probed = probed

        # -- rescue phase ---------------------------------------------------
        rescue_h = [
            s & (r_out == int(ConvergenceReason.MAX_ITERATIONS))
            for s, r_out in zip(solve_h, reason_out)
        ]
        n_rescue = int(sum(r.sum() for r in rescue_h))
        if rescue_opt is not None and n_rescue:
            with tracing.span("scheduler/rescue", cat="scheduler",
                              lanes=n_rescue):
                table, _, rescue_blocks = run_compacted(
                    rescue_h, rescue_opt, table
                )
            stats.rescue_blocks += rescue_blocks
            stats.lanes_rescued = n_rescue

        # -- active-set update ----------------------------------------------
        if freezing and not final_sweep:
            ftol = cfg.freeze_coefficient_tolerance
            gtol = cfg.freeze_gradient_tolerance
            for i in range(len(blocks)):
                sel = solve_h[i]
                quiet = (
                    sel
                    & (delta_out[i] <= ftol * (1.0 + wnorm_out[i]))
                    & (gnorm_out[i] <= gtol)
                )
                if quiet.any():
                    frozen[rows_h[i][quiet]] = True
                    stats.lanes_newly_frozen += int(quiet.sum())
            self.frozen_rows = frozen
        if final_sweep:
            # the active set does not outlive its training run
            self.frozen_rows = None

        self._carry = [
            (value_out[i].copy(), gnorm_out[i].copy())
            for i in range(len(blocks))
        ]

        traces = [
            LaneTrace(
                iterations=iters_out[i],
                reason=reason_out[i],
                value=value_out[i],
                gradient_norm=gnorm_out[i],
                valid=valid_h[i],
                # provenance: these lanes are observed into the
                # solver/lane_iters histogram below — telemetry consumers
                # (SolverTelemetry.record_lanes) must not count them again
                scheduled=True,
            )
            for i in range(len(blocks))
        ]
        self._record(stats, traces)
        self.last_stats = stats
        self.total_stats.merge(stats)
        if indexmap:
            table = _strip_scratch(table)
        return table, traces, stats

    def _run_spmd_block(self, picks, local, opt: OptimizerConfig,
                        run_block, table, scatter_back):
        """One same-(cap, d) group's compacted solve, collective-safe.

        Every rank computes the identical global layout (per-rank straggler
        assignment from the owner maps, R from the global per-rank maximum),
        builds ONLY its own rank's [R]-lane block from its addressable
        shard rows (sentinel-padding the spare lanes), and assembles the
        global [num_ranks * R] block via ``assemble_partitioned`` — so the
        solve jit (a collective SPMD program) sees the same signature on
        every rank, every sweep. Returns (table, lanes solved).
        """
        import jax
        from jax.sharding import PartitionSpec as P

        from photon_ml_tpu.parallel.multihost import assemble_partitioned

        num_ranks = jax.process_count()
        my_rank = jax.process_index()
        data_axis = int(self.mesh.shape["data"])
        if data_axis % num_ranks:
            raise ValueError(
                f"SPMD lane scheduling: mesh data axis {data_axis} must be "
                f"a multiple of the process count {num_ranks}"
            )
        dpr = data_axis // num_ranks  # devices per rank along "data"

        per_rank: list[list[tuple[int, np.ndarray]]] = [
            [] for _ in range(num_ranks)
        ]
        for b, lanes in picks:
            owner = local[b]["owner"]
            for r in range(num_ranks):
                sel = lanes[owner[lanes] == r]
                if len(sel):
                    per_rank[r].append((b, sel))
        max_count = max(
            sum(len(l) for _, l in pr) for pr in per_rank
        )
        rescue_lanes = _pow2_lanes(max(max_count, dpr))
        # round up to a multiple of the per-rank device count so the fixed
        # [num_ranks * rescue_lanes] block shards evenly over "data" on a
        # non-power-of-two dpr too (one value per pow2 tier, so the jit
        # signature set stays bounded; spare lanes are sentinel-padded)
        rescue_lanes = -(-rescue_lanes // dpr) * dpr

        # the global (block, lane) source map — identical on every rank
        src_blk = np.full(num_ranks * rescue_lanes, -1, np.int32)
        src_lane = np.zeros(num_ranks * rescue_lanes, np.int64)
        for r in range(num_ranks):
            j = r * rescue_lanes
            for b, lanes in per_rank[r]:
                src_blk[j: j + len(lanes)] = b
                src_lane[j: j + len(lanes)] = lanes
                j += len(lanes)

        # THIS rank's block only, from its addressable shard rows
        loc_picks = [
            (b, lanes - local[b]["base"]) for b, lanes in per_rank[my_rank]
        ]
        for (b, lanes), (_, loc) in zip(per_rank[my_rank], loc_picks):
            if len(loc) and (loc.min() < 0 or loc.max() >= local[b]["size"]):
                raise ValueError(
                    f"bucket {b}: owned lanes fall outside this rank's "
                    "addressable shard — the mesh 'data' axis must be "
                    "process-contiguous"
                )
        if loc_picks:
            fields, _, _ = compact_lane_blocks(
                [l["fields"] for l in local], loc_picks,
                pad_to=rescue_lanes, sentinel_row=SENTINEL_ROW,
            )
        else:
            fields = _sentinel_block(
                local[picks[0][0]]["fields"], rescue_lanes
            )

        specs = {
            "features": P("data", None, None),
            "labels": P("data", None),
            "weights": P("data", None),
            "sample_rows": P("data", None),
            "entity_rows": P("data"),
            "col_index": P("data", None),
        }
        assembled = {
            k: assemble_partitioned(
                {my_rank: v}, self.mesh, specs[k], num_ranks
            )
            for k, v in fields.items()
        }
        table, trace, delta, wnorm = run_block(assembled, opt, table)
        scatter_back(trace, delta, wnorm, src_blk, src_lane)
        return table, int((src_blk >= 0).sum())

    def _record(self, stats: SchedulerStats, traces: Sequence[LaneTrace]):
        """Feed the scheduler counters and the solver/lane_iters histogram
        (telemetry/registry.py conventions; journaled by the drivers'
        registry snapshot on success and failure paths)."""
        reg = self.registry()
        p = SCHEDULER_METRIC_PREFIX
        reg.counter(p + "sweeps").inc()
        reg.counter(p + "lanes_probed").inc(stats.lanes_probed)
        reg.counter(p + "lanes_rescued").inc(stats.lanes_rescued)
        reg.counter(p + "lanes_frozen_skipped").inc(stats.lanes_frozen_skipped)
        reg.counter(p + "rescue_blocks").inc(stats.rescue_blocks)
        if self.frozen_rows is not None:
            reg.gauge(p + "frozen_rows").set(int(self.frozen_rows.sum()))
        # the canonical per-lane iteration histogram (record_lanes skips
        # scheduler-produced traces, so lanes land here exactly once)
        from photon_ml_tpu.telemetry.solver_trace import LANE_ITERS_METRIC

        hist = reg.histogram(LANE_ITERS_METRIC)
        for t in traces:
            hist.observe_many(
                np.asarray(t.iterations)[np.asarray(t.valid)].tolist()
            )


def make_schedulers(re_specs, mesh=None, registry=None) -> dict:
    """One LaneScheduler per RE spec whose OptimizerConfig carries a
    scheduler config — the ONE mode-selection rule shared by
    ``train_distributed`` and ``train_partitioned``: collective-safe SPMD
    mode on multi-process runs (requires the training mesh), single-process
    host mode otherwise (bit-for-bit the pre-SPMD behavior)."""
    import jax

    spmd_mesh = mesh if jax.process_count() > 1 else None
    return {
        s.re_type: LaneScheduler(
            s.optimizer.scheduler, registry=registry, mesh=spmd_mesh
        )
        for s in re_specs
        if s.optimizer.scheduler is not None
    }


def _pad_minus1(arr: np.ndarray, length: int) -> np.ndarray:
    out = np.full(length, -1, np.int32)
    out[: len(arr)] = arr
    return out


def _pad_zeros(arr: np.ndarray, length: int) -> np.ndarray:
    out = np.zeros(length, np.int64)
    out[: len(arr)] = arr
    return out


def _sentinel_block(sample_fields: Mapping[str, np.ndarray],
                    lanes: int) -> dict[str, np.ndarray]:
    """An all-padding [lanes] block shaped like ``sample_fields`` — what a
    rank with zero stragglers contributes (weight 0 / sample_rows -1 /
    entity_rows sentinel: inert in the solve, dropped by the scatter)."""
    out = {}
    for k, arr in sample_fields.items():
        if k == "entity_rows":
            out[k] = np.full(lanes, SENTINEL_ROW, np.int32)
        elif k == "sample_rows":
            out[k] = np.full((lanes,) + arr.shape[1:], -1, arr.dtype)
        else:
            out[k] = np.zeros((lanes,) + arr.shape[1:], arr.dtype)
    return out


def _addressable_rows(arr) -> tuple[int, int, np.ndarray]:
    """(base, stop, rows) — the contiguous lane-axis slice of ``arr`` this
    process can read. Model-axis replicas (same row range on several local
    devices) dedup; a non-contiguous addressable range is rejected (SPMD
    lane scheduling requires the standard process-contiguous 'data'
    layout, the same contract as multihost.assemble_partitioned)."""
    arr = jnp.asarray(arr)
    pieces: dict[tuple[int, int], object] = {}
    for s in arr.addressable_shards:
        sl = s.index[0] if s.index else slice(None)
        start = 0 if sl.start is None else int(sl.start)
        stop = int(arr.shape[0]) if sl.stop is None else int(sl.stop)
        pieces.setdefault((start, stop), s)
    spans = sorted(pieces)
    expect = spans[0][0]
    datas = []
    for start, stop in spans:
        if start != expect:
            raise ValueError(
                "addressable shards are not contiguous along the lane "
                "axis; SPMD lane scheduling needs a process-contiguous "
                "'data' axis"
            )
        expect = stop
        datas.append(np.asarray(pieces[(start, stop)].data))
    return spans[0][0], expect, np.concatenate(datas, axis=0)


def _owner_map(arr) -> np.ndarray:
    """[lanes] int32: the lowest process index holding each lane — the
    rank that compacts it. Identical on every rank (computed from the
    GLOBAL device->index map, not from addressable state)."""
    arr = jnp.asarray(arr)
    owner = np.full(int(arr.shape[0]), np.iinfo(np.int32).max, np.int32)
    for dev, idx in arr.sharding.devices_indices_map(arr.shape).items():
        sl = idx[0] if idx else slice(None)
        start = 0 if sl.start is None else int(sl.start)
        stop = int(arr.shape[0]) if sl.stop is None else int(sl.stop)
        p = np.int32(getattr(dev, "process_index", 0))
        owner[start:stop] = np.minimum(owner[start:stop], p)
    return owner


def _rank_local_block(b: Mapping[str, Array]) -> dict:
    """SPMD cache entry for one bucket: this rank's addressable field
    slices (one device-to-host read each, amortized across sweeps), their
    common base row, and the global lane->owner-rank map."""
    fields = {}
    base = size = None
    for k, v in b.items():
        lo, hi, rows = _addressable_rows(v)
        if base is None:
            base, size = lo, hi - lo
        elif (lo, hi - lo) != (base, size):
            raise ValueError(
                f"bucket field '{k}' spans rows [{lo}, {hi}) but other "
                f"fields span [{base}, {base + size}) — bucket fields "
                "must share one lane-axis sharding"
            )
        fields[k] = rows
    return {
        "fields": fields,
        "base": int(base),
        "size": int(size),
        "owner": _owner_map(b["entity_rows"]),
    }


def _device_block(fields: dict[str, np.ndarray]) -> dict[str, Array]:
    return {k: jnp.asarray(v) for k, v in fields.items()}


def _group_by_shape(
    host_blocks: Sequence[Mapping[str, np.ndarray]],
    lane_masks: Sequence[np.ndarray],
) -> list[list[tuple[int, np.ndarray]]]:
    """Group selected (block, lanes) picks by (capacity, feature width) so
    each compacted block mixes only shape-compatible lanes."""
    groups: dict[tuple[int, int], list[tuple[int, np.ndarray]]] = {}
    for i, mask in enumerate(lane_masks):
        lanes = np.flatnonzero(mask)
        if not len(lanes):
            continue
        f = host_blocks[i]["features"]
        groups.setdefault((f.shape[1], f.shape[2]), []).append((i, lanes))
    return list(groups.values())
