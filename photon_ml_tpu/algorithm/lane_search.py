"""Vmapped config-tournament lanes: one solve program, many hyperparameter
configurations.

Reference parity: the reference evaluates hyperparameter candidates as a
sequential outer loop of full driver fits (photon-client hyperparameter/
HyperparameterTuning.scala-style glue around RandomSearch.scala:33-50);
there is no reference analogue of the vmapped tournament itself — it
generalizes this repo's λ-grid machinery (estimators._jitted_grid_solve,
lane-varying L2 only) to full per-lane config VECTORS: (l2, l1, solver
tolerance, optional per-lane box bounds) as traced per-lane arrays through
one vmapped LBFGS/OWLQN solve. Branch structure stays static and shared
across lanes (`use_owlqn` / `use_box` resolve once per tournament — Snap ML
arXiv:1803.06333's "keep the accelerator saturated by batching many small
solves into one resident program").

Invariants:
- A uniform-config tournament (λ lanes only: per-lane l2/l1 from one
  elastic-net α, uniform tolerance == the optimizer's, no box, cold zero
  warm starts) is BITWISE identical to `estimators.train_glm_grid`
  (tests/test_lane_search.py pins it) — tolerance and w0 become traced
  per-lane arguments but feed only exact IEEE comparisons/multiplies, and
  a runtime zero vector margins identically to the inlined constant.
- Per-lane boxes ride the projected-gradient L-BFGS path; a tournament
  with NO box lane passes bounds=None so the unprojected convergence test
  (‖g‖, not ‖P(w-g)-w‖) is preserved exactly — ±inf bounds arrays are NOT
  bitwise-equivalent to bounds=None and must never be the no-box encoding
  at the tournament level.
- Tournament evaluation stays on device: per-lane validation margins +
  the exact sharded metric (evaluation/sharded.py) reduce on-mesh and only
  the [L] metric scalars cross to the host.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.batch import LabeledPointBatch, compute_margins
from photon_ml_tpu.data.sparse_batch import SparseLabeledPointBatch
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.optim.optimizer import (
    OptimizerConfig,
    OptimizerType,
    resolve_auto_optimizer,
)
from photon_ml_tpu.telemetry.program_ledger import ledger_jit
from photon_ml_tpu.types import TaskType

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class LaneConfigs:
    """Per-lane hyperparameter vectors for one tournament round.

    l2 / l1 / tolerance: [L] float arrays (one lane per configuration).
    lower_bounds / upper_bounds: optional [L, d] per-lane box; lanes without
    a box carry ±inf rows. Leave BOTH None when no lane uses a box — that
    selects the exact unprojected L-BFGS path (see module invariants).
    """

    l2: np.ndarray
    l1: np.ndarray
    tolerance: np.ndarray
    lower_bounds: np.ndarray | None = None
    upper_bounds: np.ndarray | None = None

    def __post_init__(self):
        l2 = np.asarray(self.l2, np.float64)
        l1 = np.asarray(self.l1, np.float64)
        tol = np.asarray(self.tolerance, np.float64)
        if not (l2.shape == l1.shape == tol.shape and l2.ndim == 1):
            raise ValueError(
                "LaneConfigs needs matching [L] vectors, got "
                f"l2{l2.shape} l1{l1.shape} tolerance{tol.shape}"
            )
        if (self.lower_bounds is None) != (self.upper_bounds is None):
            raise ValueError(
                "per-lane boxes need BOTH lower_bounds and upper_bounds "
                "([L, d]; ±inf rows for box-off lanes)"
            )

    @property
    def num_lanes(self) -> int:
        return int(np.asarray(self.l2).shape[0])

    @property
    def has_box(self) -> bool:
        return self.lower_bounds is not None

    def needs_owlqn(self) -> bool:
        return bool(np.any(np.asarray(self.l1) > 0.0))


@dataclasses.dataclass
class TournamentResult:
    """One vmapped tournament: per-lane solver results + model-space models."""

    #: vmapped SolverResult — every leaf has a leading [L] lane axis
    results: object
    #: model-space GLMs, lane order
    models: list[GeneralizedLinearModel]
    #: the configs that trained them (for trajectory bookkeeping)
    configs: LaneConfigs


def run_lane_tournament(
    batch: LabeledPointBatch,
    task: TaskType,
    configs: LaneConfigs,
    *,
    optimizer: OptimizerConfig | None = None,
    warm_start: Array | np.ndarray | None = None,
    normalization=None,
    intercept_index: int | None = None,
    telemetry=None,
) -> TournamentResult:
    """Train every lane of ``configs`` in ONE vmapped solve.

    ``warm_start``: optional [L, d] per-lane initial coefficients in
    NORMALIZED (solver) space — the search driver supplies
    nearest-evaluated-config starts; None = cold zeros, which is the
    train_glm_grid-identical path. ``optimizer``: AUTO resolves ONCE here
    for the whole tournament (never per lane); only LBFGS/OWLQN vmap.
    """
    optimizer = resolve_auto_optimizer(optimizer or OptimizerConfig())
    if optimizer.optimizer_type not in (OptimizerType.LBFGS, OptimizerType.OWLQN):
        raise ValueError(
            "lane tournaments support LBFGS/OWLQN lanes; got "
            f"{optimizer.optimizer_type.name}"
        )
    use_owlqn = (
        configs.needs_owlqn()
        or optimizer.optimizer_type == OptimizerType.OWLQN
    )
    if use_owlqn and configs.has_box:
        raise ValueError(
            "box constraints cannot combine with OWL-QN / L1 lanes"
        )
    loss = loss_for_task(task)
    # deferred: estimators imports algorithm/* at module load
    from photon_ml_tpu.estimators import _objective_for_batch

    objective = _objective_for_batch(batch, loss, 0.0, normalization)
    dtype = batch.solve_dtype
    num_lanes = configs.num_lanes
    l2v = jnp.asarray(np.asarray(configs.l2), dtype)
    l1v = jnp.asarray(np.asarray(configs.l1), dtype)
    tolv = jnp.asarray(np.asarray(configs.tolerance), dtype)
    if warm_start is None:
        w0v = jnp.zeros((num_lanes, batch.dim), dtype)
    else:
        w0v = jnp.asarray(warm_start, dtype)
        if w0v.shape != (num_lanes, batch.dim):
            raise ValueError(
                f"warm_start must be [{num_lanes}, {batch.dim}], "
                f"got {w0v.shape}"
            )
    bounds = None
    if configs.has_box:
        bounds = (
            jnp.asarray(configs.lower_bounds, dtype),
            jnp.asarray(configs.upper_bounds, dtype),
        )
    results = _jitted_lane_solve(
        objective, use_owlqn, optimizer.history, optimizer.max_iterations,
        optimizer.rel_function_tolerance, batch, l2v, l1v, tolv, w0v,
        bounds,
    )
    if telemetry is not None:
        telemetry.record_lanes(
            "lane-search", results,
            keys=[
                {"l2": float(np.asarray(configs.l2)[i]),
                 "l1": float(np.asarray(configs.l1)[i])}
                for i in range(num_lanes)
            ],
        )
    norm = objective.normalization
    models = []
    for i in range(num_lanes):
        means = norm.to_model_space(results.coefficients[i], intercept_index)
        models.append(
            GeneralizedLinearModel(Coefficients(means=means), task)
        )
    return TournamentResult(results=results, models=models, configs=configs)


@functools.partial(ledger_jit, label="search/lane_solve",
                   static_argnums=(0, 1, 2, 3, 4))
def _jitted_lane_solve(objective, use_owlqn, history, max_iter,
                       rel_function_tolerance, batch, l2v, l1v, tolv, w0v,
                       bounds=None):
    """Module-level jit: one compiled tournament program per
    (objective, optimizer statics) pair; the batch and every per-lane
    config vector enter as ARGUMENTS (the 413 landmine — lint check 9).
    Mirrors estimators._jitted_grid_solve with per-lane tolerance, warm
    starts and (optionally) per-lane [L, d] boxes vmapped in; the
    objective stays use_pallas=False because these lanes are vmapped."""
    from photon_ml_tpu.optim.lbfgs import minimize_lbfgs
    from photon_ml_tpu.optim.owlqn import minimize_owlqn

    bound = objective.bind(batch)

    def solve_one(l2, l1, tol, w0, *lane_bounds):
        def vg(w):
            v, g = bound.value_and_grad(w)
            return v + 0.5 * l2 * jnp.vdot(w, w), g + l2 * w

        if use_owlqn:
            return minimize_owlqn(
                vg, w0, l1_weight=l1,
                max_iter=max_iter, tolerance=tol, history=history,
                rel_function_tolerance=rel_function_tolerance,
            )
        lo, hi = lane_bounds if lane_bounds else (None, None)
        return minimize_lbfgs(
            vg, w0, max_iter=max_iter, tolerance=tol, history=history,
            rel_function_tolerance=rel_function_tolerance,
            lower_bounds=lo, upper_bounds=hi,
        )

    if bounds is None:
        return jax.vmap(solve_one)(l2v, l1v, tolv, w0v)
    return jax.vmap(solve_one)(l2v, l1v, tolv, w0v, bounds[0], bounds[1])


@functools.partial(ledger_jit, label="search/lane_metrics",
                   static_argnums=(0, 1, 2))
def _jitted_lane_metrics(objective, metric_fn, intercept_index, batch,
                         coefficients, consts):
    """Per-lane validation metrics WITHOUT a host score round-trip: map each
    lane's solver-space coefficients to model space, margin against the
    validation batch, reduce with the exact device metric
    (evaluation/sharded.py) — only the [L] scalars leave the mesh."""
    norm = objective.normalization

    def one(w):
        wm = norm.to_model_space(w, intercept_index)
        scores = compute_margins(batch, wm)
        return metric_fn(scores, consts)

    return jax.vmap(one)(coefficients)


def evaluate_tournament_on_device(
    objective,
    metric_fn,
    val_batch: LabeledPointBatch,
    coefficients: Array,
    consts: dict,
    intercept_index: int | None = None,
) -> Array:
    """[L] on-device metric values for a tournament's coefficient stack
    (solver space). ``metric_fn``/``consts`` come from a prepared
    evaluation.sharded.DeviceEvaluator (callers keep its ``better_than``).
    Returns the DEVICE array — dispatch is async, so callers overlap host
    work (the GP fit) before reading it; ``np.asarray`` is the sync point.
    """
    if isinstance(val_batch, SparseLabeledPointBatch):
        raise TypeError(
            "tournament evaluation needs a dense validation batch "
            "(per-lane margins are one [n, d] @ [d] per lane)"
        )
    return _jitted_lane_metrics(
        objective, metric_fn, intercept_index, val_batch, coefficients,
        consts,
    )
